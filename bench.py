"""Headline benchmark: ResNet-50 training throughput + MFU, plus the four
other BASELINE.md configs.

Mirrors the reference's kubebench + tf_cnn_benchmarks ResNet-50 headline
workload (BASELINE.md config 2; reference harness
``/root/reference/kubeflow/kubebench/kubebench-job.libsonnet:250-396``).
Prints ONE JSON line: the headline metric stays
``resnet50_train_images_per_sec_per_chip`` with ``vs_baseline`` against the
reference era's GPU path (tf_cnn_benchmarks ResNet-50 on one V100, fp32,
batch 64, ~2019 ≈ 360 images/sec — the north-star per-chip target), and the
``extras`` key carries MFU plus the MNIST-smoke, BERT step-time, allreduce,
and serving-latency configs (BASELINE.md configs 1, 3, 4, 5) so every
baseline config emits numbers each round — plus the three TPU-first configs
the reference has no counterpart for: ``longcontext`` (seq-8192 flash
training), ``decode`` (KV-cache generation), and ``decode_engine``
(continuous-batching serving throughput at effective batch 32).

Rows that run a tuned Pallas kernel (longcontext, bert, and the
decode_engine paged-kernel A/B) carry ``tile_config`` — the resolved
tile blocks plus their resolution source (``table|fallback|override``,
kubeflow_tpu/ops/autotune.py) — so an A/B across rounds can attribute
a throughput move to a tile-table change (PERF.md "Tile autotune").
"""

from __future__ import annotations

import json
import sys

REFERENCE_GPU_IMAGES_PER_SEC = 360.0


def main() -> None:
    import argparse
    import os

    from kubeflow_tpu.bench.suite import run_all_isolated, run_cpu_smoke

    p = argparse.ArgumentParser()
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture XLA profiler traces into DIR")
    args = p.parse_args()

    # each config in its own subprocess under a hard timeout: a wedged
    # device transport must never stop the one-JSON-line contract
    results = run_all_isolated(profile_dir=args.profile)
    headline = results.get("resnet50", {})
    value = float(headline.get("images_per_sec_per_chip", 0.0))
    # artifact hygiene: r03/r04 skipped every suite with "device
    # transport unreachable" and the artifacts read as a flat perf
    # trajectory. Stamp WHAT actually ran at the top level, and (below)
    # exit nonzero — with the artifact already emitted — on transport
    # failure, so a skipped round is unmistakably a failed round.
    def _err_kind(r):
        # the structured classification run_all_isolated stamps; the
        # substring fallback only covers results from an older suite —
        # never reword-couple new code to the free-text message
        kind = r.get("error_kind", "")
        if kind:
            return kind
        e = r.get("error", "")
        if "device transport unreachable" in e:
            return "transport_unreachable"
        if "transport wedged" in e or "transport hung" in e:
            return "transport_wedged"
        return "error" if "error" in r else ""

    kinds = [_err_kind(r) for r in results.values()]
    if kinds and all(k == "transport_unreachable" for k in kinds):
        transport = "unreachable"
    elif any(k in ("transport_wedged", "transport_timeout")
             for k in kinds):
        transport = "wedged"
    else:
        transport = "ok"
    platforms = {r.get("platform") for r in results.values()
                 if "error" not in r and r.get("platform")}
    accel = sorted(platforms - {"cpu"})
    line = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(value / REFERENCE_GPU_IMAGES_PER_SEC, 3),
        "device_transport": transport,
        "tier": (accel[0] if accel
                 else "cpu" if platforms else "cpu-smoke"),
    }
    if "mfu" in headline:
        line["mfu"] = headline["mfu"]
        line["tflops_per_chip"] = headline["tflops_per_chip"]
    if "step_telemetry" in headline:
        # step-regularity evidence (p50/p99 step time, recompile count,
        # MFU from the instrumented pass) rides with the artifact so the
        # perf trajectory shows tails and recompiles, not just means
        # (kubeflow_tpu/obs/steps.py, docs/OBSERVABILITY.md)
        line["step_telemetry"] = headline["step_telemetry"]
    if "goodput" in headline:
        # productive-fraction next to img/s (the goodput ledger's bench
        # twin, docs/OBSERVABILITY.md "Goodput"): wall time the pass
        # spent stepping vs recompiling vs unattributed host gaps
        line["goodput"] = headline["goodput"]
    line["extras"] = results
    # the always-on CPU smoke tier (tier:"cpu" rows, tiny shapes): an
    # accelerator outage degrades the artifact to labeled correctness
    # evidence for every config instead of an empty all-skip record
    # (KFTPU_BENCH_CPU_SMOKE=0 disables)
    if os.environ.get("KFTPU_BENCH_CPU_SMOKE", "1") != "0":
        smoke = run_cpu_smoke()
        line["cpu_smoke"] = smoke
        smoke_ok = bool(smoke) and all(
            "error" not in r for r in smoke.values())
    else:
        smoke_ok = False
    if not platforms and not smoke_ok:
        line["tier"] = "none"
    if value <= 0 and smoke_ok:
        line["note"] = (
            "accelerator unreachable this run; cpu_smoke rows (tier: "
            "cpu, tiny shapes) prove every config executes end-to-end "
            "— they are correctness evidence, not performance numbers")
    print(json.dumps(line))
    if transport != "ok":
        # the artifact above records the skip; the exit code records
        # the FAILURE (a driver must not mistake it for a flat round)
        sys.exit(1)
    if value <= 0 and not smoke_ok:
        sys.exit(1)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — bench must always emit one line
        print(json.dumps({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)
