"""Headline benchmark: ResNet-50 training throughput, images/sec/chip.

Mirrors the reference's kubebench + tf_cnn_benchmarks ResNet-50 headline
workload (BASELINE.md config 2; reference harness
``/root/reference/kubeflow/kubebench/kubebench-job.libsonnet:250-396``).
Runs the in-framework SPMD train step on whatever chips are attached and
prints ONE JSON line.

``vs_baseline`` compares against the reference era's GPU path: tf_cnn_benchmarks
ResNet-50 on one V100 (fp32, batch 64, ~2019) ≈ 360 images/sec — the number
the north star asks to match per-chip on TPU.
"""

from __future__ import annotations

import json
import sys
import time

REFERENCE_GPU_IMAGES_PER_SEC = 360.0
BATCH = 128
WARMUP_STEPS = 3
MEASURE_STEPS = 10


def main() -> None:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.resnet import resnet50
    from kubeflow_tpu.parallel import MeshConfig, create_mesh
    from kubeflow_tpu.train import (
        TrainState,
        create_sharded_state,
        make_image_train_step,
        make_optimizer,
    )

    n_chips = jax.device_count()
    mesh = create_mesh(MeshConfig(dp=n_chips))
    model = resnet50(num_classes=1000)
    batch = BATCH * n_chips

    rng = jax.random.key(0)
    images = jax.random.normal(rng, (batch, 224, 224, 3), jnp.bfloat16)
    labels = jnp.zeros((batch,), jnp.int32)
    tx = make_optimizer(0.1, warmup_steps=10, decay_steps=1000)

    def init_fn(rng):
        variables = model.init(rng, images[:2], train=True)
        return TrainState.create(
            apply_fn=model.apply,
            params=variables["params"],
            batch_stats=variables["batch_stats"],
            tx=tx,
        )

    state, _ = create_sharded_state(init_fn, rng, mesh)
    step = make_image_train_step(mesh)

    for _ in range(WARMUP_STEPS):
        state, metrics = step(state, images, labels)
    float(metrics["loss"])  # host transfer: block_until_ready alone does not
    # guarantee completion on every PJRT transport (observed on axon)

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, metrics = step(state, images, labels)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = MEASURE_STEPS * batch / dt
    per_chip = images_per_sec / n_chips
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_GPU_IMAGES_PER_SEC, 3),
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — bench must always emit one line
        print(json.dumps({
            "metric": "resnet50_train_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(1)
