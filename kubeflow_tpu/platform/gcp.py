"""GCP TPU platform: GKE cluster + TPU pod-slice node pools.

Replaces the reference's Deployment-Manager path
(``/root/reference/bootstrap/pkg/kfapp/gcp/gcp.go`` — ``generateDMConfigs
:1269`` renders the jinja templates under ``deployment/gke/``, ``updateDM
:650`` drives the DM API with ``blockingWait :328`` backoff, IAM bindings
``writeIamBindingsFile :1071``). Here Generate renders declarative cluster
config + a gcloud command plan into ``<app>/gcp_config/``; Apply executes
the plan via the gcloud CLI when present (with retry/backoff) or returns
it as a dry-run report. The GPU node pool + driver DaemonSet are replaced
by TPU slice pools (:mod:`kubeflow_tpu.platform.slices`); IAP/ingress
stays at the manifest layer.

platformParams (``app.yaml`` spec.platformParams):
  project, zone, cluster_name (default: deployment name),
  slices: [{shape: v5e-8, count: 1, spot: false, reservation: ""}],
  cpu_pool_machine_type, cpu_pool_size, network, workload_identity
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
from typing import Any, Dict, List, Optional

import yaml

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.platform.base import Platform, register_platform
from kubeflow_tpu.platform.slices import node_pool_for, slice_shape

GCP_CONFIG_DIR = "gcp_config"


def _params(config: DeploymentConfig) -> Dict[str, Any]:
    p = dict(config.platform_params)
    p.setdefault("project", "")
    p.setdefault("zone", "us-central2-b")
    p.setdefault("cluster_name", config.name)
    p.setdefault("slices", [{"shape": "v5e-8", "count": 1}])
    p.setdefault("cpu_pool_machine_type", "e2-standard-8")
    p.setdefault("cpu_pool_size", 2)
    p.setdefault("network", "default")
    p.setdefault("workload_identity", True)
    return p


def cluster_config(config: DeploymentConfig) -> Dict[str, Any]:
    """The cluster + node-pool declaration (cluster.jinja equivalent)."""
    p = _params(config)
    pools: List[Dict[str, Any]] = [{
        "name": "cpu-pool",
        "machineType": p["cpu_pool_machine_type"],
        "initialNodeCount": p["cpu_pool_size"],
        "config": {"labels": {"kubeflow-tpu.org/pool": "cpu"}},
    }]
    for s in p["slices"]:
        pools.append(node_pool_for(
            s["shape"], count=int(s.get("count", 1)),
            spot=bool(s.get("spot", False)),
            reserved=s.get("reservation", "")))
    cluster: Dict[str, Any] = {
        "name": p["cluster_name"],
        "project": p["project"],
        "zone": p["zone"],
        "network": p["network"],
        "releaseChannel": "regular",
        "nodePools": pools,
    }
    if p["workload_identity"] and p["project"]:
        cluster["workloadIdentityConfig"] = {
            "workloadPool": f"{p['project']}.svc.id.goog"}
    return cluster


def iam_bindings(config: DeploymentConfig) -> List[Dict[str, str]]:
    """Service-account role bindings (writeIamBindingsFile equivalent)."""
    p = _params(config)
    if not p["project"]:
        return []
    sa = f"{config.name}-admin@{p['project']}.iam.gserviceaccount.com"
    return [
        {"member": f"serviceAccount:{sa}", "role": role}
        for role in ("roles/container.admin",
                     "roles/storage.objectAdmin",
                     "roles/logging.logWriter",
                     "roles/monitoring.metricWriter")
    ]


def gcloud_plan(config: DeploymentConfig) -> List[List[str]]:
    """The create-side command plan Apply executes."""
    p = _params(config)
    c = cluster_config(config)
    project_args = ["--project", p["project"]] if p["project"] else []
    plan = [[
        "gcloud", "container", "clusters", "create", c["name"],
        "--zone", p["zone"], "--network", p["network"],
        "--release-channel", "regular",
        "--num-nodes", str(p["cpu_pool_size"]),
        "--machine-type", p["cpu_pool_machine_type"],
        *(["--workload-pool", c["workloadIdentityConfig"]["workloadPool"]]
          if "workloadIdentityConfig" in c else []),
        *project_args,
    ]]
    for pool in c["nodePools"]:
        if pool["name"] == "cpu-pool":
            continue
        shape = slice_shape(pool["config"]["labels"][
            "kubeflow-tpu.org/slice-shape"])
        cmd = [
            "gcloud", "container", "node-pools", "create", pool["name"],
            "--cluster", c["name"], "--zone", p["zone"],
            "--machine-type", shape.machine_type,
            "--tpu-topology", shape.topology,
            "--num-nodes", str(pool["initialNodeCount"]),
            *project_args,
        ]
        if pool["config"].get("spot"):
            cmd.append("--spot")
        if "reservationAffinity" in pool["config"]:
            cmd += ["--reservation-affinity", "specific", "--reservation",
                    pool["config"]["reservationAffinity"]["values"][0]]
        plan.append(cmd)
    plan.append([
        "gcloud", "container", "clusters", "get-credentials", c["name"],
        "--zone", p["zone"], *project_args,
    ])
    return plan


def kubeconfig_path(app_dir: str) -> str:
    """Where Apply materializes cluster credentials (GetK8sConfig parity:
    ``gcp.go:200`` builds a rest.Config; here the kubeconfig file is the
    hand-off to the k8s apply layer and kubectl alike)."""
    return os.path.join(app_dir, GCP_CONFIG_DIR, "kubeconfig")


def kube_context(config: DeploymentConfig) -> str:
    """The context name get-credentials writes (gke_<project>_<zone>_<name>)."""
    p = _params(config)
    return f"gke_{p['project']}_{p['zone']}_{p['cluster_name']}"


@register_platform("gcp-tpu")
class GcpTpuPlatform(Platform):
    name = "gcp-tpu"

    max_attempts = 3
    backoff_s = 10.0

    def generate(self, config: DeploymentConfig, app_dir: str) -> List[str]:
        out_dir = os.path.join(app_dir, GCP_CONFIG_DIR)
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for fname, payload in (
            ("cluster.yaml", cluster_config(config)),
            ("iam_bindings.yaml", iam_bindings(config)),
            ("plan.json", gcloud_plan(config)),
        ):
            path = os.path.join(out_dir, fname)
            with open(path, "w") as f:
                if fname.endswith(".json"):
                    json.dump(payload, f, indent=1)
                else:
                    yaml.safe_dump(payload, f, sort_keys=False)
            paths.append(path)
        return paths

    # operation polling (blockingWait, gcp.go:328-371)
    op_poll_initial_s = 5.0
    op_poll_max_s = 60.0
    op_timeout_s = 1800.0

    def apply(self, config: DeploymentConfig, app_dir: str, *,
              dry_run: bool = True) -> Dict:
        plan = self._load_plan(config, app_dir)
        if dry_run or not shutil.which("gcloud"):
            return {"dry_run": True, "commands": plan,
                    "note": "gcloud not executed"
                            + ("" if dry_run else " (binary not found)")}
        p = _params(config)
        kubeconfig = kubeconfig_path(app_dir)
        executed = []
        for cmd in plan:
            env = None
            if "get-credentials" in cmd:
                # GetK8sConfig parity: credentials land in the app dir's
                # own kubeconfig, not the user's ~/.kube/config
                os.makedirs(os.path.dirname(kubeconfig), exist_ok=True)
                env = {**os.environ, "KUBECONFIG": kubeconfig}
            self._run_with_backoff(cmd, env=env)
            executed.append(cmd)
            if cmd[:2] == ["gcloud", "container"] and "create" in cmd:
                # the CLI can return while the server-side operation is
                # still provisioning (and always does with --async);
                # blockingWait on the cluster's operations
                self.wait_for_operations(p["project"], p["zone"],
                                         p["cluster_name"])
        return {"dry_run": False, "commands": executed,
                "kubeconfig": kubeconfig,
                "context": kube_context(config)}

    def wait_for_operations(self, project: str, zone: str,
                            cluster: str) -> None:
        """Poll THIS cluster's operations until none are pending — the
        ``blockingWait`` loop (``gcp.go:328-371``): exponential backoff,
        surfacing operation errors, hard timeout.

        Lists all operations and filters client-side by targetLink so (a)
        an op that fails by transitioning to DONE-with-error is seen, and
        (b) other teams' operations in a shared project/zone — or on a
        cluster whose name merely extends ours ("demo-prod" vs "demo") —
        neither block nor fail this apply. Historical DONE ops present at
        the first poll are baselined out: a failed attempt a retry already
        recovered from (or last week's failed upgrade) must not fail a
        successful apply."""
        deadline = time.monotonic() + self.op_timeout_s
        delay = self.op_poll_initial_s
        marker = f"/clusters/{cluster}"

        def targets_cluster(op) -> bool:
            link = op.get("targetLink", "")
            # exact segment match: the link either ends at the cluster name
            # or descends into it (/clusters/<name>/nodePools/...)
            return link.endswith(marker) or (marker + "/") in link

        baseline_done: Optional[set] = None
        while True:
            cmd = ["gcloud", "container", "operations", "list",
                   "--zone", zone, "--format", "json"]
            if project:
                cmd += ["--project", project]
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode == 0:
                try:
                    ops = json.loads(proc.stdout or "[]")
                except ValueError:
                    ops = []
                mine = [op for op in ops if targets_cluster(op)]
                if baseline_done is None:
                    baseline_done = {op.get("name") for op in mine
                                     if op.get("status") == "DONE"}
                errored = [op for op in mine
                           if op.get("status") == "DONE"
                           and op.get("name") not in baseline_done
                           and (op.get("error")
                                or op.get("statusMessage"))]
                if errored:
                    op = errored[0]
                    raise RuntimeError(
                        f"operation {op.get('name', '?')} failed: "
                        f"{op.get('statusMessage') or op.get('error')}")
                if not any(op.get("status") != "DONE" for op in mine):
                    return
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"operations still pending after "
                    f"{self.op_timeout_s:.0f}s in zone {zone}")
            time.sleep(delay)
            delay = min(delay * 2, self.op_poll_max_s)

    def delete(self, config: DeploymentConfig, app_dir: str, *,
               dry_run: bool = True) -> Dict:
        p = _params(config)
        cmd = ["gcloud", "container", "clusters", "delete",
               p["cluster_name"], "--zone", p["zone"], "--quiet"]
        if p["project"]:
            cmd += ["--project", p["project"]]
        if dry_run or not shutil.which("gcloud"):
            return {"dry_run": True, "commands": [cmd]}
        self._run_with_backoff(cmd)
        return {"dry_run": False, "commands": [cmd]}

    def _load_plan(self, config: DeploymentConfig,
                   app_dir: str) -> List[List[str]]:
        path = os.path.join(app_dir, GCP_CONFIG_DIR, "plan.json")
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        return gcloud_plan(config)

    def _run_with_backoff(self, cmd: List[str], env=None) -> None:
        """Per-command retry with exponential backoff."""
        delay = self.backoff_s
        for attempt in range(1, self.max_attempts + 1):
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  env=env)
            if proc.returncode == 0:
                return
            if attempt == self.max_attempts:
                raise RuntimeError(
                    f"{' '.join(cmd)} failed after {attempt} attempts: "
                    f"{proc.stderr.strip()[-500:]}")
            time.sleep(delay)
            delay *= 2
