"""GCP TPU platform: GKE cluster + TPU pod-slice node pools.

Replaces the reference's Deployment-Manager path
(``/root/reference/bootstrap/pkg/kfapp/gcp/gcp.go`` — ``generateDMConfigs
:1269`` renders the jinja templates under ``deployment/gke/``, ``updateDM
:650`` drives the DM API with ``blockingWait :328`` backoff, IAM bindings
``writeIamBindingsFile :1071``). Here Generate renders declarative cluster
config + a gcloud command plan into ``<app>/gcp_config/``; Apply executes
the plan via the gcloud CLI when present (with retry/backoff) or returns
it as a dry-run report. The GPU node pool + driver DaemonSet are replaced
by TPU slice pools (:mod:`kubeflow_tpu.platform.slices`); IAP/ingress
stays at the manifest layer.

platformParams (``app.yaml`` spec.platformParams):
  project, zone, cluster_name (default: deployment name),
  slices: [{shape: v5e-8, count: 1, spot: false, reservation: ""}],
  cpu_pool_machine_type, cpu_pool_size, network, workload_identity
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
from typing import Any, Dict, List

import yaml

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.platform.base import Platform, register_platform
from kubeflow_tpu.platform.slices import node_pool_for, slice_shape

GCP_CONFIG_DIR = "gcp_config"


def _params(config: DeploymentConfig) -> Dict[str, Any]:
    p = dict(config.platform_params)
    p.setdefault("project", "")
    p.setdefault("zone", "us-central2-b")
    p.setdefault("cluster_name", config.name)
    p.setdefault("slices", [{"shape": "v5e-8", "count": 1}])
    p.setdefault("cpu_pool_machine_type", "e2-standard-8")
    p.setdefault("cpu_pool_size", 2)
    p.setdefault("network", "default")
    p.setdefault("workload_identity", True)
    return p


def cluster_config(config: DeploymentConfig) -> Dict[str, Any]:
    """The cluster + node-pool declaration (cluster.jinja equivalent)."""
    p = _params(config)
    pools: List[Dict[str, Any]] = [{
        "name": "cpu-pool",
        "machineType": p["cpu_pool_machine_type"],
        "initialNodeCount": p["cpu_pool_size"],
        "config": {"labels": {"kubeflow-tpu.org/pool": "cpu"}},
    }]
    for s in p["slices"]:
        pools.append(node_pool_for(
            s["shape"], count=int(s.get("count", 1)),
            spot=bool(s.get("spot", False)),
            reserved=s.get("reservation", "")))
    cluster: Dict[str, Any] = {
        "name": p["cluster_name"],
        "project": p["project"],
        "zone": p["zone"],
        "network": p["network"],
        "releaseChannel": "regular",
        "nodePools": pools,
    }
    if p["workload_identity"] and p["project"]:
        cluster["workloadIdentityConfig"] = {
            "workloadPool": f"{p['project']}.svc.id.goog"}
    return cluster


def iam_bindings(config: DeploymentConfig) -> List[Dict[str, str]]:
    """Service-account role bindings (writeIamBindingsFile equivalent)."""
    p = _params(config)
    if not p["project"]:
        return []
    sa = f"{config.name}-admin@{p['project']}.iam.gserviceaccount.com"
    return [
        {"member": f"serviceAccount:{sa}", "role": role}
        for role in ("roles/container.admin",
                     "roles/storage.objectAdmin",
                     "roles/logging.logWriter",
                     "roles/monitoring.metricWriter")
    ]


def gcloud_plan(config: DeploymentConfig) -> List[List[str]]:
    """The create-side command plan Apply executes."""
    p = _params(config)
    c = cluster_config(config)
    project_args = ["--project", p["project"]] if p["project"] else []
    plan = [[
        "gcloud", "container", "clusters", "create", c["name"],
        "--zone", p["zone"], "--network", p["network"],
        "--release-channel", "regular",
        "--num-nodes", str(p["cpu_pool_size"]),
        "--machine-type", p["cpu_pool_machine_type"],
        *(["--workload-pool", c["workloadIdentityConfig"]["workloadPool"]]
          if "workloadIdentityConfig" in c else []),
        *project_args,
    ]]
    for pool in c["nodePools"]:
        if pool["name"] == "cpu-pool":
            continue
        shape = slice_shape(pool["config"]["labels"][
            "kubeflow-tpu.org/slice-shape"])
        cmd = [
            "gcloud", "container", "node-pools", "create", pool["name"],
            "--cluster", c["name"], "--zone", p["zone"],
            "--machine-type", shape.machine_type,
            "--tpu-topology", shape.topology,
            "--num-nodes", str(pool["initialNodeCount"]),
            *project_args,
        ]
        if pool["config"].get("spot"):
            cmd.append("--spot")
        if "reservationAffinity" in pool["config"]:
            cmd += ["--reservation-affinity", "specific", "--reservation",
                    pool["config"]["reservationAffinity"]["values"][0]]
        plan.append(cmd)
    plan.append([
        "gcloud", "container", "clusters", "get-credentials", c["name"],
        "--zone", p["zone"], *project_args,
    ])
    return plan


@register_platform("gcp-tpu")
class GcpTpuPlatform(Platform):
    name = "gcp-tpu"

    max_attempts = 3
    backoff_s = 10.0

    def generate(self, config: DeploymentConfig, app_dir: str) -> List[str]:
        out_dir = os.path.join(app_dir, GCP_CONFIG_DIR)
        os.makedirs(out_dir, exist_ok=True)
        paths = []
        for fname, payload in (
            ("cluster.yaml", cluster_config(config)),
            ("iam_bindings.yaml", iam_bindings(config)),
            ("plan.json", gcloud_plan(config)),
        ):
            path = os.path.join(out_dir, fname)
            with open(path, "w") as f:
                if fname.endswith(".json"):
                    json.dump(payload, f, indent=1)
                else:
                    yaml.safe_dump(payload, f, sort_keys=False)
            paths.append(path)
        return paths

    def apply(self, config: DeploymentConfig, app_dir: str, *,
              dry_run: bool = True) -> Dict:
        plan = self._load_plan(config, app_dir)
        if dry_run or not shutil.which("gcloud"):
            return {"dry_run": True, "commands": plan,
                    "note": "gcloud not executed"
                            + ("" if dry_run else " (binary not found)")}
        executed = []
        for cmd in plan:
            self._run_with_backoff(cmd)
            executed.append(cmd)
        return {"dry_run": False, "commands": executed}

    def delete(self, config: DeploymentConfig, app_dir: str, *,
               dry_run: bool = True) -> Dict:
        p = _params(config)
        cmd = ["gcloud", "container", "clusters", "delete",
               p["cluster_name"], "--zone", p["zone"], "--quiet"]
        if p["project"]:
            cmd += ["--project", p["project"]]
        if dry_run or not shutil.which("gcloud"):
            return {"dry_run": True, "commands": [cmd]}
        self._run_with_backoff(cmd)
        return {"dry_run": False, "commands": [cmd]}

    def _load_plan(self, config: DeploymentConfig,
                   app_dir: str) -> List[List[str]]:
        path = os.path.join(app_dir, GCP_CONFIG_DIR, "plan.json")
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        return gcloud_plan(config)

    def _run_with_backoff(self, cmd: List[str]) -> None:
        """blockingWait-style retry (gcp.go:328-371 exponential backoff)."""
        delay = self.backoff_s
        for attempt in range(1, self.max_attempts + 1):
            proc = subprocess.run(cmd, capture_output=True, text=True)
            if proc.returncode == 0:
                return
            if attempt == self.max_attempts:
                raise RuntimeError(
                    f"{' '.join(cmd)} failed after {attempt} attempts: "
                    f"{proc.stderr.strip()[-500:]}")
            time.sleep(delay)
            delay *= 2
