"""TPU slice inventory: shapes, topologies, and node-pool derivation.

The platform's equivalent of the reference's GPU accelerator node-pool
config (``/root/reference/deployment/gke/deployment_manager_configs/
cluster-kubeflow.yaml:56-66`` — gpu-pool with ``nvidia-tesla-k80``). A TPU
slice is indivisible and topology-addressed: provisioning asks for whole
pod slices, and the scheduler places gangs onto them (SURVEY.md §7 hard
part (a)).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict


@dataclass(frozen=True)
class SliceShape:
    """One provisionable slice type."""

    accelerator: str       # GKE accelerator label, e.g. "tpu-v5-lite-podslice"
    generation: str        # v4 | v5e | v5p | v6e
    topology: str          # chip grid, e.g. "4x8"
    chips: int             # total chips in the slice
    hosts: int             # VMs in the slice (chips / chips-per-host)
    chips_per_host: int
    machine_type: str

    @property
    def name(self) -> str:
        return f"{self.generation}-{self.chips}"


def _v5e(topology: str, chips: int, hosts: int) -> SliceShape:
    return SliceShape("tpu-v5-lite-podslice", "v5e", topology, chips, hosts,
                      chips // hosts, "ct5lp-hightpu-4t")


def _v5p(topology: str, chips: int, hosts: int) -> SliceShape:
    return SliceShape("tpu-v5p-slice", "v5p", topology, chips, hosts,
                      chips // hosts, "ct5p-hightpu-4t")


def _v4(topology: str, chips: int, hosts: int) -> SliceShape:
    return SliceShape("tpu-v4-podslice", "v4", topology, chips, hosts,
                      chips // hosts, "ct4p-hightpu-4t")


def _v6e(topology: str, chips: int, hosts: int) -> SliceShape:
    return SliceShape("tpu-v6e-slice", "v6e", topology, chips, hosts,
                      chips // hosts, "ct6e-standard-4t")


# the provisionable shapes (single host → full pod) per generation
SLICE_SHAPES: Dict[str, SliceShape] = {s.name: s for s in [
    _v5e("2x2", 4, 1), _v5e("2x4", 8, 2), _v5e("4x4", 16, 4),
    _v5e("4x8", 32, 8), _v5e("8x8", 64, 16), _v5e("8x16", 128, 32),
    _v5e("16x16", 256, 64),
    _v5p("2x2x1", 4, 1), _v5p("2x2x2", 8, 2), _v5p("2x2x4", 16, 4),
    _v5p("2x4x4", 32, 8),
    _v5p("4x4x4", 64, 16), _v5p("4x4x8", 128, 32), _v5p("4x8x8", 256, 64),
    _v4("2x2x1", 4, 1), _v4("2x2x2", 8, 2), _v4("2x2x4", 16, 4),
    _v4("2x4x4", 32, 8), _v4("4x4x4", 64, 16), _v4("4x4x8", 128, 32),
    _v6e("2x2", 4, 1), _v6e("2x4", 8, 2), _v6e("4x4", 16, 4),
    _v6e("4x8", 32, 8), _v6e("8x8", 64, 16), _v6e("8x16", 128, 32),
    _v6e("16x16", 256, 64),
]}


def slice_shape(name: str) -> SliceShape:
    """Look up e.g. ``v5e-8`` / ``v5p-128``."""
    if name not in SLICE_SHAPES:
        known = ", ".join(sorted(SLICE_SHAPES))
        raise ValueError(f"unknown slice shape {name!r}; known: {known}")
    return SLICE_SHAPES[name]


def node_pool_for(name: str, *, count: int = 1, spot: bool = False,
                  reserved: str = "") -> Dict:
    """Render the GKE node-pool config for ``count`` slices of this shape.

    Replaces the reference's GPU pool (``cluster.jinja:167-169``): selector
    labels are ``cloud.google.com/gke-tpu-accelerator`` + ``-topology``,
    which is what TpuJob worker pods node-select on, and there is NO driver
    DaemonSet — TPU runtime ships in the node image.
    """
    shape = slice_shape(name)
    pool: Dict = {
        "name": f"tpu-{shape.name}",
        "machineType": shape.machine_type,
        # one node per TPU host VM; a slice of H hosts needs H nodes that
        # GKE provisions atomically per slice
        "initialNodeCount": shape.hosts * count,
        "placementPolicy": {"tpuTopology": shape.topology,
                            "type": "COMPACT"},
        "config": {
            "labels": {
                "cloud.google.com/gke-tpu-accelerator": shape.accelerator,
                "cloud.google.com/gke-tpu-topology": shape.topology,
                "kubeflow-tpu.org/slice-shape": shape.name,
            },
            "taints": [{"key": "google.com/tpu", "value": "present",
                        "effect": "NO_SCHEDULE"}],
        },
    }
    if spot:
        pool["config"]["spot"] = True
    if reserved:
        pool["config"]["reservationAffinity"] = {
            "consumeReservationType": "SPECIFIC_RESERVATION",
            "key": "compute.googleapis.com/reservation-name",
            "values": [reserved],
        }
    return pool
