"""Platform interface + registry.

Mirrors the reference's Go ``Platform`` contract: a platform plugin does
``Generate`` (emit infra config to the app dir) and ``Apply``/``Delete``
(drive the cloud control plane), and yields a k8s client for the layers
above (``/root/reference/bootstrap/pkg/apis/apps/group.go:104-121``;
coordinator phase split ``coordinator.go:715-917``).
"""

from __future__ import annotations

import abc
from typing import Callable, Dict, List, Optional

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s.client import KubeClient


class Platform(abc.ABC):
    """One provisioning backend (gcp-tpu, local, existing)."""

    name = "base"

    @abc.abstractmethod
    def generate(self, config: DeploymentConfig, app_dir: str) -> List[str]:
        """Emit infra config files into the app dir; returns paths."""

    @abc.abstractmethod
    def apply(self, config: DeploymentConfig, app_dir: str, *,
              dry_run: bool = True) -> Dict:
        """Provision (or plan) the infrastructure. Returns a report dict;
        with ``dry_run`` the report carries the commands that would run."""

    @abc.abstractmethod
    def delete(self, config: DeploymentConfig, app_dir: str, *,
               dry_run: bool = True) -> Dict:
        """Tear down (or plan tearing down) the infrastructure."""

    def kube_client(self, config: DeploymentConfig) -> Optional[KubeClient]:
        """Client for the provisioned cluster; None when not applicable."""
        return None


_PLATFORMS: Dict[str, Callable[[], Platform]] = {}


def register_platform(name: str):
    def wrap(cls):
        _PLATFORMS[name] = cls
        return cls
    return wrap


def get_platform(name: str) -> Platform:
    # import built-ins so their register_platform calls run
    from kubeflow_tpu.platform import gcp, local  # noqa: F401

    if name not in _PLATFORMS:
        known = ", ".join(sorted(_PLATFORMS))
        raise ValueError(f"unknown platform {name!r}; known: {known}")
    return _PLATFORMS[name]()
