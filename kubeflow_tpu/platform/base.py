"""Platform interface + registry.

Mirrors the reference's Go ``Platform`` contract: a platform plugin does
``Generate`` (emit infra config to the app dir) and ``Apply``/``Delete``
(drive the cloud control plane), and yields a k8s client for the layers
above (``/root/reference/bootstrap/pkg/apis/apps/group.go:104-121``;
coordinator phase split ``coordinator.go:715-917``).
"""

from __future__ import annotations

import abc
from typing import Callable, Dict, List, Optional

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s.client import KubeClient


class Platform(abc.ABC):
    """One provisioning backend (gcp-tpu, local, existing)."""

    name = "base"

    @abc.abstractmethod
    def generate(self, config: DeploymentConfig, app_dir: str) -> List[str]:
        """Emit infra config files into the app dir; returns paths."""

    @abc.abstractmethod
    def apply(self, config: DeploymentConfig, app_dir: str, *,
              dry_run: bool = True) -> Dict:
        """Provision (or plan) the infrastructure. Returns a report dict;
        with ``dry_run`` the report carries the commands that would run."""

    @abc.abstractmethod
    def delete(self, config: DeploymentConfig, app_dir: str, *,
               dry_run: bool = True) -> Dict:
        """Tear down (or plan tearing down) the infrastructure."""

    def kube_client(self, config: DeploymentConfig) -> Optional[KubeClient]:
        """Client for the provisioned cluster; None when not applicable."""
        return None


_PLATFORMS: Dict[str, Callable[[], Platform]] = {}


def register_platform(name: str):
    def wrap(cls):
        _PLATFORMS[name] = cls
        return cls
    return wrap


def load_platform_plugins(env: Optional[Dict[str, str]] = None) -> List[str]:
    """Import out-of-tree platform modules named in KFTPU_PLATFORM_PLUGINS.

    The reference loads platform plugins as Go ``.so`` files
    (``LoadKfApp``, ``/root/reference/bootstrap/pkg/apis/apps/
    group.go:43-125``); the Python equivalent is an import hook: each
    comma-separated module is imported so its ``@register_platform``
    decorators run. Returns the modules imported.
    """
    import importlib
    import os

    raw = (env if env is not None else os.environ).get(
        "KFTPU_PLATFORM_PLUGINS", "")
    loaded = []
    for mod in filter(None, (m.strip() for m in raw.split(","))):
        importlib.import_module(mod)
        loaded.append(mod)
    return loaded


def platform_known(name: str) -> bool:
    """Membership check WITHOUT instantiating (config validation must
    not run a plugin's constructor, and must not mask its errors).

    A broken KFTPU_PLATFORM_PLUGINS module surfaces as ValueError so
    every caller that treats validation failures uniformly (CLI,
    bootstrap server) reports it as a config error, not a traceback.
    """
    # import built-ins so their register_platform calls run
    from kubeflow_tpu.platform import gcp, local  # noqa: F401

    if name in _PLATFORMS:
        return True
    try:
        load_platform_plugins()
    except Exception as e:  # noqa: BLE001 — a plugin body can raise anything
        raise ValueError(
            f"KFTPU_PLATFORM_PLUGINS failed to import: "
            f"{type(e).__name__}: {e}") from e
    return name in _PLATFORMS


def get_platform(name: str) -> Platform:
    if not platform_known(name):
        known = ", ".join(sorted(_PLATFORMS))
        raise ValueError(f"unknown platform {name!r}; known: {known}")
    return _PLATFORMS[name]()
