"""Platform layer: cluster provisioning for TPU workloads.

Reference surface: the Go ``Platform`` interface
(``/root/reference/bootstrap/pkg/apis/apps/group.go:116-121``: KfApp
Init/Generate/Apply/Delete + ``GetK8sConfig``) with plugins for
gcp / aws / minikube / dockerfordesktop / existing_arrikto
(``bootstrap/pkg/kfapp/*/``). The TPU build replaces the GPU node-pool DM
configs (``deployment/gke/deployment_manager_configs/cluster.jinja:
167-169``) and the gpu-driver DaemonSet (``kubeflow/gcp/gpu-driver.
libsonnet``) with TPU pod-slice node pools — no driver installer; the TPU
runtime is part of the node image.
"""

from kubeflow_tpu.platform.base import Platform, get_platform  # noqa: F401
from kubeflow_tpu.platform.slices import (  # noqa: F401
    SliceShape,
    SLICE_SHAPES,
    slice_shape,
    node_pool_for,
)
from kubeflow_tpu.platform.gcp import GcpTpuPlatform  # noqa: F401
from kubeflow_tpu.platform.local import ExistingPlatform, LocalPlatform  # noqa: F401
