"""Local and BYO-cluster platforms.

Reference analogues: minikube / dockerfordesktop plugins
(``/root/reference/bootstrap/pkg/kfapp/minikube/minikube.go``,
``dockerfordesktop/dockerfordesktop.go``) and existing_arrikto
(``existing_arrikto/existing.go`` — BYO cluster, no provisioning).

- :class:`LocalPlatform` — dev loop: a file-backed fake API server plus
  *fake slice* node objects advertising ``google.com/tpu`` capacity with
  the same accelerator/topology labels real GKE TPU pools carry, so gang
  placement and node selection exercise the real code paths with no cloud.
- :class:`ExistingPlatform` — BYO cluster: no provisioning; Apply only
  verifies the API server is reachable.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import yaml

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s.client import ApiError, HttpKubeClient, KubeClient
from kubeflow_tpu.k8s.fakefile import FileBackedFakeClient
from kubeflow_tpu.k8s.helpers import create_if_absent
from kubeflow_tpu.platform.base import Platform, register_platform
from kubeflow_tpu.platform.slices import slice_shape

LOCAL_CONFIG_DIR = "local_config"


def fake_slice_nodes(shape_name: str, *, count: int = 1) -> List[Dict]:
    """Node objects mimicking one or more TPU slices for the dev loop."""
    shape = slice_shape(shape_name)
    nodes = []
    for s in range(count):
        for h in range(shape.hosts):
            nodes.append({
                "apiVersion": "v1",
                "kind": "Node",
                "metadata": {
                    "name": f"fake-{shape.name}-s{s}-h{h}",
                    "labels": {
                        "cloud.google.com/gke-tpu-accelerator":
                            shape.accelerator,
                        "cloud.google.com/gke-tpu-topology": shape.topology,
                        "kubeflow-tpu.org/slice-shape": shape.name,
                        "kubeflow-tpu.org/slice-index": str(s),
                        "kubeflow-tpu.org/fake": "true",
                    },
                },
                "status": {
                    "capacity": {"google.com/tpu": shape.chips_per_host,
                                 "cpu": "8", "memory": "32Gi"},
                    "conditions": [{"type": "Ready", "status": "True"}],
                },
            })
    return nodes


@register_platform("local")
class LocalPlatform(Platform):
    name = "local"

    def generate(self, config: DeploymentConfig, app_dir: str) -> List[str]:
        out_dir = os.path.join(app_dir, LOCAL_CONFIG_DIR)
        os.makedirs(out_dir, exist_ok=True)
        shapes = config.platform_params.get(
            "slices", [{"shape": "v5e-8", "count": 1}])
        nodes: List[Dict] = []
        for s in shapes:
            nodes.extend(fake_slice_nodes(s["shape"],
                                          count=int(s.get("count", 1))))
        path = os.path.join(out_dir, "fake_nodes.yaml")
        with open(path, "w") as f:
            yaml.safe_dump_all(nodes, f, sort_keys=False)
        return [path]

    def apply(self, config: DeploymentConfig, app_dir: str, *,
              dry_run: bool = True) -> Dict:
        """Seed fake slice nodes into the file-backed cluster state."""
        path = os.path.join(app_dir, LOCAL_CONFIG_DIR, "fake_nodes.yaml")
        if not os.path.exists(path):
            self.generate(config, app_dir)
        with open(path) as f:
            nodes = [n for n in yaml.safe_load_all(f) if n]
        if dry_run:
            return {"dry_run": True,
                    "commands": [f"seed {len(nodes)} fake TPU node(s) into "
                                 "the local cluster state"]}
        client = self.kube_client(config, app_dir)
        for node in nodes:
            create_if_absent(client, node)
        return {"dry_run": False, "nodes": len(nodes)}

    def delete(self, config: DeploymentConfig, app_dir: str, *,
               dry_run: bool = True) -> Dict:
        client = self.kube_client(config, app_dir)
        fakes = [
            node["metadata"]["name"] for node in client.list("v1", "Node")
            if (node.get("metadata", {}).get("labels", {}) or {})
            .get("kubeflow-tpu.org/fake") == "true"
        ]
        if dry_run:
            return {"dry_run": True,
                    "commands": [f"remove fake TPU node {n}" for n in fakes]}
        for name in fakes:
            client.delete("v1", "Node", "", name)
        return {"dry_run": False, "nodes_removed": len(fakes)}

    def kube_client(self, config: DeploymentConfig,
                    app_dir: str = ".") -> KubeClient:
        state = config.platform_params.get(
            "state_file", os.path.join(app_dir, ".cluster.json"))
        return FileBackedFakeClient(state)


@register_platform("existing")
class ExistingPlatform(Platform):
    name = "existing"

    def generate(self, config: DeploymentConfig, app_dir: str) -> List[str]:
        return []  # nothing to provision

    def apply(self, config: DeploymentConfig, app_dir: str, *,
              dry_run: bool = True) -> Dict:
        client = self.kube_client(config)
        try:
            client.list("v1", "Namespace")  # read-only reachability probe
            return {"dry_run": dry_run, "reachable": True,
                    "commands": ["verify API server reachability"]}
        except (ApiError, OSError) as e:
            return {"dry_run": dry_run, "reachable": False, "error": str(e),
                    "commands": ["verify API server reachability"]}

    def delete(self, config: DeploymentConfig, app_dir: str, *,
               dry_run: bool = True) -> Dict:
        return {"dry_run": True, "note": "existing cluster is not deleted"}

    def kube_client(self, config: DeploymentConfig) -> Optional[KubeClient]:
        server = config.platform_params.get("server", "")
        if server:
            return HttpKubeClient(
                base_url=server,
                verify=not config.platform_params.get("insecure", False))
        return HttpKubeClient()
