"""CLI: ``python -m kubeflow_tpu.bench run --workload mnist -- --steps 30``."""

from __future__ import annotations

import argparse
import json
import sys

from kubeflow_tpu.bench.pipeline import (
    BenchmarkSpec,
    LocalRunner,
    WORKLOADS,
    report,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubeflow_tpu.bench")
    sub = p.add_subparsers(dest="command", required=True)
    rp = sub.add_parser("run", help="run a benchmark locally")
    rp.add_argument("--name", default=None)
    rp.add_argument("--workload", required=True,
                    help=f"one of {sorted(WORKLOADS)} or a module path")
    rp.add_argument("--out-dir", default="bench_results")
    rp.add_argument("--timeout", type=float, default=3600.0)
    rp.add_argument("workload_args", nargs="*",
                    help="args after -- go to the workload")
    args = p.parse_args(argv)

    spec = BenchmarkSpec(
        name=args.name or args.workload,
        workload=args.workload,
        args=args.workload_args,
        timeout_s=args.timeout,
    )
    result = LocalRunner().run(spec)
    paths = report(result, args.out_dir)
    print(json.dumps({
        "name": result.name,
        "status": result.status,
        "wall_time_s": round(result.wall_time_s, 2),
        "final_metrics": result.final_metrics,
        **paths,
    }))
    return 0 if result.status == "Succeeded" else 1


if __name__ == "__main__":
    sys.exit(main())
