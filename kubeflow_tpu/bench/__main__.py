"""CLI: ``python -m kubeflow_tpu.bench run --workload mnist -- --steps 30``
and the in-cluster reporter step: ``... report --name X --out /results``."""

from __future__ import annotations

import argparse
import json
import os
import sys

from kubeflow_tpu.bench.pipeline import (
    BenchmarkResult,
    BenchmarkSpec,
    LocalRunner,
    WORKLOADS,
    report,
)


def _cmd_run(args) -> int:
    spec = BenchmarkSpec(
        name=args.name or args.workload,
        workload=args.workload,
        args=args.workload_args,
        timeout_s=args.timeout,
    )
    result = LocalRunner().run(spec)
    paths = report(result, args.out_dir)
    print(json.dumps({
        "name": result.name,
        "status": result.status,
        "wall_time_s": round(result.wall_time_s, 2),
        "final_metrics": result.final_metrics,
        **paths,
    }))
    return 0 if result.status == "Succeeded" else 1


def _cmd_report(args) -> int:
    """The benchmark workflow's reporter step: read the workload's metrics
    JSONL from the shared results dir, emit csv + json (kubebench's
    ``reporter csv``)."""
    path = os.path.join(args.out, f"{args.name}.jsonl")
    metrics = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        metrics.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
    result = BenchmarkResult(
        args.name, "Succeeded" if metrics else "NoMetrics", 0.0, metrics)
    paths = report(result, args.out)
    print(json.dumps({"name": args.name, "status": result.status,
                      "final_metrics": result.final_metrics, **paths}))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kubeflow_tpu.bench")
    sub = p.add_subparsers(dest="command", required=True)
    rp = sub.add_parser("run", help="run a benchmark locally")
    rp.add_argument("--name", default=None)
    rp.add_argument("--workload", required=True,
                    help=f"one of {sorted(WORKLOADS)} or a module path")
    rp.add_argument("--out-dir", default="bench_results")
    rp.add_argument("--timeout", type=float, default=3600.0)
    rp.add_argument("workload_args", nargs="*",
                    help="args after -- go to the workload")
    rp.set_defaults(fn=_cmd_run)
    pp = sub.add_parser("report", help="reporter step for workflow runs")
    pp.add_argument("--name", required=True)
    pp.add_argument("--out", default="/results")
    pp.set_defaults(fn=_cmd_report)
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
