"""Trace inspection: the auditable per-op breakdown behind PERF.md.

The profiler tier (``kubeflow_tpu/utils/profiler.py``) writes
TensorBoard-compatible trace dirs (``plugins/profile/<run>/*.trace.json.gz``);
this reads them back and aggregates device-lane op durations, so a perf
claim ("backward conv fusions dominate at N ms/step") is reproducible
from a committed artifact with one command:

    ctl trace-top traces/r04/resnet50 [--top 20]

The reference's closest surface is "open TensorBoard and look"
(``/root/reference/kubeflow/tensorboard/tensorboard.libsonnet``); a CLI
table is what perf review actually needs.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
from typing import Any, Dict, List, Optional

# the device lane the XLA profiler emits per-op events into
_OP_LANE = "XLA Ops"
_STEP_LANE = "Steps"


def find_trace_file(trace_dir: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``trace_dir`` (searched
    recursively — the profiler nests ``plugins/profile/<timestamp>/``)."""
    hits = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                     recursive=True)
    return max(hits, key=os.path.getmtime) if hits else None


def load_events(path: str) -> List[Dict[str, Any]]:
    with gzip.open(path, "rt") as f:
        return json.load(f).get("traceEvents", [])


def top_ops(trace_dir: str, top: int = 20) -> Dict[str, Any]:
    """Aggregate device-lane op durations from the newest trace.

    Returns ``{trace_file, device, steps, device_total_ms, ops: [{name,
    total_ms, pct, count, mean_us}, ...]}`` — ops sorted by total time.
    """
    path = find_trace_file(trace_dir)
    if path is None:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {trace_dir!r} — capture one with "
            "bench.py --profile or utils.profiler.trace()")
    events = load_events(path)
    proc = {e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    lanes = {(e["pid"], e.get("tid")): e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    device_pids = {p for p, n in proc.items() if "/device:" in n}
    agg: Dict[str, float] = collections.defaultdict(float)
    cnt: collections.Counter = collections.Counter()
    steps_by_pid: collections.Counter = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        lane = lanes.get((e["pid"], e.get("tid")))
        if lane == _OP_LANE:
            agg[e["name"]] += float(e.get("dur", 0.0))
            cnt[e["name"]] += 1
        elif lane == _STEP_LANE:
            steps_by_pid[e["pid"]] += 1
    # every core replays the same steps; op totals aggregate all cores
    steps = max(steps_by_pid.values()) if steps_by_pid else 0
    total = sum(agg.values())
    ops = [{
        "name": name,
        "total_ms": round(dur / 1e3, 3),
        "pct": round(100.0 * dur / total, 1) if total else 0.0,
        "count": cnt[name],
        "mean_us": round(dur / cnt[name], 1),
    } for name, dur in sorted(agg.items(), key=lambda kv: -kv[1])[:top]]
    return {
        "trace_file": path,
        "devices": sorted(proc[p] for p in device_pids),
        "steps": steps,
        "device_total_ms": round(total / 1e3, 3),
        "ops": ops,
    }


def format_top_ops(report: Dict[str, Any]) -> str:
    lines = [
        f"trace:  {report['trace_file']}",
        f"devices: {', '.join(report['devices'])}   "
        f"steps: {report['steps']}   "
        f"device time: {report['device_total_ms']:.1f} ms",
        f"{'total ms':>10} {'%':>6} {'count':>6} {'mean us':>9}  op",
    ]
    for op in report["ops"]:
        lines.append(f"{op['total_ms']:>10.2f} {op['pct']:>6.1f} "
                     f"{op['count']:>6d} {op['mean_us']:>9.1f}  "
                     f"{op['name']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    """Shared CLI body (also behind ``ctl trace-top``)."""
    import argparse
    import sys

    p = argparse.ArgumentParser(
        description="per-op device-time table from a profiler trace dir")
    p.add_argument("trace_dir")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of a table")
    args = p.parse_args(argv)
    try:
        report = top_ops(args.trace_dir, top=args.top)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    print(json.dumps(report) if args.json else format_top_ops(report))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
