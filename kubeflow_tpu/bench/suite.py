"""The five BASELINE.md benchmark configs, runnable on whatever chips exist.

Reference counterpart: the kubebench pipeline drives ``tf_cnn_benchmarks``
workloads and a csv reporter (``/root/reference/kubeflow/kubebench/
kubebench-job.libsonnet:250-396``); the reference publishes no numbers
(BASELINE.md), so each config here *measures* and reports:

1. ``mnist``      — tf-cnn MNIST 1-worker parity: correctness smoke
                    (loss must fall) + images/sec.
2. ``resnet50``   — the headline: SPMD training throughput, images/sec/chip
                    + achieved TFLOP/s + MFU.
3. ``bert``       — DDP BERT-base parity: masked-LM step time + MFU.
4. ``allreduce``  — MPI/NCCL ring-allreduce parity: XLA AllReduce bus GB/s.
5. ``serving``    — tf-serving parity: REST predict p50/p99 latency + QPS.

MFU accounting: FLOPs per step are analytic model FLOPs (the MFU
convention — rematerialization or backend-specific lowering must not
inflate the score), adjusted for the exact model variant under test; peak
comes from the device kind (override: ``KFTPU_PEAK_TFLOPS``).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

# bf16 peak TFLOP/s per chip by device kind (substring match, lowercase)
_PEAK_TFLOPS = {
    "v5 lite": 197.0,   # v5e
    "v5litepod": 197.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v4": 275.0,
    "v6 lite": 918.0,   # v6e / Trillium
    "v6e": 918.0,
    "v3": 123.0,
    "v2": 46.0,
    "cpu": 0.0,         # MFU meaningless on host CPU
}


def _by_device_kind(table: Dict[str, float]) -> float:
    """First substring match of the attached chip's kind in ``table``."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    return next((v for k, v in table.items() if k in kind), 0.0)


def peak_flops_per_chip() -> float:
    """bf16 peak FLOP/s of one attached chip (0.0 = unknown/CPU)."""
    override = os.environ.get("KFTPU_PEAK_TFLOPS")
    if override:
        return float(override) * 1e12
    return _by_device_kind(_PEAK_TFLOPS) * 1e12


def resnet50_train_flops_per_image(stem: str) -> float:
    """Analytic fwd+bwd FLOPs per 224² image (3 × forward).

    The standard 7×7-stem ResNet-50 forward is ~4.11 GFLOP; the
    space_to_depth stem replaces the 0.236 GFLOP stem conv with a
    0.077 GFLOP 2×2 conv over folded pixels — the MFU constant must match
    the model actually compiled or the score is inflated."""
    fwd = 4.11e9 if stem == "conv" else 4.11e9 - 0.236e9 + 0.077e9
    return 3.0 * fwd


def _timed_steps(step: Callable, n_steps: int, warmup: int,
                 sync: Callable[[], None]) -> float:
    """Seconds per step, after warmup; ``sync`` forces device completion
    (a host transfer — block_until_ready alone does not guarantee
    completion on every PJRT transport; observed on axon)."""
    for _ in range(warmup):
        step()
    sync()
    t0 = time.perf_counter()
    for _ in range(n_steps):
        step()
    sync()
    return (time.perf_counter() - t0) / n_steps


# HBM bandwidth per chip by device kind (GB/s, bf16 era datasheets)
_HBM_GBPS = {
    "v5 lite": 819.0, "v5litepod": 819.0, "v5e": 819.0,
    "v5p": 2765.0, "v4": 1228.0, "v6 lite": 1640.0, "v6e": 1640.0,
    "v3": 900.0, "v2": 700.0,
}


def _roofline(jitted, mesh, sec_per_step: float, *args) -> Dict[str, Any]:
    """Memory-roofline context for a jitted step: XLA's bytes-accessed
    estimate vs the chip's HBM bandwidth.

    MFU alone misleads on bandwidth-bound workloads (ResNet-50 training
    with exact BatchNorm reads/writes ~25× more activation bytes per FLOP
    than a transformer): when ``hbm_bound_fraction`` ≈ 1, the step is at
    the memory roofline and more MFU is not available at this batch size
    and dtype — cf. the profile traces committed per round."""
    try:
        bw = _by_device_kind(_HBM_GBPS)
        if not bw:
            return {}
        # one extra AOT trace+compile to read cost_analysis; the backend
        # compile cache (the step just ran with these shapes) keeps it cheap
        from kubeflow_tpu.parallel.mesh import mesh_context

        with mesh_context(mesh):
            ca = jitted.lower(*args).compile().cost_analysis()
        if isinstance(ca, list):
            ca = ca[0] if ca else {}
        nbytes = float(ca.get("bytes accessed", 0.0))
        if not nbytes:
            return {}
        roofline_s = nbytes / (bw * 1e9)
        return {
            "hbm_gb_per_step": round(nbytes / 1e9, 2),
            "hbm_roofline_ms": round(roofline_s * 1e3, 2),
            "hbm_bound_fraction": round(roofline_s / sec_per_step, 3),
        }
    except Exception:  # noqa: BLE001 — context, never a bench failure
        return {}


def _capture_trace(step: Callable, sync: Callable[[], None],
                   logdir: str, n_steps: int = 3) -> None:
    """Profile n compiled steps AFTER timing (capture overhead must not
    contaminate the reported numbers); trace lands in ``logdir``. Capture
    is auxiliary: a profiler failure must never void the measured result."""
    import logging

    from kubeflow_tpu.utils.profiler import trace

    try:
        with trace(logdir):
            for _ in range(n_steps):
                step()
            sync()
    except Exception as e:  # noqa: BLE001
        logging.getLogger(__name__).warning(
            "trace capture failed (result kept): %s: %s",
            type(e).__name__, e)


def _mfu(flops_per_step: Optional[float], sec_per_step: float,
         n_chips: int) -> Dict[str, float]:
    peak = peak_flops_per_chip()
    if not flops_per_step or not peak:
        return {}
    achieved = flops_per_step / sec_per_step
    return {
        "tflops_per_chip": round(achieved / n_chips / 1e12, 2),
        "mfu": round(achieved / (peak * n_chips), 4),
    }


def _step_telemetry_pass(step: Callable, sync: Callable[[], None],
                         jitted: Any, *, n_steps: int,
                         flops_per_step: Optional[float],
                         n_chips: int) -> Dict[str, Any]:
    """A short per-step-synced pass through :class:`StepTelemetry` AFTER
    the mean-timing pass, so the BENCH artifact carries step-REGULARITY
    evidence (p50/p99 step time, recompile count, MFU) next to the
    means. Separate pass by design: per-step sync serializes dispatch
    and must not contaminate the headline throughput numbers. Auxiliary
    by contract — any failure returns {} and the measured result stands."""
    try:
        from kubeflow_tpu.obs.steps import StepTelemetry
        from kubeflow_tpu.utils.metrics import Registry

        telem = StepTelemetry(
            registry=Registry(),  # private: no global-registry pollution
            flops_per_step=flops_per_step,
            peak_flops_per_chip=peak_flops_per_chip() or None,
            n_chips=n_chips, use_cost_analysis=False)

        def one_synced():
            step()
            sync()

        one_synced.jitted = jitted  # real recompile accounting (cache delta)
        wrapped = telem.wrap(one_synced)
        # compile & memory evidence beside the goodput block
        # (docs/OBSERVABILITY.md "Compile & memory"): a private ledger
        # subscribed for the pass's duration records any backend
        # compiles the pass triggers, and the AOT fingerprint/budget
        # read prices the program's predicted footprint
        from kubeflow_tpu.obs.xprof import CompileLedger, HbmSampler

        ledger = CompileLedger()
        ledger.install()
        try:
            for _ in range(n_steps):
                wrapped()
        finally:
            ledger.uninstall()
        out: Dict[str, Any] = {"step_telemetry": telem.summary()}
        # the goodput block (docs/OBSERVABILITY.md "Goodput"): the
        # productive fraction of the pass's wall clock next to img/s,
        # so a round that recompiles or stalls between steps reads as
        # the badput it is, not as a flat throughput number
        from kubeflow_tpu.obs.goodput import from_step_records

        block = from_step_records(telem.recorder.records())
        if block:
            out["goodput"] = block
        compile_block = ledger.summary()
        if compile_block.get("count"):
            out["compile"] = compile_block
        memory: Dict[str, Any] = {}
        try:
            from kubeflow_tpu.obs.xprof import (
                hlo_fingerprint,
                memory_budget,
            )

            lower = getattr(jitted, "lower", None)
            if lower is not None:
                lowered = lower()
                compiled = lowered.compile()
                budget = memory_budget(compiled)
                if budget:
                    memory["budget_bytes"] = budget
                    memory["fingerprint"] = hlo_fingerprint(lowered)
        except Exception:  # noqa: BLE001 — evidence, never a failure
            pass
        watermark = HbmSampler().sample()
        if watermark:
            memory["hbm_bytes"] = {k: int(v)
                                   for k, v in watermark.items()}
        if memory:
            out["memory"] = memory
        return out
    except Exception:  # noqa: BLE001 — evidence, never a bench failure
        return {}


# -- config 1: MNIST smoke ---------------------------------------------------


def bench_mnist(steps: int = 30, batch: int = 256) -> Dict[str, Any]:
    """tf-cnn MNIST 1-worker parity: loss must fall while we time it."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models import MnistCnn
    from kubeflow_tpu.parallel import MeshConfig, create_mesh
    from kubeflow_tpu.train import (
        TrainState, create_sharded_state, make_image_train_step,
    )

    mesh = create_mesh(MeshConfig(dp=jax.device_count()))
    model = MnistCnn()
    rng = jax.random.key(0)
    # synthetic-but-learnable task: label = quadrant of the brightest pixel
    images = jax.random.uniform(rng, (batch, 28, 28, 1), jnp.float32)
    flat = images.reshape(batch, -1).argmax(axis=1)
    labels = ((flat // 28 // 14) * 2 + (flat % 28) // 14).astype(jnp.int32)

    def init_fn(rng):
        params = model.init(rng, images[:2])["params"]
        return TrainState.create(
            apply_fn=lambda v, x, train=True: model.apply(v, x),
            params=params, tx=optax.adam(1e-3))

    state, _ = create_sharded_state(init_fn, rng, mesh)
    step = make_image_train_step(mesh)
    state, first = step(state, images, labels)
    first_loss = float(first["loss"])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, images, labels)
    last_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0
    return {
        "images_per_sec": round(steps * batch / dt, 1),
        "first_loss": round(first_loss, 4),
        "last_loss": round(last_loss, 4),
        "learned": last_loss < first_loss,
    }


# -- config 2: ResNet-50 training (the headline) -----------------------------


def bench_resnet50(batch_per_chip: int = 256, steps: int = 20,
                   warmup: int = 5,
                   profile_dir: Optional[str] = None) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models.resnet import resnet50
    from kubeflow_tpu.parallel import MeshConfig, create_mesh
    from kubeflow_tpu.train import (
        TrainState, create_sharded_state, make_image_train_step,
    )

    n_chips = jax.device_count()
    mesh = create_mesh(MeshConfig(dp=n_chips))
    # KFTPU_RESNET_ACT_COMPRESS=1: int8 forward-saved conv inputs
    # (ops/act_compress.py) — the PERF.md bandwidth-lever A/B switch
    model = resnet50(
        num_classes=1000,
        act_compress=os.environ.get("KFTPU_RESNET_ACT_COMPRESS",
                                    "0") == "1",
        # KFTPU_RESNET_FUSED_BN=1: bn2+ReLU fused into conv3's GEMM
        # (ops/bnconv.py) — the PERF.md normalize-pass lever A/B switch
        fused_bn_conv=os.environ.get("KFTPU_RESNET_FUSED_BN",
                                     "0") == "1")
    stem = model.config.stem
    batch = batch_per_chip * n_chips
    rng = jax.random.key(0)
    images = jax.random.normal(rng, (batch, 224, 224, 3), jnp.bfloat16)
    labels = jax.random.randint(rng, (batch,), 0, 1000)
    # the reference workload trains with momentum SGD
    # (tf_cnn_benchmarks defaults); matching it also keeps the optimizer
    # update bandwidth-light next to adamw's two moment buffers
    tx = optax.sgd(0.1, momentum=0.9, nesterov=False)

    def init_fn(rng):
        variables = model.init(rng, images[:2], train=True)
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"],
            batch_stats=variables["batch_stats"], tx=tx)

    state, _ = create_sharded_state(init_fn, rng, mesh)
    step = make_image_train_step(mesh)

    holder = {"state": state}

    def one():
        holder["state"], holder["m"] = step(holder["state"], images, labels)

    sec = _timed_steps(one, steps, warmup,
                       sync=lambda: float(holder["m"]["loss"]))
    if profile_dir:
        _capture_trace(one, lambda: float(holder["m"]["loss"]), profile_dir)
    ips = batch / sec
    out = {
        "images_per_sec_per_chip": round(ips / n_chips, 2),
        "n_chips": n_chips,
        "batch_per_chip": batch_per_chip,
        "stem": stem,
        **_mfu(resnet50_train_flops_per_image(stem) * batch, sec, n_chips),
    }
    out.update(_roofline(step.jitted, mesh, sec,
                         holder["state"], images, labels))
    out.update(_step_telemetry_pass(
        one, lambda: float(holder["m"]["loss"]), step.jitted,
        n_steps=min(8, steps),
        flops_per_step=resnet50_train_flops_per_image(stem) * batch,
        n_chips=n_chips))
    return out


# -- config 3: BERT-base step time -------------------------------------------


def bench_bert(batch_per_chip: int = 16, seq_len: int = 512,
               steps: int = 10, warmup: int = 3,
               profile_dir: Optional[str] = None) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.bert import Bert, bert_base
    from kubeflow_tpu.parallel import MeshConfig, create_mesh
    from kubeflow_tpu.train import (
        TrainState, create_sharded_state, make_mlm_train_step, make_optimizer,
    )

    n_chips = jax.device_count()
    mesh = create_mesh(MeshConfig(dp=n_chips))
    cfg = bert_base()
    model = Bert(cfg)
    batch = batch_per_chip * n_chips
    rng = jax.random.key(0)
    tokens = jax.random.randint(rng, (batch, seq_len), 0, cfg.vocab_size)
    labels = jax.random.randint(jax.random.key(1), (batch, seq_len), 0,
                                cfg.vocab_size)
    weights = (jax.random.uniform(jax.random.key(2), (batch, seq_len))
               < 0.15).astype(jnp.float32)
    tx = make_optimizer(1e-4, warmup_steps=10, decay_steps=1000)

    def init_fn(rng):
        params = model.init(rng, tokens[:2])["params"]
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    state, _ = create_sharded_state(init_fn, rng, mesh)
    step = make_mlm_train_step(mesh)

    holder = {"state": state}

    def one():
        holder["state"], holder["m"] = step(holder["state"], tokens, labels,
                                            weights)

    # record every tile resolution the compile makes (attention_impl
    # "auto": flash + table on TPU, dense oracle elsewhere) so the
    # artifact row attributes a BERT MFU move to a table change
    from kubeflow_tpu.ops import autotune

    with autotune.record_resolutions() as tile_rec:
        sec = _timed_steps(one, steps, warmup,
                           sync=lambda: float(holder["m"]["loss"]))
    if profile_dir:
        _capture_trace(one, lambda: float(holder["m"]["loss"]), profile_dir)
    # analytic transformer train FLOPs: 6·N·D (N params, D tokens) plus the
    # attention score/value matmuls, 12·L·S²·d per token fwd+bwd
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(state.params))
    flops_per_step = (6 * n_params * batch * seq_len
                      + 12 * cfg.n_layers * batch * seq_len * seq_len
                      * cfg.d_model)
    return {
        "step_time_ms": round(sec * 1e3, 2),
        "tokens_per_sec_per_chip": round(batch * seq_len / sec / n_chips, 1),
        "n_chips": n_chips,
        "batch_per_chip": batch_per_chip,
        "seq_len": seq_len,
        # resolved tile configs + resolution source (table|fallback|
        # override); empty when the run took the dense XLA path (the
        # off-TPU "auto" oracle)
        "attention_impl": cfg.attention_impl,
        "tile_config": autotune.summarize_resolutions(tile_rec),
        **_mfu(flops_per_step, sec, n_chips),
        **_step_telemetry_pass(
            one, lambda: float(holder["m"]["loss"]), step.jitted,
            n_steps=min(8, steps), flops_per_step=flops_per_step,
            n_chips=n_chips),
    }


# -- long-context training (the capability the reference lacks) -------------


def bench_longcontext(seq_len: int = 8192, batch_per_chip: int = 2,
                      steps: int = 8, warmup: int = 2,
                      d_model: int = 1024, n_layers: int = 8,
                      n_heads: int = 16, d_ff: int = 4096,
                      loss_chunk: Optional[int] = None,
                      profile_dir: Optional[str] = None) -> Dict[str, Any]:
    """Long-sequence LM training throughput with the Pallas flash-attention
    path — the long-context capability SURVEY §5 names as first-class (the
    reference's training stack has no sequence-parallel/long-context story
    at all). On one chip this exercises the flash kernel + remat; the
    sequence-parallel ring path over tp is covered by the virtual-mesh
    tier (tests/test_ops.py) and the multichip dryrun."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Transformer, TransformerConfig
    from kubeflow_tpu.parallel import MeshConfig, create_mesh
    from kubeflow_tpu.train import (
        TrainState, create_sharded_state, make_lm_train_step, make_optimizer,
    )

    n_chips = jax.device_count()
    mesh = create_mesh(MeshConfig(dp=n_chips))
    config = TransformerConfig(
        vocab_size=32000, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, n_kv_heads=n_heads, d_ff=d_ff,
        max_seq_len=seq_len, attention_impl="flash", remat=True,
    )
    # past 16k the full (B, S, V) f32 logit tensor alone approaches HBM
    # capacity — the chunked-loss path (hidden states out, vocab
    # projection per chunk) is what makes those contexts trainable
    if loss_chunk is None and seq_len > 16384:
        loss_chunk = 4096
    model = Transformer(config, return_hidden=bool(loss_chunk))
    batch = batch_per_chip * n_chips
    tokens = jax.random.randint(jax.random.key(0), (batch, seq_len), 0,
                                config.vocab_size)
    tx = make_optimizer(3e-4, warmup_steps=5, decay_steps=100)

    def init_fn(rng):
        # init over a 2-example slice: param shapes don't depend on batch,
        # and a full-batch init would execute a whole extra forward
        params = model.init(rng, tokens[:2])["params"]
        return TrainState.create(apply_fn=model.apply, params=params, tx=tx)

    state, _ = create_sharded_state(init_fn, jax.random.key(0), mesh)
    step = make_lm_train_step(mesh, loss_chunk=loss_chunk,
                              logits_softcap=config.logits_softcap)
    holder = {"state": state}

    def one():
        holder["state"], holder["m"] = step(holder["state"], tokens)

    # the flash tiles this run compiled with, and where they resolved
    # from (tile_config in the row): an A/B round can attribute a
    # tok/s move to a tile_table.json change instead of guessing
    from kubeflow_tpu.ops import autotune

    with autotune.record_resolutions() as tile_rec:
        sec = _timed_steps(one, steps, warmup,
                           sync=lambda: float(holder["m"]["loss"]))
    if profile_dir:
        _capture_trace(one, lambda: float(holder["m"]["loss"]), profile_dir)
    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(holder["state"].params))
    # 6·N·D plus causal attention matmuls (12·L·S²·d per token, halved for
    # causality) — remat recompute is excluded per the MFU convention
    flops_per_step = (6 * n_params * batch * seq_len
                      + 6 * config.n_layers * batch * seq_len * seq_len
                      * config.d_model)
    return {
        "tokens_per_sec_per_chip": round(batch * seq_len / sec / n_chips, 1),
        "step_time_ms": round(sec * 1e3, 2),
        "seq_len": seq_len,
        "batch_per_chip": batch_per_chip,
        "attention": "flash(pallas)+remat",
        "tile_config": autotune.summarize_resolutions(tile_rec),
        "loss": f"chunked({loss_chunk})" if loss_chunk else "full_logits",
        "n_chips": n_chips,
        **_mfu(flops_per_step, sec, n_chips),
    }


# -- config 4: allreduce microbench ------------------------------------------


def bench_allreduce(size_mb: float = 64.0, iters: int = 10) -> Dict[str, Any]:
    import jax

    from kubeflow_tpu.ops.collectives import bench_collective
    from kubeflow_tpu.parallel import MeshConfig, create_mesh

    n = jax.device_count()
    if n < 2:
        # a 1-chip allreduce is the identity. Still record the 8-device
        # virtual CPU mesh number (subprocess — the parent is pinned to the
        # TPU platform) so regressions in the collective path stay visible
        # round-over-round even on 1-chip hardware.
        out: Dict[str, Any] = {"n_chips": n, "skipped": "needs >=2 chips"}
        virt = _virtual_mesh_allreduce(size_mb=8.0, iters=iters)
        if virt is not None:
            out["virtual_cpu_mesh"] = virt
        return out
    mesh = create_mesh(MeshConfig(dp=n))
    res = bench_collective("all_reduce", mesh, "dp", size_mb=size_mb,
                           iters=iters)
    return {
        "bus_gb_per_sec": round(res.bus_gb_s, 2),
        "payload_mb": round(res.size_mb, 1),
        "mean_ms": round(res.mean_s * 1e3, 3),
        "n_chips": n,
    }


def _virtual_mesh_allreduce(*, size_mb: float, iters: int,
                            n_devices: int = 8) -> Optional[Dict[str, Any]]:
    """AllReduce bus bandwidth over an 8-device virtual CPU mesh, measured
    in a subprocess (the parent interpreter is already pinned to its
    platform). Tracks the collective *code path*, not hardware speed.
    Returns None (with a logged warning) when the subprocess fails, so the
    published key always has the success shape."""
    import logging
    import subprocess
    import sys

    prog = (
        "import os, json\n"
        f"os.environ['XLA_FLAGS'] = "
        f"'--xla_force_host_platform_device_count={n_devices}'\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from kubeflow_tpu.ops.collectives import bench_collective\n"
        "from kubeflow_tpu.parallel import MeshConfig, create_mesh\n"
        f"mesh = create_mesh(MeshConfig(dp={n_devices}))\n"
        f"r = bench_collective('all_reduce', mesh, 'dp', "
        f"size_mb={size_mb}, iters={iters})\n"
        "print(json.dumps({'bus_gb_per_sec': round(r.bus_gb_s, 2), "
        "'payload_mb': round(r.size_mb, 1), "
        "'mean_ms': round(r.mean_s * 1e3, 3), "
        f"'n_devices': {n_devices}}}))\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", prog], capture_output=True, text=True,
            timeout=300, cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        if proc.returncode:
            logging.getLogger(__name__).warning(
                "virtual-mesh allreduce failed: %s",
                proc.stderr.strip()[-300:])
            return None
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, OSError, ValueError) as e:
        logging.getLogger(__name__).warning(
            "virtual-mesh allreduce failed: %s: %s", type(e).__name__, e)
        return None


def bench_decode(batch: int = 8, prompt_len: int = 128,
                 new_tokens: int = 128, d_model: int = 1024,
                 n_layers: int = 8, n_heads: int = 16,
                 d_ff: int = 4096,
                 profile_dir: Optional[str] = None) -> Dict[str, Any]:
    """Autoregressive generation throughput (KV-cache decode loop).

    The LLM-serving hot path the reference has no story for: prefill +
    ``lax.scan`` over single-token steps, all one compiled program
    (``kubeflow_tpu/models/decode.py``). Decode is memory-bound (every
    step reads all params + the KV cache), so the roofline here is
    HBM bytes/token, not FLOPs."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Transformer, TransformerConfig
    from kubeflow_tpu.models.decode import make_generate

    n_chips = jax.device_count()
    config = TransformerConfig(
        vocab_size=32000, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, n_kv_heads=n_heads, d_ff=d_ff,
        max_seq_len=prompt_len + new_tokens, remat=False)
    model = Transformer(config)
    prompt = jax.random.randint(jax.random.key(0), (batch, prompt_len), 0,
                                config.vocab_size)
    params = jax.jit(model.init)(jax.random.key(1), prompt[:2])["params"]

    fn = make_generate(config, max_new_tokens=new_tokens)
    true_len = jnp.int32(prompt_len)
    rng = jax.random.key(2)

    out = fn(params, prompt, true_len, rng)  # compile
    _ = np.asarray(out)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = fn(params, prompt, true_len, rng)
    _ = np.asarray(out)
    dt = (time.perf_counter() - t0) / reps
    if profile_dir:
        holder: Dict[str, Any] = {}

        def one():
            holder["out"] = fn(params, prompt, true_len, rng)

        _capture_trace(one, lambda: np.asarray(holder["out"]),
                       profile_dir, n_steps=1)

    n_params = sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(params))
    # per decoded token the chip reads every param (bf16) once — the
    # memory-bound roofline for batch-small decode
    total_new = batch * new_tokens
    return {
        "tokens_per_sec_per_chip": round(total_new / dt / n_chips, 1),
        "ms_per_token": round(dt / new_tokens * 1e3, 3),
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "n_params_m": round(n_params / 1e6, 1),
        "n_chips": n_chips,
    }


def engine_bench_setup(concurrency: int = 48, prompt_len: int = 128,
                       new_tokens: int = 128, d_model: int = 1024,
                       n_layers: int = 8, n_heads: int = 16,
                       d_ff: int = 4096):
    """The decode-engine bench workload: (config, params, prompts).
    Shared with ``scripts/sync_sweep.py`` so sweeps measure exactly the
    bench's shapes."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import Transformer, TransformerConfig

    config = TransformerConfig(
        vocab_size=32000, d_model=d_model, n_layers=n_layers,
        n_heads=n_heads, n_kv_heads=n_heads, d_ff=d_ff,
        max_seq_len=prompt_len + new_tokens, remat=False)
    model = Transformer(config)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, config.vocab_size,
                           (concurrency, prompt_len), dtype=np.int32)
    params = jax.jit(model.init)(
        jax.random.key(1),
        jnp.asarray(prompts[:2]))["params"]
    return config, params, prompts


def engine_drain(eng) -> None:
    while eng.active_count or eng.pending_count:
        eng.run_once(timeout=0.01)


def ledger_burst_ttft_ms(ledger, wave) -> Optional[float]:
    """Burst TTFT off the request ledger — production's definition
    (docs/OBSERVABILITY.md "Request lifecycle"), replacing the bench's
    old hand-rolled first-wave stamp: wall from the burst's first
    submit until EVERY wave member held its first token (each record's
    submit + ttft). None (JSON null) when a wave member never produced
    one — total run time masquerading as TTFT would poison any A/B
    read of this number."""
    ttfts = [ledger.ttft_ms(r.rid) for r in wave]
    if not wave or any(f is None for f in ttfts):
        return None
    first_all = (max(r.t_submit + f / 1e3 for r, f in zip(wave, ttfts))
                 - min(r.t_submit for r in wave))
    return round(first_all * 1e3, 1)


def engine_throughput(config, params, prompts, *, slots: int,
                      steps_per_sync: int, new_tokens: int,
                      sampler_bound: Optional[int], sampled: bool,
                      sample_kw: Optional[Dict[str, Any]] = None,
                      sampler_impl: Optional[str] = None,
                      paged: bool = False,
                      paged_attention_impl: Optional[str] = None,
                      request_ledger=None,
                      name: str = "bench"):
    """tokens/sec through a fresh engine (params shared in HBM).
    Returns (tok/s/chip, engine steps, burst TTFT ms, batch prefills).
    ``request_ledger`` (a fresh one per run by default, so bench bursts
    never mix into the process ledger) also hands the caller the
    per-request phase breakdown via its ``bench_block()``."""
    import jax

    from kubeflow_tpu.obs import requests as reqobs
    from kubeflow_tpu.serving.engine import DecodeEngine

    n_chips = jax.device_count()
    if request_ledger is None:
        request_ledger = reqobs.RequestLedger()
    eng = DecodeEngine(config, params, slots=slots,
                       steps_per_sync=steps_per_sync,
                       sampler_bound=sampler_bound,
                       sampler_impl=sampler_impl, paged=paged,
                       paged_attention_impl=paged_attention_impl,
                       autostart=False, name=name,
                       request_ledger=request_ledger)

    # warm the compiled programs: the row prefill, insert, step —
    # and every batch-prefill bucket burst admission can hit (a
    # first-shape compile inside the timed window would be measured
    # as serving time)
    kw = dict(sample_kw) if sampled and sample_kw else {}
    n = 1
    while True:
        warms = [eng.submit(prompts[i % len(prompts)],
                            max_new=steps_per_sync + 1, **kw)
                 for i in range(n)]
        engine_drain(eng)
        for w in warms:
            list(w.stream())
        if n >= min(eng.admit_batch_max, slots):
            break
        n *= 2

    steps0, bp0 = eng.steps_total, eng.batch_prefills
    t0 = time.perf_counter()
    reqs = [eng.submit(p, max_new=new_tokens, seed=i, **kw)
            for i, p in enumerate(prompts)]
    wave = reqs[:slots]
    if paged:
        # chunked prefill interleaves admissions with decode: burst
        # TTFT is the wall time until EVERY wave member has its first
        # token (decode of earlier admits proceeds meanwhile)
        for _ in range(10000):
            eng.run_once(timeout=0.01)
            if all(r._seen or r.out.qsize() for r in wave):
                break
    else:
        # burst TTFT: admit the first wave explicitly (one _admit pass
        # fills every free slot, and each request's first token is
        # emitted during its prefill sample) — the number batched
        # admission improves
        eng._admit(0.01)
    engine_drain(eng)
    total = sum(len(r.result()) for r in reqs)
    dt = time.perf_counter() - t0
    ttft = ledger_burst_ttft_ms(eng.rledger, wave)
    return (round(total / dt / n_chips, 1),
            eng.steps_total - steps0, ttft,
            eng.batch_prefills - bp0)


def engine_prefix_counters(config, params, prompts, *, slots: int,
                           steps_per_sync: int, new_tokens: int,
                           name: str = "bench-prefix") -> Dict[str, Any]:
    """Prefix-trie / copy-on-write effectiveness under a shared-system-
    prompt workload: every request carries the same prefix, chosen one
    token PAST a page boundary so full pages trie-share and the partial
    boundary page exercises a COW split per hit. Returns the counters
    ``engine.snapshot()`` surfaces (docs/OBSERVABILITY.md) plus the
    derived hit rate — the numbers that adjudicate page-granular
    matching against the old exact-prefix store."""
    from kubeflow_tpu.serving.engine import DecodeEngine

    eng = DecodeEngine(config, params, slots=slots,
                       steps_per_sync=steps_per_sync, paged=True,
                       autostart=False, name=name)
    prompt_len = prompts.shape[1]
    # one full page + one boundary token when prompt_len = 2 pages
    prefix_len = min(eng.kv_page_size + 1, prompt_len - 1)
    shared = np.concatenate(
        [np.broadcast_to(prompts[0, :prefix_len],
                         (len(prompts), prefix_len)),
         prompts[:, prefix_len:]], axis=1)
    # warm the trie with the first request alone (a burst placed before
    # the first prefill completes would miss by timing, not by policy —
    # the store pins pages at prefill completion), then burst the rest:
    # every follower should page-share and COW-split
    first = eng.submit(shared[0], max_new=new_tokens,
                       prefix_len=prefix_len)
    engine_drain(eng)
    first.result()
    reqs = [eng.submit(p, max_new=new_tokens, prefix_len=prefix_len)
            for p in shared[1:]]
    engine_drain(eng)
    for r in reqs:
        r.result()
    total = max(1, eng.prefix_hits + eng.prefix_misses)
    counters = {
        "paged_prefix_hits": eng.prefix_hits,
        "paged_prefix_misses": eng.prefix_misses,
        "paged_prefix_hit_rate": round(eng.prefix_hits / total, 3),
        "paged_prefix_pages_shared": eng.prefix_pages_shared,
        "paged_cow_splits": eng.cow_splits,
        "paged_prefix_len": prefix_len,
    }
    eng.close()
    return counters


def bench_decode_engine(concurrency: int = 48, slots: int = 32,
                        prompt_len: int = 128, new_tokens: int = 128,
                        steps_per_sync: int = 64, d_model: int = 1024,
                        n_layers: int = 8, n_heads: int = 16,
                        d_ff: int = 4096,
                        profile_dir: Optional[str] = None
                        ) -> Dict[str, Any]:
    """Continuous-batching serving throughput: ``concurrency`` generate
    requests share the DecodeEngine's ``slots``-row decode batch
    (``kubeflow_tpu/serving/engine.py``) — the production :generate
    path. Decode is HBM-bound per step, so throughput scales with
    effective batch until cache traffic dominates; this measures the
    engine at effective batch = ``slots`` (vs ``bench_decode``'s fixed
    whole-request batch), including prefill, admission, and sampling
    overheads — the number a capacity planner uses. ``steps_per_sync``
    defaults to the r5 sweep's measured optimum (PERF.md, 64 — the
    throughput configuration; serving's latency-bound default lives in
    the manifest)."""
    import jax

    from kubeflow_tpu.serving.engine import DecodeEngine

    n_chips = jax.device_count()
    config, params, prompts = engine_bench_setup(
        concurrency, prompt_len, new_tokens, d_model, n_layers,
        n_heads, d_ff)

    sample_kw = {"temperature": 0.8, "top_k": 40, "top_p": 0.95}

    def run_engine(sampler_bound: Optional[int], sampled: bool,
                   sampler_impl: Optional[str] = None,
                   paged: bool = False,
                   paged_attention_impl: Optional[str] = None,
                   request_ledger=None):
        return engine_throughput(
            config, params, prompts, slots=slots,
            steps_per_sync=steps_per_sync, new_tokens=new_tokens,
            sampler_bound=sampler_bound, sampled=sampled,
            sample_kw=sample_kw, sampler_impl=sampler_impl, paged=paged,
            paged_attention_impl=paged_attention_impl,
            request_ledger=request_ledger)

    # sampler modes at the same effective batch: greedy rides the
    # argmax fast-path step; "sampled" pays the per-row sampler. The
    # BENCH_r05 lever was bounded-vs-exact-sort (~2.4× tax for correct
    # sampling at slots=32); the fused Pallas kernel
    # (ops/sampling.py) is the exact path that must close that gap.
    bound = int(os.environ.get("KFTPU_SAMPLER_BOUND", "64"))
    # the headline greedy run keeps its request ledger: the artifact's
    # "requests" block is its per-phase breakdown (docs/OBSERVABILITY.md
    # "Request lifecycle")
    from kubeflow_tpu.obs import requests as reqobs

    req_ledger = reqobs.RequestLedger()
    greedy_tps, engine_steps, ttft_ms, batch_prefills = run_engine(
        bound, sampled=False, request_ledger=req_ledger)
    sampled_bounded_tps, _, _, _ = run_engine(bound, sampled=True)
    sampled_exact_tps, _, _, _ = run_engine(
        0, sampled=True, sampler_impl="exact_sort")
    sampled_fused_tps, _, _, _ = run_engine(
        0, sampled=True, sampler_impl="fused")
    # paged-vs-dense: same greedy workload through the paged KV cache
    # + chunked-prefill admission (burst TTFT is the headline there —
    # whole-prompt prefills no longer block the decode loop). The
    # gather-vs-kernel A/B adjudicates the Pallas paged-attention
    # kernel (ops/paged_attention.py): same workload, decode-step
    # attention reads the dense logical view vs streaming live pages
    # through the page table. On the CPU tier the kernel runs in the
    # Pallas interpreter — its wall-clock there proves the path
    # executes, never a perf claim; the TPU-attached round reads it.
    paged_gather_tps, _, paged_gather_ttft, _ = run_engine(
        bound, sampled=False, paged=True, paged_attention_impl="gather")
    # the kernel run is the tuned one: record its tile resolution
    # (paged_attn head_block + source) so the artifact attributes a
    # kernel-row move to a tile-table change
    from kubeflow_tpu.ops import autotune

    with autotune.record_resolutions() as paged_tile_rec:
        paged_kernel_tps, _, paged_kernel_ttft, _ = run_engine(
            bound, sampled=False, paged=True,
            paged_attention_impl="kernel")
    # "auto" resolves to the kernel on the TPU backend and the gather
    # elsewhere — the headline paged rows reuse the matching A/B run
    # instead of paying a third paged engine pass
    auto_kernel = jax.default_backend() == "tpu"
    paged_tps = paged_kernel_tps if auto_kernel else paged_gather_tps
    paged_ttft_ms = (paged_kernel_ttft if auto_kernel
                     else paged_gather_ttft)
    prefix_counters = engine_prefix_counters(
        config, params, prompts, slots=slots,
        steps_per_sync=steps_per_sync, new_tokens=new_tokens)
    if profile_dir:
        # trace a short greedy engine run. jit caches are per engine
        # instance, so this engine precompiles its step programs and
        # serves one warm request first — the captured trace is decode
        # steps, not XLA compiles. Nothing is consumed after the
        # capture: _capture_trace swallows profiler failures by design,
        # and a blocking read on a maybe-undrained request could hang
        # the bench after all measurements already succeeded.
        eng = DecodeEngine(config, params, slots=slots,
                           steps_per_sync=steps_per_sync,
                           sampler_bound=bound, precompile=True,
                           autostart=False, name="bench-trace")
        warm = eng.submit(prompts[0], max_new=steps_per_sync + 1)
        engine_drain(eng)
        list(warm.stream())
        eng.submit(prompts[0], max_new=min(new_tokens,
                                           4 * steps_per_sync))
        _capture_trace(lambda: engine_drain(eng), lambda: None, profile_dir,
                       n_steps=1)
    return {
        "tokens_per_sec_per_chip": greedy_tps,
        "sampled_bounded_tokens_per_sec_per_chip": sampled_bounded_tps,
        "sampled_exact_sort_tokens_per_sec_per_chip": sampled_exact_tps,
        "sampled_exact_fused_tokens_per_sec_per_chip": sampled_fused_tps,
        "paged_tokens_per_sec_per_chip": paged_tps,
        "paged_burst_first_tokens_ms": paged_ttft_ms,
        "paged_attn_gather_tokens_per_sec_per_chip": paged_gather_tps,
        "paged_attn_kernel_tokens_per_sec_per_chip": paged_kernel_tps,
        "paged_attn_kernel_vs_gather": (
            round(paged_kernel_tps / paged_gather_tps, 3)
            if paged_gather_tps else None),
        "tile_config": autotune.summarize_resolutions(paged_tile_rec),
        **prefix_counters,
        "requests": req_ledger.bench_block(),
        "burst_first_tokens_ms": ttft_ms,
        "batch_prefills": batch_prefills,
        "sampler_bound": bound,
        "sampled_params": sample_kw,
        "effective_batch": slots,
        "concurrency": concurrency,
        "steps_per_sync": steps_per_sync,
        "new_tokens": new_tokens,
        "prompt_len": prompt_len,
        "engine_steps": engine_steps,
        "n_chips": n_chips,
    }


# -- config 5: serving latency/QPS -------------------------------------------


def bench_serving(requests: int = 200, batch: int = 8,
                  image_size: int = 224,
                  rest_requests: int = 30) -> Dict[str, Any]:
    """Predict p50/p99 + QPS through BOTH serving surfaces.

    Primary numbers are the gRPC :9000 binary-tensor path — the reference
    model server's primary surface (``/root/reference/kubeflow/tf-serving/
    tf-serving-template.libsonnet:33-48``) and the one a production client
    uses. The REST JSON path (``rest_*`` keys, fewer iterations — the
    batch-8 224² request is ~24 MB of ASCII floats) is measured separately
    so the JSON encode/decode overhead is itself visible rather than
    masquerading as model latency."""
    import tempfile
    import urllib.request

    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.resnet import ResNet, ResNetConfig
    from kubeflow_tpu.serving import ModelServer, export_model
    from kubeflow_tpu.serving.grpc_server import PredictClient, serve_grpc

    # serving-size ResNet-50; fp32 params exported, bf16 compute.
    # init under jit: eager init would execute every op individually over
    # the device transport (minutes on a remote chip) instead of one
    # compiled program
    cfg = ResNetConfig(stage_sizes=(3, 4, 6, 3), num_classes=1000)
    model = ResNet(cfg)
    rng = jax.random.key(0)
    x0 = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    variables = jax.jit(
        lambda r: model.init(r, x0, train=False))(rng)

    def timed(fn, n):
        lat = []
        t0 = time.perf_counter()
        for _ in range(n):
            t = time.perf_counter()
            fn()
            lat.append(time.perf_counter() - t)
        wall = time.perf_counter() - t0
        ms = np.array(lat) * 1e3
        return (round(float(np.percentile(ms, 50)), 2),
                round(float(np.percentile(ms, 99)), 2), wall)

    with tempfile.TemporaryDirectory() as d:
        export_model(
            os.path.join(d, "resnet"), "resnet",
            {"params": variables["params"],
             "batch_stats": variables["batch_stats"]},
            version=1,
            config={"stage_sizes": list(cfg.stage_sizes),
                    "num_classes": cfg.num_classes,
                    "stem": cfg.stem},
            input_shape=(image_size, image_size, 3))
        server = grpc_server = client = None
        try:
            server = ModelServer(d, port=0, max_batch_size=batch,
                                 poll_interval_s=3600)
            port = server.start()
            grpc_server, grpc_port = serve_grpc(server.repo, port=0,
                                                max_batch_size=batch)
            client = PredictClient(f"127.0.0.1:{grpc_port}")
            # seeded: bench inputs must be identical run to run, or
            # latency deltas between rounds also carry a data delta
            images = np.random.default_rng(0).random(
                (batch, image_size, image_size, 3), dtype=np.float32)

            client.predict("resnet", images)  # compile
            grpc_p50, grpc_p99, grpc_wall = timed(
                lambda: client.predict("resnet", images), requests)

            # uint8 pixels (the image-client convention): 4× less wire
            # bytes; the server casts to f32 before predict
            images_u8 = (images * 255).astype(np.uint8)
            client.predict("resnet", images_u8)
            u8_p50, u8_p99, u8_wall = timed(
                lambda: client.predict("resnet", images_u8), requests)

            url = f"http://127.0.0.1:{port}/v1/models/resnet:predict"
            payload = json.dumps({"instances": images.tolist()}).encode()

            def rest_predict():
                req = urllib.request.Request(
                    url, data=payload,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as resp:
                    json.loads(resp.read())

            rest_predict()  # warm
            rest_p50, rest_p99, rest_wall = timed(rest_predict, rest_requests)
        finally:
            if client is not None:
                client.close()
            if grpc_server is not None:
                grpc_server.stop(grace=0)
            if server is not None:
                server.stop()

    n_chips = jax.device_count()
    return {
        "p50_ms": grpc_p50,
        "p99_ms": grpc_p99,
        "qps_per_chip": round(requests * batch / grpc_wall / n_chips, 1),
        "transport": "grpc",
        "uint8_p50_ms": u8_p50,
        "uint8_p99_ms": u8_p99,
        "uint8_qps_per_chip": round(
            requests * batch / u8_wall / n_chips, 1),
        "rest_p50_ms": rest_p50,
        "rest_p99_ms": rest_p99,
        "rest_qps_per_chip": round(
            rest_requests * batch / rest_wall / n_chips, 1),
        "batch": batch,
        "n_chips": n_chips,
    }


# -- runner ------------------------------------------------------------------

def bench_edge_fleet(replicas: int = 3, prefixes: int = 4,
                     repeats: int = 16, page_size: int = 16,
                     burst: int = 48) -> Dict[str, Any]:
    """Fleet-edge routing quality + multiplex cold start (docs/EDGE.md).

    Host-side control-plane numbers (routing, shedding, weight paging
    are CPU work wherever the chips are), adjudicable every round:

    - ``edge_affinity_hit_rate`` vs ``edge_round_robin_hit_rate``:
      fleet prefix-trie hit rate for the SAME repeated-prefix stream
      under both policies — the routing win as one number;
    - ``edge_shed_fraction``: fraction of a 2x-capacity burst shed at
      overload pressure (the shed-before-collapse knee);
    - ``multiplex_cold_start_ms``: wall time to fault a real exported
      model's weights from a versioned store (the "cold-start ms, not
      s" ROADMAP bar).
    """
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.edge.fleet import (
        FleetEdge,
        FleetRequest,
        FleetRouter,
        ReplicaSim,
        SloAdmissionGate,
        fleet_prefix_hits,
        sim_dispatch,
    )
    from kubeflow_tpu.models import MnistCnn
    from kubeflow_tpu.serving.model_store import export_model
    from kubeflow_tpu.serving.multiplex import ModelMultiplexer

    rng = np.random.default_rng(11)
    stream = []
    for p in range(prefixes):
        prefix = np.arange(1000 * p, 1000 * p + 3 * page_size,
                           dtype=np.int32)
        for _ in range(repeats):
            suffix = rng.integers(50000, 60000, size=page_size // 2)
            stream.append((np.concatenate([prefix, suffix])
                           .astype(np.int32), int(prefix.size)))

    def hit_rate(policy: str) -> float:
        sims = {f"r{i}": ReplicaSim(f"r{i}", page_size=page_size)
                for i in range(replicas)}
        router = FleetRouter(page_size=page_size, policy=policy)
        router.sync({n: f"http://{n}" for n in sims})
        edge = FleetEdge(router, SloAdmissionGate(),
                         dispatch=sim_dispatch(sims))
        for prompt, prefix_len in stream:
            code, _ = edge.handle(FleetRequest(prompt=prompt,
                                               prefix_len=prefix_len))
            assert code == 200
        return fleet_prefix_hits(sims) / len(stream)

    affinity_rate = hit_rate("affinity")
    rr_rate = hit_rate("round_robin")

    # overload burst: every replica at near-exhausted pages
    sims = {f"r{i}": ReplicaSim(f"r{i}", page_size=page_size)
            for i in range(replicas)}
    router = FleetRouter(page_size=page_size)
    router.sync({n: f"http://{n}" for n in sims})
    gate = SloAdmissionGate()
    edge = FleetEdge(router, gate, dispatch=sim_dispatch(sims))
    for n in sims:
        gate.observe_snapshot(n, {"pages_total": 100, "pages_free": 5,
                                  "slots": 4, "pending": 0})
    classes = ("interactive", "standard", "batch")
    shed = 0
    for i in range(burst):
        code, _ = edge.handle(FleetRequest(
            prompt=np.arange(2 * page_size),
            headers={"X-Kftpu-Slo-Class": classes[i % len(classes)]}))
        shed += code == 503

    # multiplex cold start against a real store artifact
    model = MnistCnn()
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 28, 28, 1)))["params"]
    with tempfile.TemporaryDirectory() as store_root:
        export_model(os.path.join(store_root, "m0"), "mnist", params,
                     version=1)
        export_model(os.path.join(store_root, "m1"), "mnist", params,
                     version=1)
        mux = ModelMultiplexer(store_root, max_resident=1)
        mux.get("m0")
        mux.get("m1")            # pages m0 out
        cold = mux.get("m0")     # a real re-fault from disk
        assert cold.kind == "mnist"
        snap = mux.snapshot()
        cold_ms = snap["models"]["m0"]["cold_start_ms"]

    return {
        "edge_affinity_hit_rate": round(affinity_rate, 4),
        "edge_round_robin_hit_rate": round(rr_rate, 4),
        "edge_shed_fraction": round(shed / burst, 4),
        "multiplex_cold_start_ms": round(cold_ms, 3),
        "multiplex_loads": snap["multiplex_loads"],
        "replicas": replicas,
        "requests": len(stream),
        "burst": burst,
    }


CONFIGS: Dict[str, Callable[[], Dict[str, Any]]] = {
    "mnist": bench_mnist,
    "resnet50": bench_resnet50,
    "bert": bench_bert,
    "longcontext": bench_longcontext,
    "allreduce": bench_allreduce,
    "serving": bench_serving,
    "decode": bench_decode,
    "decode_engine": bench_decode_engine,
    "edge_fleet": bench_edge_fleet,
}


_PROFILABLE = ("resnet50", "bert", "longcontext", "decode",
               "decode_engine")


def run_all(only: Optional[list] = None,
            profile_dir: Optional[str] = None) -> Dict[str, Dict[str, Any]]:
    """Run every config; one failing config must not kill the rest.

    ``profile_dir`` captures an XLA trace of the training hot loops into
    ``<profile_dir>/<config>/`` (after timing, so capture overhead never
    contaminates the numbers)."""
    out: Dict[str, Dict[str, Any]] = {}
    for name, fn in CONFIGS.items():
        if only and name not in only:
            continue
        try:
            if profile_dir and name in _PROFILABLE:
                out[name] = fn(profile_dir=os.path.join(profile_dir, name))
                out[name]["trace_dir"] = os.path.join(profile_dir, name)
            else:
                out[name] = fn()
            import jax

            # the artifact must say what actually ran the numbers
            out[name].setdefault("platform", jax.default_backend())
        except Exception as e:  # noqa: BLE001
            out[name] = {"error": f"{type(e).__name__}: {e}"}
    return out


def run_all_isolated(only: Optional[list] = None,
                     profile_dir: Optional[str] = None,
                     timeout_s: Optional[float] = None,
                     probe_retries: Optional[int] = None,
                     probe_wait_s: Optional[float] = None,
                     ) -> Dict[str, Dict[str, Any]]:
    """run_all with each config in its OWN subprocess under a hard
    timeout.

    A wedged device transport (observed: a killed client can leave the
    remote chip tunnel blocking every subsequent device op indefinitely)
    would otherwise hang the whole bench run without emitting the one
    JSON line the driver records; a subprocess can always be killed.
    Timeout default: ``KFTPU_BENCH_TIMEOUT_S`` (900)."""
    import subprocess
    import sys

    if timeout_s is None:
        timeout_s = float(os.environ.get("KFTPU_BENCH_TIMEOUT_S", "900"))
    out: Dict[str, Dict[str, Any]] = {}
    names = [n for n in CONFIGS if not only or n in only]
    # pre-flight: a transport wedged by an EARLIER session would burn the
    # first config's full timeout before the in-loop bailout triggers.
    # The probe retries with spacing — an outage that clears while the
    # bench harness is being invoked should not void the round's numbers
    # (KFTPU_BENCH_PROBE_RETRIES probes, KFTPU_BENCH_PROBE_WAIT_S apart).
    if names:
        if probe_retries is None:
            probe_retries = int(
                os.environ.get("KFTPU_BENCH_PROBE_RETRIES", "3"))
        if probe_wait_s is None:
            probe_wait_s = float(
                os.environ.get("KFTPU_BENCH_PROBE_WAIT_S", "90"))
        probe_retries = max(probe_retries, 1)
        alive = False
        for attempt in range(probe_retries):
            if _device_alive():
                alive = True
                break
            if attempt + 1 < probe_retries:
                time.sleep(probe_wait_s)
        if not alive:
            # error_kind is the STRUCTURED classification bench.py keys
            # its exit code on — the free-text error is for humans and
            # may be reworded freely
            return {name: {"error": "skipped: device transport "
                                    "unreachable at bench start "
                                    f"({probe_retries} probes)",
                           "error_kind": "transport_unreachable"}
                    for name in names}
    for i, name in enumerate(names):
        args = [name]
        if profile_dir and name in _PROFILABLE:
            args += ["--profile", profile_dir]
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "kubeflow_tpu.bench.suite", *args],
                capture_output=True, text=True, timeout=timeout_s,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__)))))
        except subprocess.TimeoutExpired:
            out[name] = {"error": f"timeout after {timeout_s:.0f}s "
                                  "(device transport hung?)",
                         "error_kind": "transport_timeout"}
            # killing a client mid-device-op can wedge the transport for
            # everyone after (see .claude/skills/verify gotchas): probe
            # before burning the full timeout on each remaining config
            if not _device_alive():
                for rest in names[i + 1:]:
                    out[rest] = {"error": "skipped: device transport "
                                          "wedged after timeout",
                                 "error_kind": "transport_wedged"}
                break
            continue
        except OSError as e:
            out[name] = {"error": f"{type(e).__name__}: {e}"}
            continue
        try:
            payload = json.loads(proc.stdout.strip().splitlines()[-1])
            out[name] = payload.get(name, payload)
        except (ValueError, IndexError):
            out[name] = {"error": (proc.stderr.strip() or "no output")
                         [-300:]}
    return out


# Tiny-shape arguments for the always-on CPU smoke tier: every config
# must EXECUTE end-to-end on the host backend each round, so an
# accelerator outage can never reduce the bench artifact to zero
# evidence (an all-skip BENCH_r*.json is indistinguishable from "the
# suite itself is broken"). These rows are correctness proofs, never
# performance claims — the shapes are deliberately minimal.
_CPU_SMOKE_ARGS: Dict[str, Dict[str, Any]] = {
    "mnist": {"steps": 3, "batch": 32},
    "resnet50": {"batch_per_chip": 2, "steps": 2, "warmup": 1},
    "bert": {"batch_per_chip": 1, "seq_len": 128, "steps": 2, "warmup": 1},
    "longcontext": {"seq_len": 512, "batch_per_chip": 1, "steps": 2,
                    "warmup": 1, "d_model": 256, "n_layers": 2,
                    "n_heads": 4, "d_ff": 512},
    "allreduce": {"size_mb": 1.0, "iters": 3},
    "serving": {"requests": 5, "batch": 2, "image_size": 64,
                "rest_requests": 3},
    "decode": {"batch": 2, "prompt_len": 16, "new_tokens": 8,
               "d_model": 128, "n_layers": 2, "n_heads": 4, "d_ff": 256},
    "decode_engine": {"concurrency": 6, "slots": 4, "prompt_len": 16,
                      "new_tokens": 8, "steps_per_sync": 2,
                      "d_model": 128, "n_layers": 2, "n_heads": 4,
                      "d_ff": 256},
    "edge_fleet": {"replicas": 3, "prefixes": 2, "repeats": 4,
                   "page_size": 4, "burst": 12},
}


def run_cpu_smoke(only: Optional[list] = None,
                  timeout_s: Optional[float] = None,
                  ) -> Dict[str, Dict[str, Any]]:
    """Every config at tiny shapes on the host CPU backend, each in its
    own subprocess (the parent may be pinned to a device platform; the
    child repins with ``jax.config.update('jax_platforms', 'cpu')``).

    Rows carry ``tier: "cpu"`` so the driver's artifact distinguishes
    them from accelerator measurements. Timeout per config:
    ``KFTPU_BENCH_CPU_TIMEOUT_S`` (420)."""
    import subprocess
    import sys

    if timeout_s is None:
        timeout_s = float(os.environ.get("KFTPU_BENCH_CPU_TIMEOUT_S",
                                         "420"))
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    out: Dict[str, Dict[str, Any]] = {}
    for name in CONFIGS:
        if only and name not in only:
            continue
        kwargs = _CPU_SMOKE_ARGS.get(name, {})
        prog = (
            "import json\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from kubeflow_tpu.bench import suite\n"
            f"r = suite.CONFIGS[{name!r}](**{kwargs!r})\n"
            "r['tier'] = 'cpu'\n"
            "print(json.dumps(r))\n"
        )
        try:
            proc = subprocess.run(
                [sys.executable, "-c", prog], capture_output=True,
                text=True, timeout=timeout_s, cwd=repo_root)
        except subprocess.TimeoutExpired:
            out[name] = {"error": f"cpu smoke timeout after "
                                  f"{timeout_s:.0f}s", "tier": "cpu"}
            continue
        except OSError as e:
            out[name] = {"error": f"{type(e).__name__}: {e}",
                         "tier": "cpu"}
            continue
        if proc.returncode:
            out[name] = {"error": (proc.stderr.strip() or "no output")
                         [-300:], "tier": "cpu"}
            continue
        try:
            out[name] = json.loads(proc.stdout.strip().splitlines()[-1])
            out[name].setdefault("tier", "cpu")
        except (ValueError, IndexError):
            out[name] = {"error": (proc.stderr.strip() or "bad output")
                         [-300:], "tier": "cpu"}
    return out


def _device_alive(timeout_s: float = 60.0) -> bool:
    """Cheap device-transport probe in a killable subprocess."""
    import subprocess
    import sys

    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, timeout=timeout_s)
        return proc.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def main() -> None:
    import argparse

    p = argparse.ArgumentParser(description="BASELINE.md bench suite")
    p.add_argument("configs", nargs="*", choices=[*CONFIGS, []],
                   help="subset to run (default: all)")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="capture XLA profiler traces of the hot loops")
    args = p.parse_args()
    print(json.dumps(run_all(args.configs or None,
                             profile_dir=args.profile)))


if __name__ == "__main__":
    main()
