"""Benchmark pipeline — the kubebench equivalent.

The reference's kubebench runs an Argo workflow: configurator (render job
from config) → create main job → monitor until ``status.completionTime`` →
post-job → csv reporter, results on a shared PVC under
``KUBEBENCH_EXP_RESULT_PATH`` (``/root/reference/kubeflow/kubebench/
kubebench-job.libsonnet:250-396,118-144``). Here the same pipeline is a
typed runner with two backends:

- :class:`LocalRunner` — exec the workload module in a subprocess on the
  attached chips, scrape its JSON-line metrics from stdout;
- :class:`ClusterRunner` — submit a TpuJob CR, poll its status conditions
  (the monitor step), read metrics from the experiment results dir.

Both feed the same :func:`report` step emitting csv + json.
"""

from __future__ import annotations

import csv
import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

from kubeflow_tpu.k8s.client import KubeClient
from kubeflow_tpu.utils.clock import Clock, Sleep
from kubeflow_tpu.manifests.components.tpujob_operator import (
    API_VERSION,
    TPUJOB_KIND,
)
from kubeflow_tpu.operators.tpujob import tpujob

WORKLOADS = {
    "mnist": "kubeflow_tpu.examples.mnist",
    "resnet": "kubeflow_tpu.examples.resnet",
    "lm": "kubeflow_tpu.examples.lm",
    "bert": "kubeflow_tpu.examples.bert",
}


@dataclasses.dataclass
class BenchmarkSpec:
    """The configurator's input (kubebench config equivalent)."""

    name: str
    workload: str                      # key into WORKLOADS or a module path
    args: List[str] = dataclasses.field(default_factory=list)
    namespace: str = "default"
    # cluster mode:
    image: str = "kubeflow-tpu/examples:latest"
    slices: int = 1
    hosts_per_slice: int = 1
    accelerator: str = "v5e-8"
    timeout_s: float = 3600.0

    def module(self) -> str:
        return WORKLOADS.get(self.workload, self.workload)


@dataclasses.dataclass
class BenchmarkResult:
    name: str
    status: str                        # Succeeded | Failed | Timeout
    wall_time_s: float
    metrics: List[Dict[str, Any]]      # parsed JSON metric lines

    @property
    def final_metrics(self) -> Dict[str, Any]:
        return self.metrics[-1] if self.metrics else {}


class LocalRunner:
    """Run the workload in a subprocess on this host's devices."""

    def __init__(self, extra_env: Optional[Dict[str, str]] = None) -> None:
        self.extra_env = dict(extra_env or {})

    def run(self, spec: BenchmarkSpec) -> BenchmarkResult:
        cmd = [sys.executable, "-m", spec.module(), *spec.args]
        env = dict(os.environ)
        env.update(self.extra_env)
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        t0 = time.perf_counter()
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=spec.timeout_s,
                env=env,
            )
            status = "Succeeded" if proc.returncode == 0 else "Failed"
            stdout = proc.stdout
        except subprocess.TimeoutExpired as e:
            status = "Timeout"
            stdout = e.stdout or ""
            if isinstance(stdout, bytes):  # TimeoutExpired ignores text=True
                stdout = stdout.decode(errors="replace")
        wall = time.perf_counter() - t0
        metrics = []
        for line in (stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    metrics.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
        return BenchmarkResult(spec.name, status, wall, metrics)


class ClusterRunner:
    """Submit a TpuJob and monitor it (the create + monitor pipeline steps)."""

    def __init__(self, client: KubeClient, *,
                 results_dir: Optional[str] = None,
                 poll_interval_s: float = 5.0,
                 clock: Optional[Clock] = None,
                 sleep: Optional[Sleep] = None) -> None:
        self.client = client
        self.results_dir = results_dir
        self.poll_interval_s = poll_interval_s
        # injectable monitor timing (autoscale.policy.Clock contract):
        # tests drive the poll loop without real elapsed time
        self.clock: Clock = clock if clock is not None else time.perf_counter
        self.sleep: Sleep = sleep if sleep is not None else time.sleep

    def run(self, spec: BenchmarkSpec) -> BenchmarkResult:
        job = tpujob(spec.name, spec.namespace, {
            "image": spec.image,
            "command": ["python", "-m", spec.module(), *spec.args],
            "slices": spec.slices,
            "hostsPerSlice": spec.hosts_per_slice,
            "accelerator": spec.accelerator,
            "env": {"KFTPU_RESULTS_DIR": self.results_dir or ""},
        })
        self.client.apply(job)
        t0 = self.clock()
        status = "Timeout"
        while self.clock() - t0 < spec.timeout_s:
            cur = self.client.get_or_none(API_VERSION, TPUJOB_KIND,
                                          spec.namespace, spec.name)
            phase = (cur or {}).get("status", {}).get("phase", "")
            if phase in ("Succeeded", "Failed"):
                status = phase
                break
            self.sleep(self.poll_interval_s)
        wall = self.clock() - t0
        metrics = self._collect_metrics(spec)
        return BenchmarkResult(spec.name, status, wall, metrics)

    def _collect_metrics(self, spec: BenchmarkSpec) -> List[Dict[str, Any]]:
        if not self.results_dir:
            return []
        path = os.path.join(self.results_dir, f"{spec.name}.jsonl")
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        out.append(json.loads(line))
                    except json.JSONDecodeError:
                        pass
        return out


def report(result: BenchmarkResult, out_dir: str) -> Dict[str, str]:
    """The reporter step: write ``<name>.csv`` + ``<name>.json`` (kubebench's
    ``reporter csv`` equivalent, ``kubebench-job.libsonnet:59-62``)."""
    os.makedirs(out_dir, exist_ok=True)
    json_path = os.path.join(out_dir, f"{result.name}.json")
    csv_path = os.path.join(out_dir, f"{result.name}.csv")
    with open(json_path, "w") as f:
        json.dump({
            "name": result.name,
            "status": result.status,
            "wall_time_s": round(result.wall_time_s, 3),
            "final_metrics": result.final_metrics,
        }, f, indent=1)
    keys: List[str] = []
    for m in result.metrics:
        for k in m:
            if k not in keys:
                keys.append(k)
    with open(csv_path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=keys)
        writer.writeheader()
        for m in result.metrics:
            writer.writerow(m)
    return {"json": json_path, "csv": csv_path}
