"""Kubebench-shaped benchmark workflows on the in-framework engine.

Reference: the kubebench-job Argo prototype — configurator renders the
main job from config, a resource step creates it with
``successCondition=status.startTime``, a second resource step waits on
``status.completionTime``, then post-job + csv reporter run on a shared
experiment PVC (``/root/reference/kubeflow/kubebench/kubebench-job.
libsonnet:250-396``; env contract KUBEBENCH_EXP_* ``:118-144``). Here the
same DAG is rendered onto the native Workflow engine with a TpuJob as the
main job.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.operators.tpujob import tpujob
from kubeflow_tpu.workflows.workflow import (
    container_step,
    resource_step,
    workflow,
)

# kubebench's env contract, carried over
ENV_EXP_ID = "KUBEBENCH_EXP_ID"
ENV_EXP_RESULT_PATH = "KUBEBENCH_EXP_RESULT_PATH"


def benchmark_workflow(
    name: str,
    ns: str,
    *,
    job_spec: Dict[str, Any],
    reporter_image: str = "kubeflow-tpu/platform:v1alpha1",
    post_job: Optional[Dict[str, Any]] = None,
    result_path: str = "/results",
    experiment_pvc: str = "",
) -> o.Obj:
    """Render the 4-step kubebench DAG around a TpuJob spec.

    ``experiment_pvc`` mounts a shared PVC at ``result_path`` across the
    main job, post-job, and reporter — without it each step sees its own
    empty filesystem and the reporter reads nothing (the reference runs
    every step on a shared experiment PVC,
    ``kubebench-job.libsonnet:160-176``).
    """
    volumes: List[Dict[str, Any]] = []
    mounts: List[Dict[str, Any]] = []
    if experiment_pvc:
        volumes = [{"name": "experiment",
                    "persistentVolumeClaim": {"claimName": experiment_pvc}}]
        mounts = [{"name": "experiment", "mountPath": result_path}]
    job_spec = dict(job_spec)
    # the workload writes <result_path>/<job-name>.jsonl; the reporter
    # step reads it back (same contract as ClusterRunner)
    job_spec["env"] = {**(job_spec.get("env") or {}),
                       "KFTPU_RESULTS_DIR": result_path}
    if experiment_pvc:
        job_spec["volumes"] = (job_spec.get("volumes") or []) + volumes
        job_spec["volumeMounts"] = (job_spec.get("volumeMounts") or []) + mounts
    job = tpujob(f"{name}-main", ns, job_spec)
    steps: List[Dict[str, Any]] = [
        # launch-main-job: success as soon as the operator records startTime
        resource_step(
            "launch-main-job", "create", job,
            success_condition="status.startTime",
            failure_condition="status.phase == Failed",
        ),
        # wait-for-main-job: completionTime appears on success
        resource_step(
            "wait-for-main-job", "create", job,
            success_condition="status.completionTime",
            failure_condition="status.phase == Failed",
            dependencies=["launch-main-job"],
        ),
    ]
    reporter_deps = ["wait-for-main-job"]
    if post_job is not None:
        steps.append(container_step(
            "run-post-job", post_job.get("image", reporter_image),
            command=post_job.get("command"),
            args=post_job.get("args"),
            env={ENV_EXP_ID: name, ENV_EXP_RESULT_PATH: result_path},
            dependencies=["wait-for-main-job"],
            volumes=volumes or None,
            volume_mounts=mounts or None,
        ))
        reporter_deps = ["run-post-job"]
    steps.append(container_step(
        "run-reporter", reporter_image,
        command=["python", "-m", "kubeflow_tpu.bench",
                 "report", "--name", f"{name}-main", "--out", result_path],
        env={ENV_EXP_ID: name, ENV_EXP_RESULT_PATH: result_path},
        dependencies=reporter_deps,
        volumes=volumes or None,
        volume_mounts=mounts or None,
    ))
    return workflow(name, ns, steps)
