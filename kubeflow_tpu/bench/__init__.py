"""Benchmark pipeline (kubebench equivalent): configure → run → monitor → report."""

from kubeflow_tpu.bench.pipeline import (  # noqa: F401
    BenchmarkResult,
    BenchmarkSpec,
    ClusterRunner,
    LocalRunner,
    WORKLOADS,
    report,
)
