"""Training loop primitives: sharded state, SPMD train steps, optimizers."""

from kubeflow_tpu.train.distill import (  # noqa: F401
    distill_draft,
    make_draft,
    sample_corpus,
    truncate_draft,
)
from kubeflow_tpu.train.trainer import (  # noqa: F401
    TrainState,
    create_sharded_state,
    make_image_train_step,
    make_lm_train_step,
    make_mlm_train_step,
    masked_lm_loss,
    make_pipelined_lm_train_step,
    make_optimizer,
    chunked_next_token_loss,
    next_token_loss,
    softmax_cross_entropy,
    state_partition_specs,
    state_shardings,
)
