"""Draft-model acquisition for speculative decoding: layer-truncate the
target, then distill it toward the target's next-token distribution.

Speculative decoding (``kubeflow_tpu/models/decode.py:
speculative_generate``) only pays off when the draft's greedy proposals
match the target's often enough; this module is the recipe that
*produces* such a draft from the target itself — no separate pretraining
run, no external checkpoint:

1. :func:`truncate_draft` — keep an evenly-strided subset of the
   target's stacked transformer blocks (``nn.scan`` stacks layer params
   on axis 0, so truncation is one gather per leaf) and share the
   embeddings and final norm. A strided skeleton retains far more of
   the target's function than random init.
2. :func:`distill_draft` — KL-distill the truncated draft on token
   sequences (ideally sequences the target itself generates, so the
   draft concentrates capacity exactly where verification will happen).
3. Export the result with ``export_model(..., draft_of="<model>@<ver>")``
   — the serving repository pairs it with its target automatically and
   routes ``speculative: true`` requests through the pair
   (``kubeflow_tpu/serving/server.py:run_generate``).

Reference parity bar: the reference wires model + server + service in
one usable step (``/root/reference/kubeflow/tf-serving/
tf-serving-template.libsonnet:33-48``); a capability that cannot serve a
request end-to-end is not shipped. This module closes that loop for
speculative decoding.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.transformer import Transformer, TransformerConfig


def truncate_draft(config: TransformerConfig, params: Any,
                   n_layers: int) -> Tuple[TransformerConfig, Any]:
    """Layer-truncated draft: ``n_layers`` evenly-strided blocks (always
    including the first and last) of the target, sharing its embeddings
    and final norm. Requires ``scan_layers=True`` params (the default) —
    layer truncation is then a single axis-0 gather per block leaf.

    Returns ``(draft_config, draft_params)``; the params are NEW arrays
    (gathers), so the draft can be trained without touching the target.
    """
    if not config.scan_layers:
        raise ValueError("truncate_draft needs scan_layers=True params "
                         "(stacked block leaves)")
    L = config.n_layers
    if not 1 <= n_layers <= L:
        raise ValueError(f"n_layers must be in [1, {L}], got {n_layers}")
    if "blocks" not in params:
        raise ValueError("params has no 'blocks' collection — not a "
                         "scan-stacked transformer param tree")
    # evenly spaced, first and last always kept: the bottom layers feed
    # every representation and the top layers shape the logits
    idx = np.unique(np.linspace(0, L - 1, n_layers).round().astype(int))
    draft_config = dataclasses.replace(config, n_layers=int(idx.size),
                                       remat=False)
    draft_params = dict(params)
    draft_params["blocks"] = jax.tree_util.tree_map(
        lambda leaf: jnp.asarray(leaf)[jnp.asarray(idx)],
        params["blocks"])
    return draft_config, draft_params


def sample_corpus(config: TransformerConfig, params: Any, *,
                  n_seqs: int, seq_len: int, seed: int = 0,
                  temperature: float = 1.0) -> np.ndarray:
    """Self-distillation corpus: ``(n_seqs, seq_len)`` token sequences
    sampled FROM THE TARGET (one random BOS-ish token, then the target's
    own continuation). Distilling on the target's generations focuses
    the draft on the distribution speculative verification will actually
    traverse."""
    from kubeflow_tpu.models.decode import generate

    rng = jax.random.key(seed)
    k_prompt, k_gen = jax.random.split(rng)
    first = jax.random.randint(k_prompt, (n_seqs, 1), 0,
                               config.vocab_size)
    rest = generate(config, params, first,
                    max_new_tokens=seq_len - 1,
                    temperature=temperature, rng=k_gen)
    return np.concatenate([np.asarray(first), np.asarray(rest)], axis=1)


def distill_draft(target_config: TransformerConfig, target_params: Any,
                  draft_config: TransformerConfig, draft_params: Any,
                  corpus: np.ndarray, *, steps: int = 100,
                  batch: int = 8, lr: float = 1e-3,
                  seed: int = 0) -> Tuple[Any, Dict[str, Any]]:
    """KL-distill the draft toward the target on ``corpus`` (N, S)
    int32 tokens. Loss is ``KL(target || draft)`` over every next-token
    position, target frozen. Returns ``(trained_draft_params, stats)``
    with ``stats = {"first_loss", "last_loss"}``.

    All-device-resident and jit-compiled: the target's logits for a
    batch are computed under the same step (no materialized logit
    corpus — at 32k vocab a stored logit set would dwarf the corpus).
    """
    import optax

    corpus = np.asarray(corpus, np.int32)
    if corpus.ndim != 2:
        raise ValueError(f"corpus must be (N, S) tokens, got "
                         f"{corpus.shape}")
    n = corpus.shape[0]
    if n < batch:
        batch = n
    target = Transformer(target_config)
    draft = Transformer(draft_config)
    tx = optax.adamw(lr)
    opt_state = tx.init(draft_params)

    # target params enter as a jit ARGUMENT: closing over them would
    # embed the full frozen target as HLO constants — catastrophic at
    # real model sizes (a 167M-param target is a ~334 MB program body;
    # remote-compile transports reject it outright)
    # one ad-hoc distillation program per make_draft call, closed over
    # this tx/draft pair — billed by the CompileLedger listener; there
    # is no long-lived runner to hang an AOT handle on
    @jax.jit
    def step(dparams, opt_state, tokens, tparams):  # tpulint: disable=TPU018
        t_logits = target.apply({"params": tparams}, tokens)
        t_probs = jax.nn.softmax(t_logits.astype(jnp.float32), axis=-1)
        t_logp = jax.nn.log_softmax(t_logits.astype(jnp.float32), -1)

        def loss_fn(p):
            d_logits = draft.apply({"params": p}, tokens)
            d_logp = jax.nn.log_softmax(
                d_logits.astype(jnp.float32), axis=-1)
            # KL(t||d) = sum t*(log t - log d); constant t-entropy kept
            # (it doesn't affect gradients, and the reported loss → 0
            # exactly when the draft matches)
            kl = jnp.sum(t_probs * (t_logp - d_logp), axis=-1)
            return jnp.mean(kl)

        loss, grads = jax.value_and_grad(loss_fn)(dparams)
        updates, opt_state = tx.update(grads, opt_state, dparams)
        return optax.apply_updates(dparams, updates), opt_state, loss

    rng = np.random.default_rng(seed)
    # first-step loss stays a device value until after the loop: a
    # float() inside would stall the host on step 1's dispatch queue
    first_loss: Optional[jnp.ndarray] = None
    loss = jnp.float32(0.0)
    for _ in range(steps):
        rows = rng.integers(0, n, size=(batch,))
        draft_params, opt_state, loss = step(
            draft_params, opt_state, jnp.asarray(corpus[rows]),
            target_params)
        if first_loss is None:
            first_loss = loss
    return draft_params, {
        "first_loss": round(float(first_loss) if first_loss is not None
                            else 0.0, 4),
        "last_loss": round(float(loss), 4)}


def make_draft(config: TransformerConfig, params: Any, *,
               n_layers: int, distill_steps: int = 100,
               corpus: Optional[np.ndarray] = None,
               corpus_seqs: int = 64, corpus_len: int = 64,
               batch: int = 8, lr: float = 1e-3,
               seed: int = 0) -> Tuple[TransformerConfig, Any,
                                       Dict[str, Any]]:
    """The one-call recipe: truncate, (optionally self-)sample a corpus,
    distill. Returns ``(draft_config, draft_params, stats)`` ready for
    ``export_model(..., draft_of=...)``."""
    draft_config, draft_params = truncate_draft(config, params, n_layers)
    if distill_steps > 0:
        if corpus is None:
            # the self-sampled corpus must fit the target's context
            corpus_len = min(corpus_len, config.max_seq_len)
            corpus = sample_corpus(config, params, n_seqs=corpus_seqs,
                                   seq_len=corpus_len, seed=seed)
        draft_params, stats = distill_draft(
            config, params, draft_config, draft_params, corpus,
            steps=distill_steps, batch=batch, lr=lr, seed=seed)
    else:
        stats = {"first_loss": 0.0, "last_loss": 0.0}
    stats["n_layers"] = draft_config.n_layers
    return draft_config, draft_params, stats
