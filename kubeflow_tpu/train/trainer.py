"""Sharded training loop primitives: state init, train steps, optimizers.

In the reference, the training loop lives in opaque workload containers
(``tf_cnn_benchmarks`` — see SURVEY.md §3.3 "HOT LOOP"): workers pull params
from parameter servers over gRPC per step. Here the hot loop is a single
pjit-compiled SPMD step over a device mesh; gradient exchange is an XLA
AllReduce over ICI, and TP/SP/EP shardings come from the models' logical
axes (``kubeflow_tpu/parallel/mesh.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu.models.transformer import leaf_logical_axes
from kubeflow_tpu.parallel.mesh import (
    AxisRules,
    DEFAULT_RULES,
    logical_to_mesh_axes,
    mesh_context,
    shape_aware_spec,
    spec_for_mesh,
)


class TrainState(train_state.TrainState):
    """TrainState with optional BN statistics (for the ResNet family)."""

    batch_stats: Any = None


def _leaf_axes(path, leaf, pipelined: bool):
    axes = leaf_logical_axes(path, leaf)
    if pipelined and axes:
        # scanned "blocks" leaves: leading layer axis becomes the pipeline
        # stage axis (contiguous L/pp layers per pp rank)
        from kubeflow_tpu.models.transformer import _path_names

        if "blocks" in _path_names(path):
            axes = ("stage",) + tuple(axes[1:])
    return axes


def state_partition_specs(state: Any, rules: AxisRules = DEFAULT_RULES,
                          *, pipelined: bool = False) -> Any:
    """PartitionSpec for every leaf of a (possibly abstract) train state."""

    def spec(path, leaf):
        return logical_to_mesh_axes(_leaf_axes(path, leaf, pipelined), rules)

    return jax.tree_util.tree_map_with_path(spec, state)


def state_shardings(state: Any, mesh: Mesh, rules: AxisRules = DEFAULT_RULES,
                    *, pipelined: bool = False) -> Any:
    def shard(path, leaf):
        spec = spec_for_mesh(
            logical_to_mesh_axes(_leaf_axes(path, leaf, pipelined), rules),
            mesh)
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, shape_aware_spec(spec, shape, mesh))

    return jax.tree_util.tree_map_with_path(shard, state)


def make_optimizer(
    learning_rate: float = 3e-4,
    *,
    warmup_steps: int = 100,
    decay_steps: int = 10_000,
    weight_decay: float = 0.1,
    b1: float = 0.9,
    b2: float = 0.95,
    grad_clip: float = 1.0,
) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=learning_rate,
        warmup_steps=warmup_steps,
        decay_steps=max(decay_steps, warmup_steps + 1),
    )
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def create_sharded_state(
    init_fn: Callable[[jax.Array], TrainState],
    rng: jax.Array,
    mesh: Mesh,
    rules: AxisRules = DEFAULT_RULES,
    *,
    pipelined: bool = False,
) -> Tuple[TrainState, Any]:
    """Initialize a TrainState directly into its sharded layout.

    ``init_fn`` is traced abstractly to derive per-leaf shardings, then
    jit-compiled with those as out_shardings so every param lands sharded —
    no host-side full materialization (matters when params exceed one HBM).
    ``pipelined`` shards the scanned layer axis over pp (pipeline stages).
    """
    abstract = jax.eval_shape(init_fn, rng)
    shardings = state_shardings(abstract, mesh, rules, pipelined=pipelined)
    # one-time init compile, consumed immediately — billed by the
    # CompileLedger listener; an AOT fingerprint buys nothing here
    state = jax.jit(init_fn, out_shardings=shardings)(rng)  # tpulint: disable=TPU018
    return state, shardings


def next_token_loss(logits: jnp.ndarray, tokens: jnp.ndarray) -> jnp.ndarray:
    """Causal LM loss: predict tokens[:, 1:] from logits[:, :-1]."""
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def chunked_next_token_loss(hidden: jnp.ndarray, embed: jnp.ndarray,
                            tokens: jnp.ndarray, *, chunk: int = 4096,
                            softcap: float = 0.0) -> jnp.ndarray:
    """``next_token_loss`` computed from HIDDEN states with the vocab
    projection done per sequence chunk — (B, S, V) f32 logits are never
    materialized, and ``jax.checkpoint`` recomputes each chunk's logits
    in the backward so only (B, chunk, V) lives at once. At seq 65536 /
    vocab 32k the full-logit path alone is ~8.4 GB; chunked, the loss's
    working set is chunk/S of that. The math matches the model's head
    exactly (tied-embedding einsum in activation dtype, f32 softmax,
    optional softcap) so loss values and gradients are parity-testable
    against the unchunked path."""
    B, S, D = hidden.shape
    n = S - 1
    h = hidden[:, :-1]
    tgt = tokens[:, 1:]
    pad = (-n) % chunk
    if pad:
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        tgt = jnp.pad(tgt, ((0, 0), (0, pad)))
    valid = (jnp.arange(n + pad) < n)
    nc = (n + pad) // chunk
    h = h.reshape(B, nc, chunk, D).transpose(1, 0, 2, 3)
    tgt = tgt.reshape(B, nc, chunk).transpose(1, 0, 2)
    valid = valid.reshape(nc, chunk)

    @jax.checkpoint
    def chunk_ll(h_c, t_c, m_c):
        logits = jnp.einsum("bcd,vd->bcv", h_c,
                            embed.astype(h_c.dtype)).astype(jnp.float32)
        if softcap:
            logits = softcap * jnp.tanh(logits / softcap)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, t_c[..., None], axis=-1)[..., 0]
        return jnp.sum(ll * m_c[None, :])

    def body(acc, xs):
        return acc + chunk_ll(*xs), None

    total, _ = jax.lax.scan(body, jnp.float32(0.0), (h, tgt, valid))
    return -total / (B * n)


def make_lm_train_step(
    mesh: Mesh,
    rules: AxisRules = DEFAULT_RULES,
    *,
    moe_aux_weight: float = 0.01,
    donate: bool = True,
    loss_chunk: Optional[int] = None,
    logits_softcap: float = 0.0,
):
    """Build the jitted SPMD LM train step: (state, tokens) -> (state, metrics).

    ``loss_chunk``: long-context mode — ``state.apply_fn`` must return
    post-final-norm HIDDEN states (``Transformer(config,
    return_hidden=True)``) and the loss projects to vocab per
    ``loss_chunk``-token chunk (``chunked_next_token_loss``), so the
    full (B, S, V) logit tensor never exists. ALWAYS forward the
    model's ``config.logits_softcap`` here — the chunked loss re-applies
    the head's softcap itself (the hidden-states model never applies
    it), and a mismatch silently trains a different objective than the
    full-logits path."""
    batch_spec = spec_for_mesh(logical_to_mesh_axes(("batch", "seq"), rules), mesh)

    def step(state: TrainState, tokens: jnp.ndarray):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_spec)

        def loss_fn(params):
            out, mut = state.apply_fn(
                {"params": params}, tokens, mutable=["losses"]
            )
            if loss_chunk:
                loss = chunked_next_token_loss(
                    out, params["token_embed"], tokens,
                    chunk=loss_chunk, softcap=logits_softcap)
            else:
                loss = next_token_loss(out, tokens)
            aux = sum(
                jnp.sum(v) for v in jax.tree_util.tree_leaves(mut)
            ) if mut else 0.0
            return loss + moe_aux_weight * aux, loss

        grads, lm_loss = jax.grad(loss_fn, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads)
        metrics = {
            "loss": lm_loss,
            "grad_norm": optax.global_norm(grads),
            "step": new_state.step,
        }
        return new_state, metrics

    def run(state, tokens):
        with mesh_context(mesh):
            return jitted(state, tokens)

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
    return _ledgered(run, jitted, mesh)


def _ledgered(run, jitted, mesh):
    """Expose a step runner's AOT surfaces: ``run.jitted`` (bench
    roofline / HLO inspection) and ``run.aot_compile(ledger, *args)``,
    which lands the step's compile on a ``CompileLedger`` — HLO
    fingerprint, memory budget, and the ``kftpu_compile_seconds``
    series — before the step loop starts, so startup compile cost is
    attributed instead of billed as badput."""
    def aot_compile(ledger, *example_args, module: str = "train.step"):
        with mesh_context(mesh):
            return ledger.timed_compile(jitted, *example_args,
                                        module=module)
    run.jitted = jitted
    run.aot_compile = aot_compile
    return run


def masked_lm_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                   weights: jnp.ndarray) -> jnp.ndarray:
    """MLM objective: cross-entropy at masked positions only."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return -jnp.sum(ll * weights) / denom


def make_mlm_train_step(
    mesh: Mesh,
    rules: AxisRules = DEFAULT_RULES,
    *,
    donate: bool = True,
):
    """Jitted SPMD masked-LM step: (state, tokens, labels, weights) ->
    (state, metrics). ``tokens`` are the corrupted inputs; ``labels`` the
    originals; ``weights`` mark masked positions."""
    batch_spec = spec_for_mesh(logical_to_mesh_axes(("batch", "seq"), rules), mesh)

    def step(state: TrainState, tokens, labels, weights):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_spec)
        labels = jax.lax.with_sharding_constraint(labels, batch_spec)
        weights = jax.lax.with_sharding_constraint(weights, batch_spec)

        def loss_fn(params):
            logits = state.apply_fn({"params": params}, tokens)
            return masked_lm_loss(logits, labels, weights)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state = state.apply_gradients(grads=grads)
        metrics = {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": new_state.step,
        }
        return new_state, metrics

    def run(state, tokens, labels, weights):
        with mesh_context(mesh):
            return jitted(state, tokens, labels, weights)

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())
    return _ledgered(run, jitted, mesh)


def make_pipelined_lm_train_step(
    model,
    mesh: Mesh,
    *,
    n_microbatches: int,
    rules: AxisRules = DEFAULT_RULES,
    donate: bool = True,
):
    """LM train step with the block stack pipelined over the ``pp`` axis.

    Composes pp with dp/tp: stages are manual over pp
    (``kubeflow_tpu/parallel/pipeline.py``); dp/tp sharding inside each
    stage stays auto. State must be created with ``pipelined=True`` so the
    scanned layer axis lands stage-sharded. MoE auxiliary losses are not
    collected on this path (the pipeline applies blocks functionally).
    """
    from kubeflow_tpu.parallel.pipeline import make_pipelined_lm_forward

    fwd = make_pipelined_lm_forward(model, mesh, n_microbatches=n_microbatches)
    batch_spec = spec_for_mesh(logical_to_mesh_axes(("batch", "seq"), rules), mesh)

    def step(state: TrainState, tokens: jnp.ndarray):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_spec)

        def loss_fn(params):
            return next_token_loss(fwd(params, tokens), tokens)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        new_state = state.apply_gradients(grads=grads)
        return new_state, {
            "loss": loss,
            "grad_norm": optax.global_norm(grads),
            "step": new_state.step,
        }

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())

    def run(state, tokens):
        with mesh_context(mesh):
            return jitted(state, tokens)

    return _ledgered(run, jitted, mesh)


def make_image_train_step(
    mesh: Mesh,
    rules: AxisRules = DEFAULT_RULES,
    *,
    donate: bool = True,
):
    """Jitted SPMD classifier train step with BN-stat updates (ResNet path)."""
    batch_spec = spec_for_mesh(
        logical_to_mesh_axes(("batch", None, None, None), rules), mesh)
    label_spec = spec_for_mesh(logical_to_mesh_axes(("batch",), rules), mesh)

    def step(state: TrainState, images: jnp.ndarray, labels: jnp.ndarray):
        images = jax.lax.with_sharding_constraint(images, batch_spec)
        labels = jax.lax.with_sharding_constraint(labels, label_spec)

        def loss_fn(params):
            variables = {"params": params}
            if state.batch_stats is not None:
                variables["batch_stats"] = state.batch_stats
                logits, mut = state.apply_fn(
                    variables, images, train=True, mutable=["batch_stats"]
                )
                new_stats = mut["batch_stats"]
            else:
                logits = state.apply_fn(variables, images, train=True)
                new_stats = None
            loss = softmax_cross_entropy(logits, labels)
            acc = jnp.mean(jnp.argmax(logits, -1) == labels)
            return loss, (new_stats, acc)

        (loss, (new_stats, acc)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        new_state = state.apply_gradients(grads=grads)
        if new_stats is not None:
            new_state = new_state.replace(batch_stats=new_stats)
        return new_state, {"loss": loss, "accuracy": acc, "step": new_state.step}

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())

    def run(state, images, labels):
        with mesh_context(mesh):
            return jitted(state, images, labels)

    return _ledgered(run, jitted, mesh)
