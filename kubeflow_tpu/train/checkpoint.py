"""In-framework checkpoint/resume (SURVEY.md §5 gap).

The reference delegates checkpointing entirely to workloads — its platform
contribution is storage plumbing (PVCs, GCS/S3 creds injection; see
``mpi-job.libsonnet:64-82``, ``controller.py:104-116``). On TPU that is not
enough: a worker failure kills the whole SPMD gang and restart lands on a
fresh slice (SURVEY.md §7 hard part (b)), so resumable state must be a
framework primitive. Orbax handles the multi-host coordination; this module
pins the policy: step-numbered directories, keep-N retention, resume-latest.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

import jax

log = logging.getLogger(__name__)


class CheckpointManager:
    """Save/restore sharded TrainStates under ``<dir>/<step>/``."""

    def __init__(self, directory: str, *, keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = directory
        self._ocp = ocp
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, create=True, enable_async_checkpointing=True,
            ),
        )

    def save(self, step: int, state: Any, *, wait: bool = False) -> None:
        """Async save; set ``wait`` to block (end of training / tests)."""
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def reload(self) -> None:
        """Refresh the cached step list from disk. The operator-side
        ``DirCheckpointer`` reads a directory the WORKERS write from
        another process; orbax caches the step scan, so without this
        the preemption/resize victim-cost reads (and the goodput
        ledger's restore attribution) see only the steps that existed
        when the manager was built. Best-effort: an orbax without
        ``reload()`` keeps its cache."""
        reload_fn = getattr(self._mgr, "reload", None)
        if callable(reload_fn):
            try:
                reload_fn()
            except Exception:  # noqa: BLE001 — stale read beats a crash
                log.debug("orbax reload failed", exc_info=True)

    def all_steps(self) -> list:
        """Every step with a persisted checkpoint, ascending."""
        return sorted(self._mgr.all_steps())

    def restore(self, state: Any, step: Optional[int] = None) -> Any:
        """Restore into the sharding/structure of ``state`` (abstract ok).

        An explicit ``step`` that has no checkpoint raises
        ``FileNotFoundError`` loudly — the elastic reshard path resumes
        at an exact step, and silently restoring some OTHER step (or
        none) would fork the step clock instead of surviving the
        resize."""
        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self.directory}")
        elif step not in set(self._mgr.all_steps()):
            raise FileNotFoundError(
                f"no checkpoint for step {step} under {self.directory} "
                f"(have {self.all_steps()})")
        return self._mgr.restore(step, args=self._ocp.args.StandardRestore(state))

    def restore_or_init(self, state: Any) -> tuple[Any, int]:
        """Resume from the latest checkpoint, else keep the fresh state.

        Returns (state, start_step). This is the restart path after a gang
        re-placement: same code runs on first start and every resume.
        """
        step = self.latest_step()
        if step is None:
            return state, 0
        log.info("resuming from %s step %d", self.directory, step)
        return self.restore(state, step), step

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
