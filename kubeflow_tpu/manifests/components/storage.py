"""Shared-filesystem storage plumbing: NFS/Filestore PV + PVC pair.

Reference: ``/root/reference/kubeflow/gcp/google-cloud-filestore-pv.libsonnet``
(and the aws-efs twin) — a ReadWriteMany NFS PersistentVolume bound to a
same-named claim, the storage notebooks/checkpoints/kubebench experiment
dirs mount. Same shape here; the TPU use cases are checkpoint dirs
(orbax), TensorBoard log dirs, and the workflow run-archive/artifact
store.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "kubeflow-shared",
    "server_ip": "",          # Filestore/NFS server address (required)
    "path": "/shared",
    "capacity": "1Ti",
    "storage_class": "nfs-storage",
}


@register("nfs-storage", DEFAULTS,
          "ReadWriteMany NFS/Filestore PV + PVC (filestore-pv parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    if not params["server_ip"]:
        raise ValueError("nfs-storage: server_ip is required "
                         "(the Filestore/NFS endpoint)")
    ns = config.namespace
    name = params["name"]
    sc = params["storage_class"]
    return [
        {
            "apiVersion": "v1",
            "kind": "PersistentVolume",
            "metadata": {"name": name},
            "spec": {
                "capacity": {"storage": params["capacity"]},
                "accessModes": ["ReadWriteMany"],
                "nfs": {"path": params["path"],
                        "server": params["server_ip"]},
                "storageClassName": sc,
            },
        },
        {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": o.metadata(name, ns),
            "spec": {
                "accessModes": ["ReadWriteMany"],
                "storageClassName": sc,
                "resources": {"requests":
                              {"storage": params["capacity"]}},
            },
        },
    ]
