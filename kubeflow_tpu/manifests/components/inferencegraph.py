"""InferenceGraph component: CRD + graph controller Deployment + RBAC.

Manifest parity with the reference's seldon package — cluster-manager
Deployment + SeldonDeployment CRD + RBAC
(``/root/reference/kubeflow/seldon/core.libsonnet``) — recast onto the
framework's inference-graph controller
(:mod:`kubeflow_tpu.serving.graph_controller`).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "cluster_scope": True,
}


@register("inference-graph", DEFAULTS,
          "inference graph controller: chains/routers/ensembles (seldon parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    from kubeflow_tpu.serving.graph_controller import inference_graph_crd

    ns = config.namespace
    name = "inferencegraph-controller"
    rules = [
        {"apiGroups": ["kubeflow-tpu.org"],
         "resources": ["inferencegraphs", "inferencegraphs/status"],
         "verbs": ["*"]},
        {"apiGroups": ["apps"], "resources": ["deployments"], "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["services", "events"],
         "verbs": ["*"]},
    ]
    env = {"KFTPU_GRAPH_NAMESPACE": "" if params["cluster_scope"] else ns}
    pod = o.pod_spec(
        [o.container(
            name, params["image"],
            command=["python", "-m",
                     "kubeflow_tpu.serving.graph_controller"],
            env=env,
        )],
        service_account_name=name,
    )
    return [
        inference_graph_crd(),
        o.service_account(name, ns),
        o.cluster_role(name, rules),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod),
    ]
