"""Edge/ingress conventions shared by the web-service components.

The platform's web services (dashboard, notebook web app, kfam, bootstrap)
authorize on the ``X-Kubeflow-Userid`` header, so they must only be
reachable through the authenticating edge — the ingress gateway and the
gatekeeper (reference: every UI sits behind the Ambassador/Istio gateway +
IAP or basic-auth, ``/root/reference/kubeflow/common/ambassador.libsonnet:
152-179``, ``/root/reference/kubeflow/gcp/iap.libsonnet``). These label
selectors are the contract between the gateway component and the
NetworkPolicies each web component renders.
"""

from __future__ import annotations

from typing import List

from kubeflow_tpu.k8s import objects as o

# pods allowed to talk to header-trusting backends
INGRESS_POD_LABELS = {"app": "kftpu-ingressgateway"}
GATEKEEPER_POD_LABELS = {"app": "gatekeeper"}
PROBER_POD_LABELS = {"app": "availability-prober"}


def edge_only_policy(name: str, ns: str, app_label: str,
                     port: int, *, extra_from: List[dict] = ()) -> o.Obj:
    """NetworkPolicy locking ``app=<app_label>`` to the edge pods (plus the
    availability prober, whose whole job is reaching these services)."""
    return o.network_policy(
        f"{name}-edge-only", ns, {"app": app_label},
        from_pod_labels=[INGRESS_POD_LABELS, GATEKEEPER_POD_LABELS,
                         PROBER_POD_LABELS, *list(extra_from)],
        ports=[port],
    )
