"""Model registry component: Deployment + Service over a registry PVC.

Manifest parity with the reference's modeldb package — backend Deployment
:6543 + frontend + db (``/root/reference/kubeflow/modeldb/
modeldb.libsonnet``) — collapsed to the framework's file-backed registry
service (:mod:`kubeflow_tpu.serving.registry`): no database pod, the PVC
is the store, the dashboard is the frontend.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "model-registry",
    "image": "kubeflow-tpu/serving:v1alpha1",
    "port": 6543,  # modeldb backend's port, kept for familiarity
    "registry_dir": "/registry",
    "pvc": "model-registry",
    "replicas": 1,
}


@register("model-registry", DEFAULTS,
          "model registry/metadata service (modeldb parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = params["name"]
    use_pvc = bool(params["pvc"])
    mounts = ([{"name": "store", "mountPath": params["registry_dir"]}]
              if use_pvc else None)
    volumes = ([{"name": "store",
                 "persistentVolumeClaim": {"claimName": params["pvc"]}}]
               if use_pvc else None)
    pod = o.pod_spec(
        [o.container(
            name, params["image"],
            command=["python", "-m", "kubeflow_tpu.serving.registry"],
            env={"KFTPU_MODEL_REGISTRY_DIR": params["registry_dir"],
                 "KFTPU_REGISTRY_PORT": str(params["port"])},
            ports=[params["port"]],
            volume_mounts=mounts,
        )],
        volumes=volumes,
    )
    out: List[o.Obj] = []
    if use_pvc:
        out.append({
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": o.metadata(params["pvc"], ns),
            "spec": {
                "accessModes": ["ReadWriteOnce"],
                "resources": {"requests": {"storage": "1Gi"}},
            },
        })
    out.extend([
        o.deployment(name, ns, pod, replicas=params["replicas"]),
        o.service(name, ns, {"app": name},
                  [{"name": "http", "port": params["port"],
                    "targetPort": params["port"]}],
                  labels={"app": name}),
    ])
    return out
