"""Auth component: gatekeeper + availability prober Deployments.

Reference manifests: ``/root/reference/kubeflow/common/basic-auth.
libsonnet`` (kflogin + gatekeeper deploy) and the metric-collector deploy
(``kubeflow/gcp/metric-collector``-adjacent; prober source
``metric-collector/service-readiness/metric_collect.py``).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "auth_port": 8085,
    "secret_name": "kftpu-auth",
    # {"admin": "<salt$hash>"} from kubeflow_tpu.auth.hash_password — never
    # plaintext (reference stores the hash too: buildBasicAuthSecret
    # gcp.go:1486)
    "users": {},
    "cookie_secret": "",  # empty → gatekeeper uses an ephemeral secret
    "probe_url": "http://centraldashboard",
    "probe_period_s": 30,
    "monitoring_port": 8090,
}


@register("auth", DEFAULTS,
          "Basic-auth gatekeeper + availability prober (basic-auth parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    gk_pod = o.pod_spec([
        o.container(
            "gatekeeper", params["image"],
            command=["python", "-m", "kubeflow_tpu.auth.gatekeeper"],
            env={"KFTPU_AUTH_PORT": str(params["auth_port"])},
            ports=[params["auth_port"]],
        )
    ])
    # credentials come from a Secret, never inline env (reference:
    # buildBasicAuthSecret gcp.go:1486); rendered below so the pod never
    # crashloops on a missing ref
    gk_pod["containers"][0]["envFrom"] = [
        {"secretRef": {"name": params["secret_name"]}}]
    import json as _json

    auth_secret = o.secret(params["secret_name"], ns, {
        "KFTPU_AUTH_USERS": _json.dumps(dict(params["users"])),
        "KFTPU_AUTH_SECRET": params["cookie_secret"],
    })
    prober_pod = o.pod_spec([
        o.container(
            "availability-prober", params["image"],
            command=["python", "-m", "kubeflow_tpu.utils.availability"],
            env={
                "KFTPU_PROBE_URL": params["probe_url"],
                "KFTPU_PROBE_PERIOD_S": str(params["probe_period_s"]),
                "KFTPU_MONITORING_PORT": str(params["monitoring_port"]),
            },
            ports=[params["monitoring_port"]],
        )
    ])
    metrics_svc = o.service(
        "availability-prober", ns, {"app": "availability-prober"},
        [{"name": "metrics", "port": params["monitoring_port"],
          "targetPort": params["monitoring_port"]}],
        annotations={
            "prometheus.io/scrape": "true",
            "prometheus.io/path": "/metrics",
            "prometheus.io/port": str(params["monitoring_port"]),
        },
    )
    return [
        auth_secret,
        o.deployment("gatekeeper", ns, gk_pod),
        o.service("gatekeeper", ns, {"app": "gatekeeper"},
                  [{"name": "http", "port": params["auth_port"],
                    "targetPort": params["auth_port"]}]),
        o.deployment("availability-prober", ns, prober_pod),
        metrics_svc,
    ]
