"""Cloud-credentials PodDefault component.

Parity with the reference's credentials-pod-preset package
(``/root/reference/kubeflow/credentials-pod-preset/``): a PodPreset that
mounts a service-account key Secret and points
``GOOGLE_APPLICATION_CREDENTIALS`` at it for every pod opting in via a
label. Here it rides the framework's PodDefault machinery
(:mod:`kubeflow_tpu.tenancy.poddefault`) — the admission webhook the
tenancy component deploys performs the injection.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "secret_name": "gcp-credentials",
    "key_file": "key.json",
    "mount_path": "/secret/gcp",
    "label": "inject-gcp-credentials",
}


@register("credentials", DEFAULTS,
          "GOOGLE_APPLICATION_CREDENTIALS PodDefault (credentials-pod-preset parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    from kubeflow_tpu.tenancy.poddefault import pod_default

    from kubeflow_tpu.tenancy.profiles import SYNC_PODDEFAULTS_LABEL

    ns = config.namespace
    mount = params["mount_path"].rstrip("/")
    pd = pod_default(
        "gcp-credentials", ns,
        {params["label"]: "true"},
        desc="mount GCP service-account key + set "
             "GOOGLE_APPLICATION_CREDENTIALS",
        env={"GOOGLE_APPLICATION_CREDENTIALS":
             f"{mount}/{params['key_file']}"},
        volumes=[{"name": "gcp-credentials",
                  "secret": {"secretName": params["secret_name"]}}],
        volume_mounts=[{"name": "gcp-credentials",
                        "mountPath": mount,
                        "readOnly": True}],
    )
    # tenant pods live in per-profile namespaces; the profile controller
    # copies sync-labeled PodDefaults there (the webhook only consults
    # the pod's own namespace)
    pd["metadata"]["labels"] = {SYNC_PODDEFAULTS_LABEL: "true"}
    return [pd]
