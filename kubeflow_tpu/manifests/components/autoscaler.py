"""Serving autoscaler component: the KPA/activator role as a Deployment.

The reference gets serving autoscale from Knative's KPA via KFServing;
here it is the framework's own control loop
(:mod:`kubeflow_tpu.autoscale`) deployed next to the model server. The
pod runs ``kubeflow_tpu.autoscale.service``: it watches the configured
models, scales the target serving Deployment by patching
``spec.replicas``, reads slice inventory from node labels (the gang
scheduler's scan), and serves loop status + the remote-report endpoint
the proxy posts request telemetry to (``KFTPU_AUTOSCALE_URL``).

RBAC mirrors what the loop touches: Deployments (scale target), Nodes +
Pods (slice inventory), Events (degradation notices).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "serving-autoscaler",
    # same image as the serving tier — the autoscaler is framework code
    "image": "kubeflow-tpu/serving:v1alpha1",
    # every http://serving-autoscaler:<port> literal elsewhere (presets,
    # proxy/dashboard wiring) must match — enforced by tpulint TPU004
    "port": 8090,
    # policy preset (kubeflow_tpu/autoscale/policy.py POLICY_PRESETS)
    # plus the per-field overrides most deployments touch
    "policy": "serving",
    "target_concurrency": 0.0,   # 0 = preset value
    "max_replicas": 0,           # 0 = preset value
    "slice_shape": "",           # "" = preset value, e.g. "v5e-8"
    # serving Deployment whose spec.replicas the loop drives
    "target_deployment": "model-server-v1",
    # comma-separated model names to watch from zero replicas
    "models": "",
    "interval_s": 2.0,
}


@register("autoscaler", DEFAULTS,
          "TPU-slice-aware serving autoscaler (Knative-KPA parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = params["name"]

    env = {
        "KFTPU_AUTOSCALE_POLICY": params["policy"],
        "KFTPU_AUTOSCALE_TARGET": params["target_deployment"],
        "KFTPU_AUTOSCALE_MODELS": params["models"],
        "KFTPU_AUTOSCALE_INTERVAL_S": str(params["interval_s"]),
        "KFTPU_AUTOSCALE_PORT": str(params["port"]),
        "KFTPU_NAMESPACE": ns,
    }
    # 0/"" = keep the preset's value; only real overrides render
    if params["target_concurrency"]:
        env["KFTPU_AUTOSCALE_TARGET_CONCURRENCY"] = str(
            params["target_concurrency"])
    if params["max_replicas"]:
        env["KFTPU_AUTOSCALE_MAX_REPLICAS"] = str(params["max_replicas"])
    if params["slice_shape"]:
        env["KFTPU_AUTOSCALE_SLICE_SHAPE"] = params["slice_shape"]

    pod = o.pod_spec([
        o.container(
            "autoscaler",
            params["image"],
            command=["python", "-m", "kubeflow_tpu.autoscale.service"],
            env=env,
            ports=[params["port"]],
        )
    ], service_account_name=name)
    return [
        o.service_account(name, ns),
        o.cluster_role(name, [
            {"apiGroups": ["apps"], "resources": ["deployments"],
             "verbs": ["get", "list", "update", "patch"]},
            {"apiGroups": [""], "resources": ["nodes", "pods"],
             "verbs": ["get", "list", "watch"]},
            {"apiGroups": [""], "resources": ["events"],
             "verbs": ["create"]},
        ]),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod, labels={"app": name}),
        o.service(
            name, ns, {"app": name},
            [{"name": "http", "port": params["port"],
              "targetPort": params["port"]}],
            labels={"app": name},
            annotations={
                "prometheus.io/scrape": "true",
                "prometheus.io/path": "/metrics",
                "prometheus.io/port": str(params["port"]),
            }),
    ]
