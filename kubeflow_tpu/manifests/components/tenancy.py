"""Tenancy component: Profile/PodDefault CRDs, controllers, kfam, roles.

Manifest parity with the reference's profiles package + profile-controller
(``/root/reference/kubeflow/profiles/``), admission-webhook manifests
(``kubeflow/admission-webhook/``), and the kfam Deployment
(``components/access-management/``). Also defines the kubeflow-admin/
edit/view ClusterRoles every tenant RoleBinding references.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.components.edge import edge_only_policy
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "cluster_admins": [],
    "kfam_port": 8081,
}


def profile_crd() -> o.Obj:
    return o.crd(
        "profiles", GROUP, "Profile",
        versions=(VERSION,),
        scope="Cluster",
        printer_columns=(
            {"name": "State", "type": "string", "jsonPath": ".status.phase"},
        ),
    )


def poddefault_crd() -> o.Obj:
    return o.crd("poddefaults", GROUP, "PodDefault", versions=(VERSION,))


def tenant_cluster_roles() -> List[o.Obj]:
    """The admin/edit/view trio tenant RoleBindings reference."""
    everything = [{"apiGroups": ["", "apps", GROUP],
                   "resources": ["*"], "verbs": ["*"]}]
    edit = [
        {"apiGroups": ["", "apps", GROUP],
         "resources": ["pods", "services", "configmaps",
                       "persistentvolumeclaims", "statefulsets",
                       "tpujobs", "notebooks", "studies", "trials"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
    ]
    view = [
        {"apiGroups": ["", "apps", GROUP],
         "resources": ["pods", "services", "configmaps", "statefulsets",
                       "tpujobs", "notebooks", "studies", "trials"],
         "verbs": ["get", "list", "watch"]},
    ]
    return [
        o.cluster_role("kubeflow-admin", everything),
        o.cluster_role("kubeflow-edit", edit),
        o.cluster_role("kubeflow-view", view),
    ]


@register("tenancy", DEFAULTS,
          "Profiles, PodDefault webhook, access management (kfam parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "profile-controller"
    rules = [
        {"apiGroups": [GROUP],
         "resources": ["profiles", "profiles/status", "poddefaults"],
         "verbs": ["*"]},
        {"apiGroups": [""],
         "resources": ["namespaces", "serviceaccounts", "resourcequotas"],
         "verbs": ["*"]},
        {"apiGroups": ["rbac.authorization.k8s.io"],
         "resources": ["rolebindings", "clusterroles"], "verbs": ["*"]},
    ]
    ctrl_pod = o.pod_spec(
        [o.container(
            name, params["image"],
            command=["python", "-m", "kubeflow_tpu.tenancy.profiles"],
            # PodDefault sync sources ONLY from this namespace (tenant
            # namespaces must never be sync sources)
            env={"KFTPU_PLATFORM_NAMESPACE": ns},
        )],
        service_account_name=name,
    )
    kfam_pod = o.pod_spec(
        [o.container(
            "kfam", params["image"],
            command=["python", "-m", "kubeflow_tpu.tenancy.kfam"],
            env={
                "CLUSTER_ADMINS": ",".join(params["cluster_admins"]),
                "KFTPU_KFAM_PORT": str(params["kfam_port"]),
            },
            ports=[params["kfam_port"]],
        )],
        service_account_name=name,
    )
    from kubeflow_tpu.tenancy.webhook import (
        WEBHOOK_PORT,
        WEBHOOK_SERVICE,
        webhook_configuration,
    )

    webhook_pod = o.pod_spec(
        [o.container(
            WEBHOOK_SERVICE, params["image"],
            command=["python", "-m", "kubeflow_tpu.tenancy.webhook"],
            env={"KFTPU_NAMESPACE": ns},
            ports=[WEBHOOK_PORT],
        )],
        service_account_name=name,
    )
    webhook_rules = [
        # bootstrap: store the cert Secret + patch its own caBundle
        {"apiGroups": [""], "resources": ["secrets"],
         "verbs": ["get", "create"]},
        {"apiGroups": ["admissionregistration.k8s.io"],
         "resources": ["mutatingwebhookconfigurations"],
         "verbs": ["get", "create", "update"]},
    ]
    return [
        profile_crd(),
        poddefault_crd(),
        *tenant_cluster_roles(),
        o.service_account(name, ns),
        o.cluster_role(name, rules + webhook_rules),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, ctrl_pod),
        o.deployment("kfam", ns, kfam_pod),
        o.deployment(WEBHOOK_SERVICE, ns, webhook_pod),
        o.service(WEBHOOK_SERVICE, ns, {"app": WEBHOOK_SERVICE},
                  [{"name": "https", "port": WEBHOOK_PORT,
                    "targetPort": WEBHOOK_PORT}]),
        # rendered without caBundle; the webhook pod patches trust in at
        # bootstrap (see kubeflow_tpu/tenancy/webhook.py)
        webhook_configuration(ns),
        o.service("kfam", ns, {"app": "kfam"},
                  [{"name": "http", "port": params["kfam_port"],
                    "targetPort": params["kfam_port"]}]),
        edge_only_policy(
            "kfam", ns, "kfam", params["kfam_port"],
            # the dashboard's workgroup flow calls kfam server-side
            extra_from=[{"app": "centraldashboard"}]),
    ]
