"""Trace-collector component: the fleet-wide span sink.

No reference equivalent — the reference platform's observability stops
at Prometheus scrape annotations (``tf-job-operator.libsonnet:180-184``)
with no request-level tracing at all. This deploys
``kubeflow_tpu.obs.service`` (ingest + trace query API) next to the
``monitoring`` Prometheus: components push span batches to
``http://trace-collector:8095/api/traces:ingest`` (the default wired in
:mod:`kubeflow_tpu.obs.export`; tpulint TPU004 cross-checks host, port,
and path), and the dashboard's traces panel reads the same
``/api/traces`` shape it serves locally.

RBAC mirrors what trace correlation touches (resolving a span's
``service``/``pod`` attrs against live objects): read-only pods,
services, endpoints — the same read surface the Prometheus scraper has.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "trace-collector",
    # framework code — same image as the serving tier
    "image": "kubeflow-tpu/serving:v1alpha1",
    # every http://trace-collector:<port> literal elsewhere (the
    # push_spans default, dashboard wiring) must match — tpulint TPU004
    "port": 8095,
    # ring-buffer capacity: the retained incident window, not an archive
    "capacity": 65536,
}


@register("trace-collector", DEFAULTS,
          "Distributed-trace span sink + query API (docs/OBSERVABILITY.md)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = params["name"]
    pod = o.pod_spec([
        o.container(
            "collector",
            params["image"],
            command=["python", "-m", "kubeflow_tpu.obs.service"],
            env={"KFTPU_TRACE_PORT": str(params["port"]),
                 "KFTPU_TRACE_CAPACITY": str(params["capacity"])},
            ports=[params["port"]],
        )
    ], service_account_name=name)
    return [
        o.service_account(name, ns),
        o.cluster_role(name, [
            {"apiGroups": [""],
             "resources": ["pods", "services", "endpoints"],
             "verbs": ["get", "list", "watch"]},
        ]),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod, labels={"app": name}),
        o.service(
            name, ns, {"app": name},
            [{"name": "http", "port": params["port"],
              "targetPort": params["port"]}],
            labels={"app": name},
            annotations={
                # the collector exposes its own ingest/eviction counters
                "prometheus.io/scrape": "true",
                "prometheus.io/path": "/metrics",
                "prometheus.io/port": str(params["port"]),
            }),
    ]
