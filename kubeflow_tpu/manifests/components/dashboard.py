"""Central dashboard component (reference: ``components/centraldashboard``,
deployed by ``/root/reference/kubeflow/common/centraldashboard.libsonnet``)."""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.components.edge import edge_only_policy
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/dashboard:v1alpha1",
    "port": 8082,
    "replicas": 1,
    # autoscaler service URL for the /api/metrics/autoscale panel; ""
    # falls back to the dashboard's own (empty) local gauges
    "autoscale_url": "",
}


@register("dashboard", DEFAULTS, "Central dashboard web service")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "centraldashboard"
    pod = o.pod_spec(
        [o.container(
            name,
            params["image"],
            command=["python", "-m", "kubeflow_tpu.dashboard.server"],
            env={"KFTPU_DASHBOARD_PORT": str(params["port"]),
                 **({"KFTPU_AUTOSCALE_URL": params["autoscale_url"]}
                    if params["autoscale_url"] else {})},
            ports=[params["port"]],
        )],
        service_account_name=name,
    )
    rules = [
        {"apiGroups": [""], "resources": ["namespaces", "events"],
         "verbs": ["get", "list"]},
        {"apiGroups": ["kubeflow-tpu.org"], "resources": ["*"],
         "verbs": ["get", "list"]},
    ]
    return [
        o.service_account(name, ns),
        o.cluster_role(name, rules),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod, replicas=params["replicas"]),
        o.service(name, ns, {"app": name},
                  [{"name": "http", "port": 80, "targetPort": params["port"]}]),
        edge_only_policy(name, ns, name, params["port"]),
    ]
