"""Notebooks component: Notebook CRD + controller + web-app Deployments.

Manifest parity with the reference's jupyter package + notebook-controller
deploy (``/root/reference/kubeflow/jupyter/notebooks.libsonnet:7-27`` CRD,
``notebook_controller.libsonnet``) and jupyter-web-app
(``components/jupyter-web-app``).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.components.edge import edge_only_policy
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "enable_culling": False,
    "cull_idle_minutes": 1440,
    "webapp_port": 5000,
}


def notebook_crd() -> o.Obj:
    return o.crd(
        "notebooks", GROUP, "Notebook",
        versions=(VERSION,),
        short_names=("nb",),
        printer_columns=(
            {"name": "State", "type": "string", "jsonPath": ".status.phase"},
            {"name": "Age", "type": "date",
             "jsonPath": ".metadata.creationTimestamp"},
        ),
    )


@register("notebooks", DEFAULTS,
          "Notebook CRD + controller + web app (jupyter parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    ctrl_name = "notebook-controller"
    rules = [
        {"apiGroups": [GROUP],
         "resources": ["notebooks", "notebooks/status"], "verbs": ["*"]},
        {"apiGroups": ["apps"], "resources": ["statefulsets"], "verbs": ["*"]},
        {"apiGroups": [""],
         "resources": ["pods", "services", "events",
                       "persistentvolumeclaims", "namespaces"],
         "verbs": ["*"]},
    ]
    ctrl_pod = o.pod_spec(
        [o.container(
            ctrl_name,
            params["image"],
            command=["python", "-m", "kubeflow_tpu.notebooks.controller"],
            env={
                "ENABLE_CULLING": str(params["enable_culling"]).lower(),
                "CULL_IDLE_TIME": str(params["cull_idle_minutes"]),
            },
        )],
        service_account_name=ctrl_name,
    )
    webapp_name = "notebook-webapp"
    webapp_pod = o.pod_spec(
        [o.container(
            webapp_name,
            params["image"],
            command=["python", "-m", "kubeflow_tpu.notebooks.webapp"],
            env={"KFTPU_WEBAPP_PORT": str(params["webapp_port"])},
            ports=[params["webapp_port"]],
        )],
        service_account_name=ctrl_name,
    )
    return [
        notebook_crd(),
        o.service_account(ctrl_name, ns),
        o.cluster_role(ctrl_name, rules),
        o.cluster_role_binding(ctrl_name, ctrl_name, ctrl_name, ns),
        o.deployment(ctrl_name, ns, ctrl_pod),
        o.deployment(webapp_name, ns, webapp_pod),
        o.service(webapp_name, ns, {"app": webapp_name},
                  [{"name": "http", "port": 80,
                    "targetPort": params["webapp_port"]}]),
        edge_only_policy(
            webapp_name, ns, webapp_name, params["webapp_port"],
            # the dashboard embeds the notebook manager and proxies its API
            extra_from=[{"app": "centraldashboard"}]),
    ]
