"""Workflows component: Workflow/ScheduledWorkflow CRDs + controllers.

Manifest parity with the reference's argo package (CRD + workflow-
controller + UI, ``/root/reference/kubeflow/argo/argo.libsonnet:13-166``)
and the pipeline package's scheduledworkflow controller.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "cluster_scope": True,
}


def workflow_crd() -> o.Obj:
    return o.crd(
        "workflows", GROUP, "Workflow",
        versions=(VERSION,),
        short_names=("wf",),
        printer_columns=(
            {"name": "State", "type": "string", "jsonPath": ".status.phase"},
            {"name": "Started", "type": "date",
             "jsonPath": ".status.startedAt"},
        ),
    )


def scheduled_workflow_crd() -> o.Obj:
    return o.crd("scheduledworkflows", GROUP, "ScheduledWorkflow",
                 versions=(VERSION,), short_names=("swf",))


@register("workflows", DEFAULTS,
          "DAG workflow + cron-schedule controllers (argo/pipelines parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "workflow-controller"
    rules = [
        {"apiGroups": [GROUP], "resources": ["*"], "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "configmaps", "events"],
         "verbs": ["*"]},
    ]
    env = {"KFTPU_WORKFLOW_NAMESPACE": "" if params["cluster_scope"] else ns}
    wf_pod = o.pod_spec(
        [o.container(
            name, params["image"],
            command=["python", "-m", "kubeflow_tpu.workflows.controller"],
            env=env,
        )],
        service_account_name=name,
    )
    swf_pod = o.pod_spec(
        [o.container(
            "scheduledworkflow-controller", params["image"],
            command=["python", "-m", "kubeflow_tpu.workflows.cron"],
            env=env,
        )],
        service_account_name=name,
    )
    return [
        workflow_crd(),
        scheduled_workflow_crd(),
        o.service_account(name, ns),
        o.cluster_role(name, rules),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, wf_pod),
        o.deployment("scheduledworkflow-controller", ns, swf_pod),
    ]
