"""TensorBoard component: visualize training logs + profiler traces.

Reference: ``/root/reference/kubeflow/tensorboard/tensorboard.libsonnet``
(Service + Deployment + optional Istio VirtualService at
``/tensorboard/<name>/``, ambassador mapping annotation, gcp/aws log-dir
volume variants). The TPU build keeps the same surface and points the log
dir at either a PVC (mounted read-only — the trainer's profiler/metrics
write side, ``kubeflow_tpu/utils/profiler.py``) or a ``gs://`` path read
directly by TensorBoard. This is where the committed XLA traces
(``bench.py --profile``) get opened.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "tensorboard",
    "image": "tensorflow/tensorflow:2.15.0",
    "log_dir": "/logs",          # mount point, or a gs:// url
    "pvc": "training-logs",      # PVC holding the logs; "" when log_dir
                                 # is a gs:// url read directly
    "create_pvc": True,          # render the PVC too, so the preset's
                                 # happy path schedules out of the box
                                 # (set False to bind an existing claim,
                                 # e.g. nfs-storage's RWX one)
    "pvc_size": "10Gi",
    # RWO shares writer (trainer) and reader (tensorboard) only when they
    # land on one node; multi-node clusters should bind an RWX claim
    # instead (nfs-storage component) or set this to ReadWriteMany where
    # the storage class supports it
    "pvc_access_mode": "ReadWriteOnce",
    "port": 80,
    "target_port": 6006,
    "replicas": 1,
    "inject_istio": False,       # VirtualService at /tensorboard/<name>/
    "cpu": "1",
    "memory": "1Gi",
    "cpu_limit": "4",
    "memory_limit": "4Gi",
}


def _virtual_service(name: str, ns: str, port: int) -> o.Obj:
    """Prefix route + rewrite, the libsonnet istioVirtualService shape."""
    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": o.metadata(name, ns),
        "spec": {
            "hosts": ["*"],
            "gateways": ["kubeflow-gateway"],
            "http": [{
                "match": [{"uri": {"prefix": f"/tensorboard/{name}/"}}],
                "rewrite": {"uri": "/"},
                "route": [{"destination": {
                    "host": f"{name}.{ns}.svc.cluster.local",
                    "port": {"number": port},
                }}],
            }],
        },
    }


@register("tensorboard", DEFAULTS,
          "TensorBoard over a training-logs PVC or GCS path")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = params["name"]
    log_dir = params["log_dir"]
    use_pvc = bool(params["pvc"]) and not str(log_dir).startswith("gs://")

    mounts = ([{"name": "logs", "mountPath": log_dir, "readOnly": True}]
              if use_pvc else None)
    volumes = ([{"name": "logs",
                 "persistentVolumeClaim": {"claimName": params["pvc"],
                                           "readOnly": True}}]
               if use_pvc else None)
    ctr = o.container(
        name, params["image"],
        command=["tensorboard"],
        args=[f"--logdir={log_dir}", f"--port={params['target_port']}",
              "--bind_all"],
        ports=[params["target_port"]],
        resources={
            "requests": {"cpu": params["cpu"],
                         "memory": params["memory"]},
            "limits": {"cpu": params["cpu_limit"],
                       "memory": params["memory_limit"]},
        },
        volume_mounts=mounts,
    )
    objs: List[o.Obj] = []
    if use_pvc and params["create_pvc"]:
        objs.append({
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": o.metadata(params["pvc"], ns),
            "spec": {
                "accessModes": [params["pvc_access_mode"]],
                "resources": {"requests": {"storage": params["pvc_size"]}},
            },
        })
    objs += [
        o.deployment(name, ns, o.pod_spec([ctr], volumes=volumes),
                     replicas=int(params["replicas"])),
        o.service(name, ns, {"app": name},
                  [{"name": "tb", "port": int(params["port"]),
                    "targetPort": int(params["target_port"])}]),
    ]
    if params["inject_istio"]:
        objs.append(_virtual_service(name, ns, int(params["port"])))
    return objs
