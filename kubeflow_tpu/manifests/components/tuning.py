"""Tuning component: Study/Trial CRDs + controller + suggestion services.

Manifest parity with the reference's katib package — vizier-core manager +
per-algorithm suggestion Deployments + studyjob-controller + katib-ui
(``/root/reference/kubeflow/katib/vizier.libsonnet:99-455``,
``suggestion.libsonnet:44-240``, ``studyjobcontroller.libsonnet:297-323``) —
minus the MySQL vizier-db: study state lives in the Study/Trial CR status,
so there is no separate database to run.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.components.tpujob_operator import (
    GROUP,
    TPUJOB_PLURAL,
    VERSION,
)
from kubeflow_tpu.manifests.registry import register

SUGGESTION_PORT = 6789  # reference: each suggestion service binds :6789

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/tuning:v1alpha1",
    "suggestion_algorithms": ["random", "grid", "bayesian", "hyperband"],
    "monitoring_port": 8444,
    "replicas": 1,
}


def study_crd() -> o.Obj:
    return o.crd(
        "studies", GROUP, "Study",
        versions=(VERSION,),
        short_names=("st",),
        printer_columns=(
            {"name": "State", "type": "string", "jsonPath": ".status.phase"},
            {"name": "Trials", "type": "integer",
             "jsonPath": ".status.trials"},
            {"name": "Age", "type": "date",
             "jsonPath": ".metadata.creationTimestamp"},
        ),
    )


def trial_crd() -> o.Obj:
    return o.crd(
        "trials", GROUP, "Trial",
        versions=(VERSION,),
        printer_columns=(
            {"name": "State", "type": "string", "jsonPath": ".status.phase"},
            {"name": "Age", "type": "date",
             "jsonPath": ".metadata.creationTimestamp"},
        ),
    )


@register("tuning", DEFAULTS,
          "HP tuning: Study controller + suggestion services (katib parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "study-controller"
    rules = [
        {"apiGroups": [GROUP],
         "resources": ["studies", "studies/status", "trials", "trials/status",
                       TPUJOB_PLURAL, f"{TPUJOB_PLURAL}/status"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["configmaps", "events"],
         "verbs": ["*"]},
        # the controller provisions trial-metrics-writer Role/RoleBindings
        # in every namespace where studies run
        {"apiGroups": ["rbac.authorization.k8s.io"],
         "resources": ["roles", "rolebindings"],
         "verbs": ["get", "create", "update"]},
    ]
    pod = o.pod_spec(
        [o.container(
            name,
            params["image"],
            command=["python", "-m", "kubeflow_tpu.tuning.controller"],
            env={"KFTPU_MONITORING_PORT": str(params["monitoring_port"])},
            ports=[params["monitoring_port"]],
        )],
        service_account_name=name,
    )
    # trial workload pods run under the namespace default SA (the TpuJob
    # operator sets no serviceAccountName) and must be able to publish
    # their trial-metrics ConfigMap via report_trial_metrics()
    metrics_writer = o.role(
        "trial-metrics-writer", ns,
        [{"apiGroups": [""], "resources": ["configmaps"],
          "verbs": ["get", "create", "update", "patch"]}])
    out = [
        study_crd(),
        trial_crd(),
        o.service_account(name, ns),
        o.cluster_role(name, rules),
        o.cluster_role_binding(name, name, name, ns),
        metrics_writer,
        o.role_binding("trial-metrics-writer", ns, "trial-metrics-writer",
                       "default", ns),
        o.deployment(name, ns, pod, replicas=params["replicas"]),
    ]
    # one suggestion Deployment+Service per algorithm, like the reference's
    # vizier-suggestion-{random,grid,hyperband,bayesianoptimization}
    for algo in params["suggestion_algorithms"]:
        sname = f"suggestion-{algo}"
        spod = o.pod_spec([o.container(
            sname,
            params["image"],
            command=["python", "-m", "kubeflow_tpu.tuning.service"],
            env={"KFTPU_SUGGESTION_PORT": str(SUGGESTION_PORT)},
            ports=[SUGGESTION_PORT],
        )])
        out.append(o.deployment(sname, ns, spod))
        out.append(o.service(
            sname, ns, {"app": sname},
            [{"name": "api", "port": SUGGESTION_PORT,
              "targetPort": SUGGESTION_PORT}]))
    return out
