"""JAX model-serving component: versioned model server + traffic-split Service.

Replaces TF-Serving / TensorRT Inference Server behind the same surface:
gRPC :9000 + REST :8500 ports and per-version Deployments with a
weight-split Service (reference: ``/root/reference/kubeflow/tf-serving/
tf-serving-template.libsonnet:33-48``, version split
``tf-serving-service-template.libsonnet`` / ``prototypes/
tf-serving-service.jsonnet:8``, prometheus config ``:128-130``).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "model-server",
    "image": "kubeflow-tpu/serving:v1alpha1",
    "model_base_path": "/models/default",
    "version": "v1",
    "replicas": 1,
    "rest_port": 8500,
    "grpc_port": 9000,
    "tpu_chips": 0,  # 0 = CPU serving; >0 requests google.com/tpu
    "batch_timeout_ms": 5,
    "max_batch_size": 8,
    # continuous-batching decode engine slots for LM :generate (0 = the
    # whole-request bucketed fallback) and on-device steps per host sync
    "decode_slots": 8,
    "decode_steps_per_sync": 4,
    # "" = single-chip; "tp=4" serves LMs tensor-parallel across the
    # pod's chips (params + KV cache sharded over the mesh)
    "serving_mesh": "",
    # version -> weight (e.g. {"v1": 90, "v2": 10}); empty = single version.
    # Renders one Deployment per version + an Istio VirtualService carrying
    # the weights (tf-serving-service-template.libsonnet trafficRule parity)
    "traffic_split": {},
    # request-logging http proxy sidecar service (k8s-model-server/http-proxy)
    "proxy": False,
    "proxy_port": 8008,
    # autoscaler service URL; non-empty wires the proxy's per-request
    # start/finish telemetry to it (kubeflow_tpu/autoscale), e.g.
    # "http://serving-autoscaler:8090"
    "autoscale_url": "",
}


def istio_virtual_service(name: str, ns: str, ports: List[int],
                          splits: Dict[str, int]) -> o.Obj:
    """Weighted version routing (reference: Istio VS weighting in
    ``tf-serving-service-template.libsonnet``; ``trafficRule`` "v1:100").

    One match-per-port http route so REST and gRPC each keep their own
    port while sharing the same version weights — a catch-all route would
    rewrite gRPC traffic onto the REST port.
    """
    total = sum(splits.values())
    if total != 100:
        raise ValueError(f"traffic_split weights must sum to 100, got {total}")
    for version, weight in splits.items():
        if not 0 <= int(weight) <= 100:
            raise ValueError(
                f"traffic_split weight for {version!r} must be in [0,100], "
                f"got {weight}")
    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": o.metadata(name, ns),
        "spec": {
            "hosts": [name],
            "http": [
                {
                    "match": [{"port": port}],
                    "route": [
                        {"destination": {"host": name,
                                         "subset": version,
                                         "port": {"number": port}},
                         "weight": weight}
                        for version, weight in sorted(splits.items())
                    ],
                }
                for port in ports
            ],
        },
    }


def istio_destination_rule(name: str, ns: str,
                           versions: List[str]) -> o.Obj:
    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "DestinationRule",
        "metadata": o.metadata(name, ns),
        "spec": {
            "host": name,
            "subsets": [{"name": v, "labels": {"version": v}}
                        for v in sorted(versions)],
        },
    }


@register("serving", DEFAULTS,
          "JAX/XLA model server (replaces tf-serving / nvidia-inference-server)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = params["name"]

    resources: Dict[str, Any] = {}
    if params["tpu_chips"]:
        resources = {"limits": {"google.com/tpu": params["tpu_chips"]}}

    env = {
        "KFTPU_MODEL_BASE_PATH": params["model_base_path"],
        "KFTPU_REST_PORT": str(params["rest_port"]),
        "KFTPU_GRPC_PORT": str(params["grpc_port"]),
        "KFTPU_BATCH_TIMEOUT_MS": str(params["batch_timeout_ms"]),
        "KFTPU_MAX_BATCH_SIZE": str(params["max_batch_size"]),
        "KFTPU_DECODE_SLOTS": str(params["decode_slots"]),
        "KFTPU_DECODE_STEPS_PER_SYNC": str(params["decode_steps_per_sync"]),
        **({"KFTPU_SERVING_MESH": params["serving_mesh"]}
           if params["serving_mesh"] else {}),
    }

    def version_deploy(version: str, pin: bool) -> o.Obj:
        labels = {"app": name, "version": version}
        # Under a traffic split, pin each backend to its own model version so
        # the Istio-weighted split actually routes between different models
        # (tf-serving runs one server per version dir for the same reason:
        # tf-serving-service-template.libsonnet per-version deployments).
        # Single-version serving stays unpinned: hot-reload of the latest
        # version is the advertised behavior there.
        pod = o.pod_spec([
            o.container(
                "server",
                params["image"],
                command=["python", "-m", "kubeflow_tpu.serving.server"],
                env={**env, "KFTPU_MODEL_VERSION": version} if pin else env,
                ports=[params["rest_port"], params["grpc_port"]],
                resources=resources,
            )
        ])
        return o.deployment(f"{name}-{version}", ns, pod,
                            replicas=params["replicas"], labels=labels)

    splits: Dict[str, int] = dict(params["traffic_split"] or {})
    versions = sorted(splits) if splits else [params["version"]]
    out: List[o.Obj] = [version_deploy(v, pin=bool(splits))
                        for v in versions]
    svc = o.service(
        name,
        ns,
        {"app": name},  # selects every version; Istio VS carries the weights
        [
            {"name": "rest", "port": params["rest_port"],
             "targetPort": params["rest_port"]},
            {"name": "grpc", "port": params["grpc_port"],
             "targetPort": params["grpc_port"]},
        ],
        labels={"app": name},
        annotations={
            "prometheus.io/scrape": "true",
            "prometheus.io/path": "/metrics",
            "prometheus.io/port": str(params["rest_port"]),
        },
    )
    out.append(svc)
    if splits:
        out.append(istio_destination_rule(name, ns, versions))
        out.append(istio_virtual_service(
            name, ns, [params["rest_port"], params["grpc_port"]], splits))
    if params["proxy"]:
        proxy_pod = o.pod_spec([
            o.container(
                "http-proxy",
                params["image"],
                command=["python", "-m", "kubeflow_tpu.serving.proxy"],
                env={"KFTPU_PROXY_PORT": str(params["proxy_port"]),
                     "KFTPU_BACKEND_URL":
                         f"http://{name}:{params['rest_port']}",
                     **({"KFTPU_AUTOSCALE_URL": params["autoscale_url"]}
                        if params["autoscale_url"] else {})},
                ports=[params["proxy_port"]],
            )
        ])
        out.append(o.deployment(f"{name}-proxy", ns, proxy_pod))
        out.append(o.service(
            f"{name}-proxy", ns, {"app": f"{name}-proxy"},
            [{"name": "http", "port": params["proxy_port"],
              "targetPort": params["proxy_port"]}]))
    return out
