"""JAX model-serving component: versioned model server + traffic-split Service.

Replaces TF-Serving / TensorRT Inference Server behind the same surface:
gRPC :9000 + REST :8500 ports and per-version Deployments with a
weight-split Service (reference: ``/root/reference/kubeflow/tf-serving/
tf-serving-template.libsonnet:33-48``, version split
``tf-serving-service-template.libsonnet`` / ``prototypes/
tf-serving-service.jsonnet:8``, prometheus config ``:128-130``).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "model-server",
    "image": "kubeflow-tpu/serving:v1alpha1",
    "model_base_path": "/models/default",
    "version": "v1",
    "replicas": 1,
    "rest_port": 8500,
    "grpc_port": 9000,
    "tpu_chips": 0,  # 0 = CPU serving; >0 requests google.com/tpu
    "batch_timeout_ms": 5,
    "max_batch_size": 8,
}


@register("serving", DEFAULTS,
          "JAX/XLA model server (replaces tf-serving / nvidia-inference-server)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = params["name"]
    version = params["version"]
    deploy_name = f"{name}-{version}"
    labels = {"app": name, "version": version}

    resources: Dict[str, Any] = {}
    if params["tpu_chips"]:
        resources = {"limits": {"google.com/tpu": params["tpu_chips"]}}

    env = {
        "KFTPU_MODEL_BASE_PATH": params["model_base_path"],
        "KFTPU_REST_PORT": str(params["rest_port"]),
        "KFTPU_GRPC_PORT": str(params["grpc_port"]),
        "KFTPU_BATCH_TIMEOUT_MS": str(params["batch_timeout_ms"]),
        "KFTPU_MAX_BATCH_SIZE": str(params["max_batch_size"]),
    }
    pod = o.pod_spec([
        o.container(
            "server",
            params["image"],
            command=["python", "-m", "kubeflow_tpu.serving.server"],
            env=env,
            ports=[params["rest_port"], params["grpc_port"]],
            resources=resources,
        )
    ])
    deploy = o.deployment(
        deploy_name, ns, pod, replicas=params["replicas"], labels=labels,
    )
    svc = o.service(
        name,
        ns,
        {"app": name},  # selects every version; weights via per-version replicas
        [
            {"name": "rest", "port": params["rest_port"],
             "targetPort": params["rest_port"]},
            {"name": "grpc", "port": params["grpc_port"],
             "targetPort": params["grpc_port"]},
        ],
        labels={"app": name},
        annotations={
            "prometheus.io/scrape": "true",
            "prometheus.io/path": "/metrics",
            "prometheus.io/port": str(params["rest_port"]),
        },
    )
    return [deploy, svc]
