"""Ingress gateway component: edge proxy Deployment + routes.

Replaces the reference's Ambassador API gateway
(``/root/reference/kubeflow/common/ambassador.libsonnet:152-179``) and the
IAP/basic-auth ingress pair (``/root/reference/kubeflow/gcp/iap.libsonnet``,
``basic-auth-ingress``): one in-framework reverse proxy
(:mod:`kubeflow_tpu.edge.proxy`) that authenticates at the edge via the
gatekeeper and routes prefixes to the platform services. With
``use_istio`` it additionally renders an Istio Gateway + VirtualServices
carrying the same routes for mesh environments.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.components.edge import INGRESS_POD_LABELS
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "port": 8080,
    "replicas": 1,
    "hostname": "*",
    "use_istio": False,
    # GCP Cloud IAP in front of the gateway (the reference's iap.libsonnet
    # envoy+ESP stack, collapsed onto GKE-native BackendConfig IAP): renders
    # Ingress + BackendConfig + optional ManagedCertificate, and switches
    # the proxy to trust IAP's authenticated-user header
    "use_iap": False,
    "iap_oauth_secret": "kftpu-oauth",   # Secret: client_id/client_secret
    "managed_cert_domain": "",           # e.g. kubeflow.example.com
    # prefix -> {service, port, stripPrefix}; merged over the built-ins
    "extra_routes": {},
    # fleet serving edge (docs/EDGE.md): prefix-affinity routing +
    # SLO-class shedding in front of the serving replicas. Off by
    # default — single-replica serving needs no ring.
    "fleet_edge": False,
    "fleet_port": 8088,
    "fleet_metrics_port": 8089,     # kftpu_edge_* exposition (scraped)
    "fleet_page_size": 16,          # MUST match the engines' kv_page_size
    "fleet_ring_vnodes": 64,
    "fleet_ring_load_factor": 1.25,
    # pages of prefix the router keys on: bounded hashing per request,
    # late-diverging shared-prefix prompts share a key; 0 = exact
    # whole-aligned-prefix keying (O(prompt) hashing, opt-in)
    "fleet_affinity_pages": 16,
    "fleet_queue_wait_slo_s": 1.0,
    "fleet_poll_s": 2.0,            # backend /metrics scrape interval
    # replicas' engine slot count: the exposition carries no slot
    # capacity, so without this the gate's queue-depth pressure signal
    # is off and only page exhaustion sheds
    "fleet_slots": 0,
    "fleet_slo_classes": {},        # name -> [rank, shed_at]; {} = built-ins
    "fleet_default_class": "",      # "" = standard, else lowest rank
    "fleet_replicas": {},           # replica name -> target URL
}

FLEET_EDGE_NAME = "kftpu-fleet-edge"

GATEWAY_NAME = "kftpu-ingressgateway"


def _routes(params: Dict[str, Any]) -> List[Dict[str, Any]]:
    routes = [
        {"prefix": "/login", "target": "http://gatekeeper:8085",
         "stripPrefix": False},
        {"prefix": "/logout", "target": "http://gatekeeper:8085",
         "stripPrefix": False},
        {"prefix": "/jupyter/", "target": "http://notebook-webapp",
         "stripPrefix": True},
        {"prefix": "/serving/", "target": "http://model-server:8500",
         "stripPrefix": True},
        {"prefix": "/deploy/", "target": "http://bootstrap:8086",
         "stripPrefix": True},
    ]
    if params.get("fleet_edge"):
        # the authenticated path into the fleet serving edge
        routes.append({"prefix": "/fleet/",
                       "target": f"http://{FLEET_EDGE_NAME}:"
                                 f"{params.get('fleet_port', 8088)}",
                       "stripPrefix": True})
    for prefix, spec in sorted((params.get("extra_routes") or {}).items()):
        routes.append({"prefix": prefix,
                       "target": f"http://{spec['service']}:"
                                 f"{spec.get('port', 80)}",
                       "stripPrefix": bool(spec.get("stripPrefix", True))})
    # catch-all last: the dashboard shell owns every unclaimed path
    routes.append({"prefix": "/", "target": "http://centraldashboard",
                   "stripPrefix": False})
    return routes


def istio_gateway(ns: str, hostname: str) -> o.Obj:
    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "Gateway",
        "metadata": o.metadata("kubeflow-gateway", ns),
        "spec": {
            "selector": {"istio": "ingressgateway"},
            "servers": [{
                "hosts": [hostname],
                "port": {"name": "http", "number": 80, "protocol": "HTTP"},
            }],
        },
    }


def istio_route(ns: str, name: str, prefix: str, service: str, port: int,
                strip: bool) -> o.Obj:
    http: Dict[str, Any] = {
        "match": [{"uri": {"prefix": prefix}}],
        "route": [{"destination": {
            "host": f"{service}.{ns}.svc.cluster.local",
            "port": {"number": port}}}],
    }
    if strip:
        http["rewrite"] = {"uri": "/"}
    return {
        "apiVersion": "networking.istio.io/v1beta1",
        "kind": "VirtualService",
        "metadata": o.metadata(name, ns),
        "spec": {"hosts": ["*"], "gateways": ["kubeflow-gateway"],
                 "http": [http]},
    }


# GCLB/IAP proxy + health-check source ranges (fixed, documented GCP CIDRs)
GCLB_SOURCE_RANGES = ("130.211.0.0/22", "35.191.0.0/16")


def iap_gateway_policy(ns: str, port: int) -> o.Obj:
    """NetworkPolicy: in IAP mode the gateway accepts traffic ONLY from the
    Google load balancer ranges. This is what makes trusting the IAP
    identity header sound — without it any in-cluster pod could forge
    ``X-Goog-Authenticated-User-Email`` and impersonate anyone."""
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": o.metadata(f"{GATEWAY_NAME}-glb-only", ns),
        "spec": {
            "podSelector": {"matchLabels": dict(INGRESS_POD_LABELS)},
            "policyTypes": ["Ingress"],
            "ingress": [{
                "from": [{"ipBlock": {"cidr": c}}
                         for c in GCLB_SOURCE_RANGES],
                "ports": [{"protocol": "TCP", "port": port}],
            }],
        },
    }


def iap_backend_config(ns: str, oauth_secret: str) -> o.Obj:
    """GKE BackendConfig enabling Cloud IAP on the gateway's backend —
    the whole envoy+JWT-check deployment of ``iap.libsonnet`` collapsed
    into the load balancer (``iap.libsonnet:1-100`` wires the same OAuth
    client credentials into ESP)."""
    return {
        "apiVersion": "cloud.google.com/v1",
        "kind": "BackendConfig",
        "metadata": o.metadata(GATEWAY_NAME, ns),
        "spec": {"iap": {
            "enabled": True,
            "oauthclientCredentials": {"secretName": oauth_secret},
        }},
    }


def iap_ingress(ns: str, domain: str) -> List[o.Obj]:
    """GCLB Ingress → gateway Service (+ ManagedCertificate when a domain
    is configured; the reference used cloud-endpoints + cert jobs)."""
    annotations = {"kubernetes.io/ingress.class": "gce"}
    out: List[o.Obj] = []
    if domain:
        annotations["networking.gke.io/managed-certificates"] = GATEWAY_NAME
        out.append({
            "apiVersion": "networking.gke.io/v1",
            "kind": "ManagedCertificate",
            "metadata": o.metadata(GATEWAY_NAME, ns),
            "spec": {"domains": [domain]},
        })
    out.insert(0, {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "Ingress",
        "metadata": o.metadata(GATEWAY_NAME, ns, annotations=annotations),
        "spec": {"defaultBackend": {"service": {
            "name": GATEWAY_NAME, "port": {"number": 80}}}},
    })
    return out


@register("gateway", DEFAULTS,
          "Edge reverse proxy + routes (ambassador / IAP-envoy parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    routes = _routes(params)
    env = {
        "KFTPU_EDGE_PORT": str(params["port"]),
        "KFTPU_VERIFY_URL": "http://gatekeeper:8085/verify",
        "KFTPU_ROUTES": json.dumps(routes),
    }
    svc_annotations: Dict[str, str] = {}
    if params["use_iap"]:
        # identity comes from IAP's header, not the gatekeeper cookie; the
        # GCLB is the only path in (NEG annotation pins container-native LB)
        env["KFTPU_EDGE_AUTH_MODE"] = "iap"
        env.pop("KFTPU_VERIFY_URL")
        svc_annotations = {
            "cloud.google.com/neg": '{"ingress": true}',
            "cloud.google.com/backend-config":
                json.dumps({"default": GATEWAY_NAME}),
        }
    pod = o.pod_spec([
        o.container(
            GATEWAY_NAME,
            params["image"],
            command=["python", "-m", "kubeflow_tpu.edge.proxy"],
            env=env,
            ports=[params["port"]],
        )
    ])
    out: List[o.Obj] = [
        o.deployment(GATEWAY_NAME, ns, pod, replicas=params["replicas"],
                     labels=dict(INGRESS_POD_LABELS)),
        o.service(GATEWAY_NAME, ns, dict(INGRESS_POD_LABELS),
                  [{"name": "http", "port": 80,
                    "targetPort": params["port"]}],
                  labels=dict(INGRESS_POD_LABELS),
                  annotations=svc_annotations or None),
    ]
    if params["fleet_edge"]:
        # the fleet serving edge rides the gateway component: same
        # trust domain (behind the auth edge), its own Deployment so
        # routing capacity scales apart from the auth proxy
        fleet_env = {
            "KFTPU_FLEET_PORT": str(params["fleet_port"]),
            "KFTPU_FLEET_METRICS_PORT": str(params["fleet_metrics_port"]),
            "KFTPU_FLEET_PAGE_SIZE": str(params["fleet_page_size"]),
            "KFTPU_RING_VNODES": str(params["fleet_ring_vnodes"]),
            "KFTPU_RING_LOAD_FACTOR":
                str(params["fleet_ring_load_factor"]),
            "KFTPU_AFFINITY_PAGES": str(params["fleet_affinity_pages"]),
            "KFTPU_QUEUE_WAIT_SLO_S":
                str(params["fleet_queue_wait_slo_s"]),
            "KFTPU_FLEET_POLL_S": str(params["fleet_poll_s"]),
            "KFTPU_FLEET_SLOTS": str(params["fleet_slots"]),
            "KFTPU_FLEET_REPLICAS": json.dumps(params["fleet_replicas"]),
        }
        if params["fleet_slo_classes"]:
            fleet_env["KFTPU_SLO_CLASSES"] = json.dumps(
                params["fleet_slo_classes"])
        if params["fleet_default_class"]:
            fleet_env["KFTPU_SLO_DEFAULT_CLASS"] = \
                params["fleet_default_class"]
        fleet_pod = o.pod_spec([
            o.container(
                FLEET_EDGE_NAME,
                params["image"],
                command=["python", "-m", "kubeflow_tpu.edge.fleet"],
                env=fleet_env,
                ports=[params["fleet_port"],
                       params["fleet_metrics_port"]],
            )
        ])
        out.append(o.deployment(FLEET_EDGE_NAME, ns, fleet_pod,
                                labels={"app": FLEET_EDGE_NAME}))
        # prometheus.io annotations: the monitoring component derives
        # its scrape targets from these, so the shed/pressure series
        # reach the tsdb in a real deployment, not only in-process
        out.append(o.service(
            FLEET_EDGE_NAME, ns, {"app": FLEET_EDGE_NAME},
            [{"name": "http", "port": params["fleet_port"],
              "targetPort": params["fleet_port"]},
             {"name": "metrics", "port": params["fleet_metrics_port"],
              "targetPort": params["fleet_metrics_port"]}],
            labels={"app": FLEET_EDGE_NAME},
            annotations={
                "prometheus.io/scrape": "true",
                "prometheus.io/path": "/metrics",
                "prometheus.io/port": str(params["fleet_metrics_port"]),
            }))
    if params["use_iap"]:
        out.append(iap_backend_config(ns, params["iap_oauth_secret"]))
        out.extend(iap_ingress(ns, params["managed_cert_domain"]))
        out.append(iap_gateway_policy(ns, params["port"]))
    if params["use_istio"]:
        out.append(istio_gateway(ns, params["hostname"]))
        for r in routes:
            if r["prefix"] == "/":
                name, service, port = "kftpu-dashboard", "centraldashboard", 80
            else:
                service, _, port_s = r["target"][len("http://"):].partition(":")
                port = int(port_s or 80)
                name = "kftpu-" + r["prefix"].strip("/").replace("/", "-")
            out.append(istio_route(ns, name, r["prefix"], service, port,
                                   r["stripPrefix"] and r["prefix"] != "/"))
    return out
