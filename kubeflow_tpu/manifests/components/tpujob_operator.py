"""TpuJob operator component: CRD + RBAC + operator Deployment + metrics Service.

The single job operator replacing the reference's whole operator family —
TFJob (``/root/reference/kubeflow/tf-training/tf-job-operator.libsonnet``),
PyTorchJob, MPIJob, MXJob, ChainerJob, PaddleJob. Its manifest surface keeps
the TFJob package's ergonomics: namespace-vs-cluster scope (libsonnet
:216-227), gang-scheduling flag adding podgroup RBAC (:107-109,268-277),
prometheus scrape annotations on the metrics Service (:180-184) — mapped
onto SPMD/TPU-slice semantics.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

GROUP = "kubeflow-tpu.org"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
TPUJOB_KIND = "TpuJob"
TPUJOB_PLURAL = "tpujobs"

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/operator:v1alpha1",
    "cluster_scope": True,
    "gang_scheduling": True,
    "monitoring_port": 8443,
    "replicas": 1,
}


def tpujob_crd() -> o.Obj:
    return o.crd(
        TPUJOB_PLURAL,
        GROUP,
        TPUJOB_KIND,
        versions=(VERSION,),
        short_names=("tj",),
        printer_columns=(
            {"name": "State", "type": "string",
             "jsonPath": ".status.phase"},
            {"name": "Slices", "type": "integer",
             "jsonPath": ".spec.slices"},
            {"name": "Age", "type": "date",
             "jsonPath": ".metadata.creationTimestamp"},
        ),
    )


@register("tpujob-operator", DEFAULTS,
          "Slice-aware TpuJob operator (replaces tf/pytorch/mpi operator family)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "tpujob-operator"
    rules = [
        {"apiGroups": [GROUP], "resources": [TPUJOB_PLURAL,
                                             f"{TPUJOB_PLURAL}/status"],
         "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "services", "events",
                                          "configmaps"],
         "verbs": ["*"]},
        {"apiGroups": ["apps"], "resources": ["statefulsets"], "verbs": ["*"]},
    ]
    if params["gang_scheduling"]:
        rules.append({
            "apiGroups": ["scheduling.k8s.io", "scheduling.sigs.k8s.io"],
            "resources": ["podgroups", "priorityclasses"],
            "verbs": ["*"],
        })

    env = {
        "KFTPU_OPERATOR_NAMESPACE": "" if params["cluster_scope"] else ns,
        "KFTPU_GANG_SCHEDULING": str(params["gang_scheduling"]).lower(),
        "KFTPU_MONITORING_PORT": str(params["monitoring_port"]),
    }
    pod = o.pod_spec(
        [o.container(
            name,
            params["image"],
            command=["python", "-m", "kubeflow_tpu.operators.tpujob"],
            env=env,
            ports=[params["monitoring_port"]],
        )],
        service_account_name=name,
    )
    metrics_svc = o.service(
        name,
        ns,
        {"app": name},
        [{"name": "monitoring-port", "port": params["monitoring_port"],
          "targetPort": params["monitoring_port"]}],
        annotations={
            "prometheus.io/scrape": "true",
            "prometheus.io/path": "/metrics",
            "prometheus.io/port": str(params["monitoring_port"]),
        },
    )
    return [
        tpujob_crd(),
        o.service_account(name, ns),
        o.cluster_role(name, rules),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod, replicas=params["replicas"]),
        metrics_svc,
    ]
