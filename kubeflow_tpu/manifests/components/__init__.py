"""Built-in platform components. Importing this package registers them all."""

from kubeflow_tpu.manifests.components import (  # noqa: F401
    application,
    auth,
    autoscaler,
    credentials,
    dashboard,
    dataprep,
    echo,
    gateway,
    inferencegraph,
    modelregistry,
    monitoring,
    notebooks,
    serving,
    storage,
    tenancy,
    tensorboard,
    tpujob_operator,
    tuning,
    usage,
    workflows,
)
