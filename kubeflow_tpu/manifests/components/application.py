"""Application component: CRD + aggregator controller + the deployment's
own Application CR.

Manifest parity with the reference's application package
(``/root/reference/kubeflow/application/application.libsonnet``): the
Application CRD, the controller that assembles grouped status, and one
Application CR describing THIS deployment — selecting on the
``app.kubernetes.io/part-of`` label every rendered object carries.
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import PART_OF_LABEL, register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "cluster_scope": True,
}


@register("application", DEFAULTS,
          "application CRD + aggregated platform health (sig-apps parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    from kubeflow_tpu.operators.application import application, application_crd

    ns = config.namespace
    name = "application-controller"
    rules = [
        {"apiGroups": ["kubeflow-tpu.org"],
         "resources": ["applications", "applications/status"], "verbs": ["*"]},
        {"apiGroups": ["apps"], "resources": ["deployments", "statefulsets"],
         "verbs": ["get", "list", "watch"]},
        {"apiGroups": [""],
         "resources": ["services", "pods", "configmaps", "secrets",
                       "serviceaccounts", "persistentvolumeclaims"],
         "verbs": ["get", "list", "watch"]},
    ]
    env = {"KFTPU_APPLICATION_NAMESPACE": "" if params["cluster_scope"] else ns}
    pod = o.pod_spec(
        [o.container(
            name, params["image"],
            command=["python", "-m", "kubeflow_tpu.operators.application"],
            env=env,
        )],
        service_account_name=name,
    )
    return [
        application_crd(),
        o.service_account(name, ns),
        o.cluster_role(name, rules),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod),
        # this deployment's own grouped-health CR
        application(
            config.name, ns,
            selector={PART_OF_LABEL: config.name},
            component_kinds=["Deployment", "StatefulSet", "Service"],
            descriptor={
                "type": "kubeflow-tpu",
                "components": [c.name for c in config.components],
            }),
    ]
