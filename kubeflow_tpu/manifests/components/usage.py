"""Anonymous usage-reporting component — spartakus-volunteer parity.

Reference: ``/root/reference/kubeflow/common/spartakus.libsonnet``
(ClusterRole reading nodes + Deployment with a random ``cluster-id``
arg, gated by ``reportUsage``). Opt-out: the component renders nothing
when ``enabled`` is false, and the report carries only anonymous coarse
facts (``kubeflow_tpu/utils/usage.py``).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "enabled": True,
    "image": "kubeflow-tpu/platform:v1alpha1",
    "collector_url": "",      # empty = reporter idles (nothing sent)
    "cluster_id": "",         # empty = random uuid at render time
    "interval_hours": 24,
}


@register("usage-reporting", DEFAULTS,
          "Anonymous opt-out usage reporting (spartakus parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    if not params["enabled"]:
        return []
    ns = config.namespace
    name = "usage-reporter"
    # render must be idempotent: a fresh uuid4 per render would diff every
    # generate, roll the Deployment on each apply, and reset the collector's
    # longitudinal identity. Derive a stable id from the deployment identity
    # instead (uuid5 — not reversible to anything not already anonymous).
    cluster_id = params["cluster_id"] or str(uuid.uuid5(
        uuid.NAMESPACE_DNS, f"kftpu.{config.name}.{ns}"))
    pod = o.pod_spec(
        [o.container(
            name, params["image"],
            command=["python", "-m", "kubeflow_tpu.utils.usage"],
            env={
                "KFTPU_USAGE_COLLECTOR_URL": params["collector_url"],
                "KFTPU_USAGE_CLUSTER_ID": cluster_id,
            },
        )],
        service_account_name=name,
    )
    return [
        o.service_account(name, ns),
        o.cluster_role(name, [
            {"apiGroups": [""], "resources": ["nodes"],
             "verbs": ["get", "list"]},
        ]),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod),
    ]
