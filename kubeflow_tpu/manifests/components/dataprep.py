"""DataPrep component: DataPrepJob CRD + operator Deployment + RBAC.

Manifest parity with the reference's spark package — operator Deployment,
CRD, service account and RBAC for pod management
(``/root/reference/kubeflow/spark/all.libsonnet``) — recast as the
framework's batch map/reduce operator
(:mod:`kubeflow_tpu.operators.dataprep`).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "kubeflow-tpu/platform:v1alpha1",
    "cluster_scope": True,
}


@register("dataprep", DEFAULTS,
          "batch data-preparation operator (spark parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    from kubeflow_tpu.operators.dataprep import dataprep_crd

    ns = config.namespace
    name = "dataprep-operator"
    rules = [
        {"apiGroups": ["kubeflow-tpu.org"], "resources": ["dataprepjobs",
         "dataprepjobs/status"], "verbs": ["*"]},
        {"apiGroups": [""], "resources": ["pods", "events"], "verbs": ["*"]},
    ]
    env = {"KFTPU_DATAPREP_NAMESPACE": "" if params["cluster_scope"] else ns}
    pod = o.pod_spec(
        [o.container(
            name, params["image"],
            command=["python", "-m", "kubeflow_tpu.operators.dataprep"],
            env=env,
        )],
        service_account_name=name,
    )
    return [
        dataprep_crd(),
        o.service_account(name, ns),
        o.cluster_role(name, rules),
        o.cluster_role_binding(name, name, name, ns),
        o.deployment(name, ns, pod),
    ]
