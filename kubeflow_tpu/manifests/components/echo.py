"""Echo server component — the route-debugging tool.

Parity with the reference's echo-server (``/root/reference/kubeflow/
common/echo-server.libsonnet``): a trivial Deployment + Service that
reflects request details, used to verify gateway/edge routing before
pointing it at real services. The container runs the framework's own
echo module (no external image needed).
"""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "name": "echo-server",
    "image": "kubeflow-tpu/platform:v1alpha1",
    "port": 8080,
    "replicas": 1,
}


@register("echo-server", DEFAULTS,
          "request-echo service for route debugging (echo-server parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = params["name"]
    pod = o.pod_spec([o.container(
        name, params["image"],
        command=["python", "-m", "kubeflow_tpu.utils.echo"],
        env={"KFTPU_ECHO_PORT": str(params["port"])},
        ports=[params["port"]],
    )])
    return [
        o.deployment(name, ns, pod, replicas=params["replicas"]),
        o.service(name, ns, {"app": name},
                  [{"name": "http", "port": params["port"],
                    "targetPort": params["port"]}],
                  labels={"app": name}),
    ]
