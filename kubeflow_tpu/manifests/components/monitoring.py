"""Cluster monitoring: Prometheus scraper + optional Stackdriver bridge.

Reference: ``/root/reference/kubeflow/gcp/prometheus.libsonnet`` — a
Prometheus Deployment (nodes/services/endpoints/pods read RBAC, k8s
service-discovery scrape config) whose ``stackdriver-prometheus-sidecar``
exports to Cloud Monitoring. Here the scrape targets are the framework's
own ``serve_metrics`` endpoints (every component Service annotates
``prometheus.io/scrape``), and the sidecar renders only when a GCP
project is configured.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import yaml

from kubeflow_tpu.config.deployment import ComponentSpec, DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

log = logging.getLogger(__name__)

DEFAULTS: Dict[str, Any] = {
    "image": "prom/prometheus:v2.45.0",
    "sidecar_image": "gcr.io/stackdriver-prometheus/stackdriver-prometheus-sidecar:0.10.1",
    "port": 9090,
    "scrape_interval": "30s",
    # non-empty project enables the Stackdriver export sidecar
    "project": "",
    "cluster": "",
    "zone": "",
    "retention": "6h",
}


def scrape_targets(config: Optional[DeploymentConfig] = None
                   ) -> Dict[str, str]:
    """Static scrape-target map (target name → metrics URL), derived by
    rendering components and reading the ``prometheus.io/*``
    annotations off their Services.

    This is the ONE source of scrape wiring: :func:`scrape_config`
    renders it into the deployed prometheus ConfigMap as a static job,
    and the in-process :class:`kubeflow_tpu.obs.scrape.Scraper`
    defaults its target list to it — the manifest and the scraper
    cannot drift (the TPU004 consistency stance, applied at runtime
    because these URLs are constructed, not literal).

    With a ``config`` that enables components, exactly the DEPLOYED
    component set is rendered, with its per-component param overrides —
    a port override reaches the target URL, and a disabled component
    never becomes a dead target. Without one (the dev/in-process
    default), every registered component renders with defaults;
    components whose defaults cannot render standalone are skipped
    (they cannot be scraped by default either)."""
    from kubeflow_tpu.manifests.registry import (
        list_components,
        render_component,
    )

    cfg = config if config is not None else DeploymentConfig(
        name="scrape-discovery")
    specs = (list(cfg.components) if cfg.components
             else [ComponentSpec(c.name) for c in list_components()])
    out: Dict[str, str] = {}
    for spec in specs:
        if spec.name == "monitoring":
            # never render ourselves: render() calls scrape_config()
            # calls scrape_targets() — recursing here would nest to the
            # stack limit (and prometheus does not scrape itself anyway)
            continue
        try:
            objs = render_component(cfg, spec)
        except Exception as e:  # noqa: BLE001 — default-unrenderable
            log.debug("scrape_targets: skipping %s: %s", spec.name, e)
            continue
        for obj in objs:
            if obj.get("kind") != "Service":
                continue
            ann = (obj.get("metadata", {}).get("annotations") or {})
            if ann.get("prometheus.io/scrape") != "true":
                continue
            svc = obj["metadata"]["name"]
            port = ann.get("prometheus.io/port")
            if not port:
                ports = obj.get("spec", {}).get("ports") or [{}]
                port = str(ports[0].get("port", 80))
            path = ann.get("prometheus.io/path", "/metrics")
            out[svc] = f"http://{svc}:{port}{path}"
    return out


def scrape_config(interval: str,
                  targets: Optional[Dict[str, str]] = None) -> str:
    """Pod-annotation service discovery, the libsonnet scrape shape —
    plus the framework's own static target job (:func:`scrape_targets`)
    so the deployed prometheus and the in-process scraper share one
    target list."""
    if targets is None:
        targets = scrape_targets()
    # group by metrics path: a prometheus job has ONE metrics_path, and
    # flattening every target onto /metrics would silently diverge from
    # the per-annotation paths the in-process Scraper honors — exactly
    # the drift the shared target list exists to rule out
    by_path: Dict[str, List[str]] = {}
    for url in targets.values():
        rest = url.split("://", 1)[-1]   # tolerate scheme-less targets
        hostport, slash, path = rest.partition("/")
        # a URL with an explicit path keeps it VERBATIM (including a
        # bare trailing "/"); only a pathless target defaults — the
        # in-process Scraper fetches the same URL, so any rewrite here
        # is exactly the manifest/scraper drift this list rules out
        by_path.setdefault(("/" + path) if slash else "/metrics",
                           []).append(hostport)
    static_jobs = [{
        "job_name": ("kftpu-components-static" if path == "/metrics"
                     else "kftpu-components-static-"
                     + (path.strip("/").replace("/", "-") or "root")),
        "metrics_path": path,
        "static_configs": [{"targets": sorted(hosts)}],
    } for path, hosts in sorted(by_path.items())]
    return yaml.safe_dump({
        "global": {"scrape_interval": interval},
        "scrape_configs": [{
            "job_name": "kftpu-components",
            "kubernetes_sd_configs": [{"role": "endpoints"}],
            "relabel_configs": [
                {"source_labels":
                     ["__meta_kubernetes_service_annotation_prometheus_io_scrape"],
                 "action": "keep", "regex": "true"},
                # honor the per-service metrics port/path annotations the
                # framework's Services set (multi-port services would
                # otherwise be scraped on every endpoint port)
                {"source_labels":
                     ["__address__",
                      "__meta_kubernetes_service_annotation_prometheus_io_port"],
                 "action": "replace",
                 "regex": r"([^:]+)(?::\d+)?;(\d+)",
                 "replacement": "$1:$2",
                 "target_label": "__address__"},
                {"source_labels":
                     ["__meta_kubernetes_service_annotation_prometheus_io_path"],
                 "action": "replace", "regex": "(.+)",
                 "target_label": "__metrics_path__"},
                {"source_labels": ["__meta_kubernetes_namespace"],
                 "action": "replace", "target_label": "namespace"},
                {"source_labels": ["__meta_kubernetes_service_name"],
                 "action": "replace", "target_label": "service"},
            ],
        }] + static_jobs,
        # the same component endpoints as SD-free static jobs (one per
        # metrics path): scrape keeps working before RBAC/SD converges,
        # and the target list is pinned to the components' annotations
    }, sort_keys=False)


@register("monitoring", DEFAULTS,
          "Prometheus scraper + optional Stackdriver bridge (gcp parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "prometheus"
    containers = [o.container(
        name, params["image"],
        args=["--config.file=/etc/prometheus/prometheus.yaml",
              "--storage.tsdb.path=/prometheus",
              f"--storage.tsdb.retention.time={params['retention']}"],
        ports=[params["port"]],
        volume_mounts=[{"name": "config", "mountPath": "/etc/prometheus"},
                       {"name": "data", "mountPath": "/prometheus"}],
    )]
    # the component param wins; otherwise the platform's project flows
    # through (the gcp-tpu preset user fills platform_params.project once)
    project = params["project"] or config.platform_params.get("project", "")
    if project:
        # the sidecar tails Prometheus's WAL, so both containers share the
        # /prometheus data volume (the libsonnet pairs them the same way)
        containers.append(o.container(
            "stackdriver-sidecar", params["sidecar_image"],
            args=[f"--stackdriver.project-id={project}",
                  "--stackdriver.kubernetes.location="
                  f"{params['zone'] or config.platform_params.get('zone', '')}",
                  "--stackdriver.kubernetes.cluster-name="
                  f"{params['cluster'] or config.platform_params.get('cluster', '')}",
                  "--prometheus.wal-directory=/prometheus/wal"],
            volume_mounts=[{"name": "data", "mountPath": "/prometheus"}],
        ))
    pod = o.pod_spec(
        containers,
        service_account_name=name,
        volumes=[{"name": "config", "configMap": {"name": name}},
                 {"name": "data", "emptyDir": {}}],
    )
    return [
        o.service_account(name, ns),
        o.cluster_role(name, [
            {"apiGroups": [""],
             "resources": ["nodes", "nodes/proxy", "services",
                           "endpoints", "pods"],
             "verbs": ["get", "list", "watch"]},
        ]),
        o.cluster_role_binding(name, name, name, ns),
        o.config_map(name, ns,
                     {"prometheus.yaml":
                      # the LIVE deployment's component set + params
                      # flow into the static job (not the registry-wide
                      # defaults), so a disabled component never becomes
                      # a dead target and a port override is honored
                      scrape_config(params["scrape_interval"],
                                    scrape_targets(config))}),
        o.deployment(name, ns, pod),
        o.service(name, ns, {"app": name},
                  [{"name": "http", "port": int(params["port"]),
                    "targetPort": int(params["port"])}]),
    ]
