"""Cluster monitoring: Prometheus scraper + optional Stackdriver bridge.

Reference: ``/root/reference/kubeflow/gcp/prometheus.libsonnet`` — a
Prometheus Deployment (nodes/services/endpoints/pods read RBAC, k8s
service-discovery scrape config) whose ``stackdriver-prometheus-sidecar``
exports to Cloud Monitoring. Here the scrape targets are the framework's
own ``serve_metrics`` endpoints (every component Service annotates
``prometheus.io/scrape``), and the sidecar renders only when a GCP
project is configured.
"""

from __future__ import annotations

from typing import Any, Dict, List

import yaml

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {
    "image": "prom/prometheus:v2.45.0",
    "sidecar_image": "gcr.io/stackdriver-prometheus/stackdriver-prometheus-sidecar:0.10.1",
    "port": 9090,
    "scrape_interval": "30s",
    # non-empty project enables the Stackdriver export sidecar
    "project": "",
    "cluster": "",
    "zone": "",
    "retention": "6h",
}


def scrape_config(interval: str) -> str:
    """Pod-annotation service discovery, the libsonnet scrape shape."""
    return yaml.safe_dump({
        "global": {"scrape_interval": interval},
        "scrape_configs": [{
            "job_name": "kftpu-components",
            "kubernetes_sd_configs": [{"role": "endpoints"}],
            "relabel_configs": [
                {"source_labels":
                     ["__meta_kubernetes_service_annotation_prometheus_io_scrape"],
                 "action": "keep", "regex": "true"},
                # honor the per-service metrics port/path annotations the
                # framework's Services set (multi-port services would
                # otherwise be scraped on every endpoint port)
                {"source_labels":
                     ["__address__",
                      "__meta_kubernetes_service_annotation_prometheus_io_port"],
                 "action": "replace",
                 "regex": r"([^:]+)(?::\d+)?;(\d+)",
                 "replacement": "$1:$2",
                 "target_label": "__address__"},
                {"source_labels":
                     ["__meta_kubernetes_service_annotation_prometheus_io_path"],
                 "action": "replace", "regex": "(.+)",
                 "target_label": "__metrics_path__"},
                {"source_labels": ["__meta_kubernetes_namespace"],
                 "action": "replace", "target_label": "namespace"},
                {"source_labels": ["__meta_kubernetes_service_name"],
                 "action": "replace", "target_label": "service"},
            ],
        }],
    }, sort_keys=False)


@register("monitoring", DEFAULTS,
          "Prometheus scraper + optional Stackdriver bridge (gcp parity)")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "prometheus"
    containers = [o.container(
        name, params["image"],
        args=["--config.file=/etc/prometheus/prometheus.yaml",
              "--storage.tsdb.path=/prometheus",
              f"--storage.tsdb.retention.time={params['retention']}"],
        ports=[params["port"]],
        volume_mounts=[{"name": "config", "mountPath": "/etc/prometheus"},
                       {"name": "data", "mountPath": "/prometheus"}],
    )]
    # the component param wins; otherwise the platform's project flows
    # through (the gcp-tpu preset user fills platform_params.project once)
    project = params["project"] or config.platform_params.get("project", "")
    if project:
        # the sidecar tails Prometheus's WAL, so both containers share the
        # /prometheus data volume (the libsonnet pairs them the same way)
        containers.append(o.container(
            "stackdriver-sidecar", params["sidecar_image"],
            args=[f"--stackdriver.project-id={project}",
                  "--stackdriver.kubernetes.location="
                  f"{params['zone'] or config.platform_params.get('zone', '')}",
                  "--stackdriver.kubernetes.cluster-name="
                  f"{params['cluster'] or config.platform_params.get('cluster', '')}",
                  "--prometheus.wal-directory=/prometheus/wal"],
            volume_mounts=[{"name": "data", "mountPath": "/prometheus"}],
        ))
    pod = o.pod_spec(
        containers,
        service_account_name=name,
        volumes=[{"name": "config", "configMap": {"name": name}},
                 {"name": "data", "emptyDir": {}}],
    )
    return [
        o.service_account(name, ns),
        o.cluster_role(name, [
            {"apiGroups": [""],
             "resources": ["nodes", "nodes/proxy", "services",
                           "endpoints", "pods"],
             "verbs": ["get", "list", "watch"]},
        ]),
        o.cluster_role_binding(name, name, name, ns),
        o.config_map(name, ns,
                     {"prometheus.yaml":
                      scrape_config(params["scrape_interval"])}),
        o.deployment(name, ns, pod),
        o.service(name, ns, {"app": name},
                  [{"name": "http", "port": int(params["port"]),
                    "targetPort": int(params["port"])}]),
    ]
