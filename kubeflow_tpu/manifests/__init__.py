"""Manifest engine: component registry + renderers (ksonnet-layer replacement)."""

from kubeflow_tpu.manifests.registry import (  # noqa: F401
    Component,
    get_component,
    list_components,
    merge_params,
    render_all,
    render_component,
    register,
)
