"""Release tooling: enumerate + retag component images.

Reference parity: ``/root/reference/releasing/`` (image build/tag
scripts) and the per-component image params threaded through the ksonnet
configs. Here every component exposes its image as a typed param, so a
release is a config rewrite: enumerate the images a deployment renders,
then pin a new registry/tag across all components in ``app.yaml`` —
``ctl images <app> [--retag TAG] [--registry REG]``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.manifests.registry import get_component, render_all


def rendered_images(config: DeploymentConfig) -> List[Tuple[str, str, str]]:
    """(kind/name, container, image) for every container the config renders,
    initContainers included — the ground truth of what a release ships."""
    out = []
    for obj in render_all(config):
        tmpl = obj.get("spec", {}).get("template", {})
        pod = tmpl.get("spec", {}) if tmpl else obj.get("spec", {})
        where = f"{obj['kind']}/{obj.get('metadata', {}).get('name', '')}"
        for key in ("initContainers", "containers"):
            for c in pod.get(key, []) or []:
                if "image" in c:
                    out.append((where, c["name"], c["image"]))
    return out


def _retag(image: str, tag: str, registry: str = "") -> str:
    """Pin ``image`` to ``tag`` (and optionally a new registry prefix).

    Digest-pinned references (``repo/img@sha256:...``) are returned
    unchanged — rewriting the digest's hex to a tag would produce an
    invalid reference, and silently replacing a content pin with a
    mutable tag would defeat the pin."""
    if "@" in image:
        return image
    # split a trailing :tag — but not a registry :port (which precedes a /)
    base = image
    if ":" in image.rsplit("/", 1)[-1]:
        base = image.rsplit(":", 1)[0]
    if registry:
        base = f"{registry.rstrip('/')}/{base.rsplit('/', 1)[-1]}"
    return f"{base}:{tag}"


def retag_config(config: DeploymentConfig, tag: str,
                 registry: str = "") -> Dict[str, str]:
    """Pin every component's image params to ``tag`` in-place.

    Any param named ``image`` or ``*_image`` counts. Returns
    {old: new} for reporting. The caller persists the config."""
    changes: Dict[str, str] = {}
    for spec in config.components:
        comp = get_component(spec.name)
        for key, default in comp.defaults.items():
            if key != "image" and not key.endswith("_image"):
                continue
            current = spec.params.get(key, default)
            if not isinstance(current, str) or not current:
                continue
            new = _retag(current, tag, registry)
            if new != current:
                spec.params[key] = new
                changes[current] = new
    return changes
