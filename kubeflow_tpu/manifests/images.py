"""Release tooling: enumerate + retag component images.

Reference parity: ``/root/reference/releasing/`` (image build/tag
scripts) and the per-component image params threaded through the ksonnet
configs. Here every component exposes its image as a typed param, so a
release is a config rewrite: enumerate the images a deployment renders,
then pin a new registry/tag across all components in ``app.yaml`` —
``ctl images <app> [--retag TAG] [--registry REG]``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.manifests.registry import get_component, render_all


def rendered_images(config: DeploymentConfig) -> List[Tuple[str, str, str]]:
    """(kind/name, container, image) for every container the config renders,
    initContainers included — the ground truth of what a release ships."""
    out = []
    for obj in render_all(config):
        tmpl = obj.get("spec", {}).get("template", {})
        pod = tmpl.get("spec", {}) if tmpl else obj.get("spec", {})
        where = f"{obj['kind']}/{obj.get('metadata', {}).get('name', '')}"
        for key in ("initContainers", "containers"):
            for c in pod.get(key, []) or []:
                if "image" in c:
                    out.append((where, c["name"], c["image"]))
    return out


def _strip_tag(image: str) -> str:
    """Drop a trailing ``:tag`` — but not a registry ``:port`` (which
    precedes a ``/``). Shared by retag and digest-pin rewrites."""
    if ":" in image.rsplit("/", 1)[-1]:
        return image.rsplit(":", 1)[0]
    return image


def _retag(image: str, tag: str, registry: str = "") -> str:
    """Pin ``image`` to ``tag`` (and optionally a new registry prefix).

    Digest-pinned references (``repo/img@sha256:...``) are returned
    unchanged — rewriting the digest's hex to a tag would produce an
    invalid reference, and silently replacing a content pin with a
    mutable tag would defeat the pin."""
    if "@" in image:
        return image
    base = _strip_tag(image)
    if registry:
        base = f"{registry.rstrip('/')}/{base.rsplit('/', 1)[-1]}"
    return f"{base}:{tag}"


def retag_config(config: DeploymentConfig, tag: str,
                 registry: str = "") -> Dict[str, str]:
    """Pin every component's image params to ``tag`` in-place.

    Any param named ``image`` or ``*_image`` counts. Returns
    {old: new} for reporting. The caller persists the config."""
    changes: Dict[str, str] = {}
    for spec in config.components:
        comp = get_component(spec.name)
        for key, default in comp.defaults.items():
            if key != "image" and not key.endswith("_image"):
                continue
            current = spec.params.get(key, default)
            if not isinstance(current, str) or not current:
                continue
            new = _retag(current, tag, registry)
            if new != current:
                spec.params[key] = new
                changes[current] = new
    return changes


def digest_map_from_cluster(client) -> Tuple[Dict[str, str], List[str]]:
    """``(image -> sha256 digest, ambiguous images)`` observed on the
    RUNNING cluster.

    Kubelet reports the resolved content digest of every pulled image in
    ``status.containerStatuses[].imageID`` — a registry-less resolver
    (reference parity: ``/root/reference/releasing/add_image_shas.py``
    queried gcloud; here the cluster itself is the source of truth, so
    pinning needs no registry egress). An image tag observed with TWO
    different digests (mid-rollout) is AMBIGUOUS: it is excluded from
    the map and listed, never silently resolved to whichever pod
    iterated first."""
    seen: Dict[str, set] = {}
    for pod in client.list("v1", "Pod"):
        statuses = (pod.get("status", {}).get("containerStatuses") or [])
        for cs in statuses:
            image, iid = cs.get("image"), cs.get("imageID", "")
            if image and "@sha256:" in iid:
                seen.setdefault(image, set()).add(
                    "sha256:" + iid.rsplit("@sha256:", 1)[1])
    ambiguous = sorted(i for i, ds in seen.items() if len(ds) > 1)
    return ({i: next(iter(ds)) for i, ds in seen.items()
             if len(ds) == 1}, ambiguous)


def _pin(image: str, digest: str) -> str:
    """``repo/img:tag`` -> ``repo/img@sha256:...`` (tag dropped: a
    digest reference is immutable; keeping the tag would be decorative
    and some runtimes reject tag+digest)."""
    return f"{_strip_tag(image)}@{digest}"


def pin_config(config: DeploymentConfig, digests: Dict[str, str]
               ) -> Tuple[Dict[str, str], List[str]]:
    """Rewrite every component image param to its content digest.

    Returns ``({old: new}, [unresolvable images])``. Already-pinned
    (``@``) refs are left alone. The caller persists the config and the
    lock manifest, after which every ``ctl generate`` renders immutable
    references — the reference's add_image_shas/apply_image_tags flow
    collapsed into one config rewrite."""
    changes: Dict[str, str] = {}
    missing: List[str] = []
    for spec in config.components:
        comp = get_component(spec.name)
        for key, default in comp.defaults.items():
            if key != "image" and not key.endswith("_image"):
                continue
            current = spec.params.get(key, default)
            if not isinstance(current, str) or not current or "@" in current:
                continue
            digest = digests.get(current)
            if digest is None:
                if current not in missing:
                    missing.append(current)
                continue
            new = _pin(current, digest)
            spec.params[key] = new
            changes[current] = new
    return changes, missing
