"""Automated image-bump proposals — the CI freshness bot.

Reference parity: ``/root/reference/py/kubeflow/kubeflow/ci/`` (the bot
that opened image-bump PRs whenever a component image was rebuilt) and
``/root/reference/releasing/auto-update/``. Their role: nobody should
hand-edit dozens of manifests when an image gets a new release — a bot
detects newer tags, rewrites the configs, and proposes the change for
review rather than applying it blind.

TPU-framework shape: component images are typed config params
(``manifests/images.py``), so a "bump PR" is a config rewrite plus a
review artifact —

1. :func:`scan_updates` — compare every image param of a deployment
   against a tag CATALOG (a YAML of ``image-base: [tags...]``, produced
   by your registry's listing job; no registry egress from here) using
   version-aware tag ordering.
2. :func:`apply_updates` — rewrite the config params in place.
3. :func:`propose_updates` — the bot entrypoint (``ctl images <app>
   --bump CATALOG``): scan, rewrite ``app.yaml``, emit a changelog
   (``image-bumps.md``), and — when the app dir lives in a git repo —
   commit the bump to a dedicated branch for review: the PR-equivalent
   in a forge-less cluster.

Schedule it with a CronWorkflow (:func:`autoupdate_cron_spec`) the same
way the reference ran its bot on Prow periodics.
"""

from __future__ import annotations

import dataclasses
import os
import re
import subprocess
import time
from typing import Any, Dict, List, Optional, Tuple

import yaml

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.manifests.images import _strip_tag
from kubeflow_tpu.manifests.registry import get_component


def _tag_of(image: str) -> Optional[str]:
    """The ``:tag`` of an image ref (None for untagged or digest-pinned
    refs — a content pin must never be silently replaced by a tag)."""
    if "@" in image:
        return None
    last = image.rsplit("/", 1)[-1]
    if ":" not in last:
        return None
    return last.rsplit(":", 1)[1]


def _tag_key(tag: str) -> Tuple:
    """Version-aware ordering key: numeric runs compare numerically
    (v1.10 > v1.9, 20200131 > 20190116), alpha runs lexically,
    pre-release words (rc/alpha/beta/dev) rank below everything. The
    terminator ``(0, -1)`` makes a bare release beat its own
    pre-releases (v1.2 > v1.2-rc1) while staying below extensions
    (v1.2 < v1.2.1). A leading ``v`` is stripped so v-prefixed and bare
    tags order together (v1.9 < 1.10, 2.0.0 > v1.0.0)."""
    tag = re.sub(r"^[vV](?=\d)", "", tag)
    parts: List[Tuple] = []
    for run in re.findall(r"\d+|[A-Za-z]+", tag):
        if run.isdigit():
            parts.append((0, int(run)))
        elif re.fullmatch(r"rc|alpha|beta|dev|pre|preview", run, re.I):
            parts.append((-1, run.lower()))
        else:
            parts.append((1, run.lower()))
    parts.append((0, -1))
    return tuple(parts)


def newer_tag(current: str, candidates: List[str]) -> Optional[str]:
    """The highest candidate strictly newer than ``current`` under
    version ordering; None when current is already newest. ``latest``
    and other non-versioned floating tags never win (bumping a pin to
    a floating tag would be a downgrade in reproducibility)."""
    floating = {"latest", "master", "main", "nightly"}
    cur = _tag_key(current)
    best = None
    for cand in candidates:
        if cand in floating or cand == current:
            continue
        if _tag_key(cand) > cur and (
                best is None or _tag_key(cand) > _tag_key(best)):
            best = cand
    return best


@dataclasses.dataclass
class ImageBump:
    component: str
    param: str
    image: str      # current full ref
    old_tag: str
    new_tag: str

    @property
    def new_image(self) -> str:
        return f"{_strip_tag(self.image)}:{self.new_tag}"


def scan_updates(config: DeploymentConfig,
                 catalog: Dict[str, List[str]]) -> List[ImageBump]:
    """Every image param with a strictly newer tag in ``catalog``
    (keys: image base without tag, values: available tags)."""
    bumps: List[ImageBump] = []
    for spec in config.components:
        comp = get_component(spec.name)
        for key, default in comp.defaults.items():
            if key != "image" and not key.endswith("_image"):
                continue
            current = spec.params.get(key, default)
            if not isinstance(current, str) or not current:
                continue
            tag = _tag_of(current)
            if tag is None:
                continue
            tags = catalog.get(_strip_tag(current))
            if not tags:
                continue
            new = newer_tag(tag, list(tags))
            if new:
                bumps.append(ImageBump(spec.name, key, current, tag, new))
    return bumps


def apply_updates(config: DeploymentConfig,
                  bumps: List[ImageBump]) -> Dict[str, str]:
    """Rewrite the bumped image params in place; returns {old: new}."""
    changes: Dict[str, str] = {}
    for b in bumps:
        spec = config.component(b.component)
        if spec is None:
            continue
        spec.params[b.param] = b.new_image
        changes[b.image] = b.new_image
    return changes


def _changelog(bumps: List[ImageBump]) -> str:
    when = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    lines = [f"# Image bumps — {when}", ""]
    for b in bumps:
        lines.append(f"- **{b.component}.{b.param}**: "
                     f"`{b.image}` → `{b.new_image}`")
    return "\n".join(lines) + "\n"


def _git(app_dir: str, *args: str) -> subprocess.CompletedProcess:
    return subprocess.run(["git", *args], cwd=app_dir,
                          capture_output=True, text=True, timeout=60)


def propose_updates(app_dir: str, catalog_path: str, *,
                    write: bool = False,
                    git_branch: Optional[str] = None) -> Dict[str, Any]:
    """The bot entrypoint. Scans ``<app_dir>/app.yaml`` against the tag
    catalog; with ``write`` rewrites the config and drops
    ``image-bumps.md`` beside it; with ``git_branch`` additionally
    commits the change to that branch (created from the current HEAD)
    when the app dir is inside a git work tree — the reviewable
    PR-equivalent: only the bump files are committed, and the original
    branch is checked out again afterwards, so the operator's working
    branch is untouched until the proposal is merged. A failed checkout
    is reported (``git_error``), never silently committed elsewhere.
    Returns a report dict (also what ``ctl images --bump`` prints)."""
    app_yaml = os.path.join(app_dir, "app.yaml")
    config = DeploymentConfig.load(app_yaml)
    with open(catalog_path) as f:
        catalog = yaml.safe_load(f) or {}
    if not isinstance(catalog, dict):
        raise ValueError(f"catalog {catalog_path} must map image base "
                         "-> [tags]")
    bumps = scan_updates(config, catalog)
    report: Dict[str, Any] = {
        "bumps": [dataclasses.asdict(b) for b in bumps],
        "written": False, "branch": None,
    }
    if not bumps or not write:
        return report
    apply_updates(config, bumps)
    config.save(app_yaml)
    log_path = os.path.join(app_dir, "image-bumps.md")
    with open(log_path, "w") as f:
        f.write(_changelog(bumps))
    report["written"] = True
    if git_branch:
        inside = _git(app_dir, "rev-parse", "--is-inside-work-tree")
        if inside.returncode == 0 and inside.stdout.strip() == "true":
            orig = _git(app_dir, "rev-parse",
                        "--abbrev-ref", "HEAD").stdout.strip()
            co = _git(app_dir, "checkout", "-B", git_branch)
            if co.returncode == 0:
                msg = (f"Bump {len(bumps)} component image"
                       f"{'s' if len(bumps) != 1 else ''}")
                # add (image-bumps.md may be untracked) + pathspec'd
                # commit: only the bump files, never whatever the
                # operator happened to have staged
                _git(app_dir, "add", "--", "app.yaml", "image-bumps.md")
                commit = _git(app_dir, "commit", "-m", msg, "--",
                              "app.yaml", "image-bumps.md")
                if commit.returncode == 0:
                    report["branch"] = git_branch
                else:
                    # a scheduled bot whose commits silently fail would
                    # look healthy forever — surface it
                    report["git_error"] = ("commit: " +
                                           (commit.stderr.strip() or
                                            commit.stdout.strip())[-200:])
                # PR semantics: the proposal lives on the review branch;
                # the working branch returns to where the operator was
                # (checkout restores their app.yaml on disk too)
                if orig and orig not in ("HEAD", git_branch):
                    back = _git(app_dir, "checkout", orig)
                    if back.returncode != 0:
                        report["git_error"] = (
                            f"checkout {orig} (restore): "
                            + back.stderr.strip()[-200:])
            else:
                log_msg = co.stderr.strip()[-200:]
                report["git_error"] = f"checkout -B {git_branch}: {log_msg}"
    return report


def autoupdate_cron_spec(app_dir: str, catalog_path: str, *,
                         schedule: str = "0 7 * * 1",
                         image: str = "kubeflow-tpu/ctl:latest"
                         ) -> Dict[str, Any]:
    """A CronWorkflow object that runs the bump bot on a schedule (the
    reference ran its bot as a Prow periodic;
    ``workflows/cron.py:scheduled_workflow`` is our scheduler)."""
    from kubeflow_tpu.workflows.cron import scheduled_workflow

    return scheduled_workflow(
        "image-autoupdate", "kubeflow",
        {"steps": [{
            "name": "bump",
            "type": "container",
            "image": image,
            "command": ["ctl", "images", app_dir, "--bump", catalog_path,
                        "--write", "--git-branch", "image-bumps"],
        }]},
        cron=schedule)
