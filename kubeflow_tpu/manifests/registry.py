"""Component registry: name -> manifest renderer.

The reference's equivalent is its ksonnet package library — each component a
jsonnet package with ``params+env`` defaults merged into prototypes
(``/root/reference/kubeflow/*/``), assembled per-deployment by the kustomize
package manager (``kustomize.go:561-642``). Here a component is a Python
function; params are validated against declared defaults; output is a list
of canonical k8s dicts that golden tests snapshot.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Mapping, Optional

from kubeflow_tpu.config.deployment import ComponentSpec, DeploymentConfig
from kubeflow_tpu.k8s.objects import Obj, namespace

Renderer = Callable[[DeploymentConfig, Dict[str, Any]], List[Obj]]


@dataclasses.dataclass(frozen=True)
class Component:
    name: str
    render: Renderer
    defaults: Mapping[str, Any]
    description: str = ""


_REGISTRY: Dict[str, Component] = {}


def register(
    name: str,
    defaults: Optional[Mapping[str, Any]] = None,
    description: str = "",
) -> Callable[[Renderer], Renderer]:
    def wrap(fn: Renderer) -> Renderer:
        if name in _REGISTRY:
            raise ValueError(f"component {name!r} already registered")
        _REGISTRY[name] = Component(name, fn, dict(defaults or {}), description)
        return fn

    return wrap


def get_component(name: str) -> Component:
    _ensure_builtins()
    if name not in _REGISTRY:
        known = ", ".join(sorted(_REGISTRY))
        raise KeyError(f"unknown component {name!r}; known: {known}")
    return _REGISTRY[name]


def list_components() -> List[Component]:
    _ensure_builtins()
    return sorted(_REGISTRY.values(), key=lambda c: c.name)


def merge_params(component: Component, overrides: Mapping[str, Any]) -> Dict[str, Any]:
    params = dict(component.defaults)
    unknown = set(overrides) - set(params)
    if unknown:
        raise ValueError(
            f"component {component.name!r}: unknown params {sorted(unknown)}; "
            f"valid: {sorted(params)}"
        )
    params.update(overrides)
    return params


def render_component(config: DeploymentConfig, spec: ComponentSpec) -> List[Obj]:
    comp = get_component(spec.name)
    params = merge_params(comp, spec.params)
    return comp.render(config, params)


PART_OF_LABEL = "app.kubernetes.io/part-of"


def render_all(config: DeploymentConfig) -> List[Obj]:
    """Render the full deployment: namespace first, then every component.

    Every object is stamped with the ``app.kubernetes.io/part-of`` label
    (kustomize commonLabels role): the Application aggregator selects on
    it and ``ctl gc`` prunes stale cluster objects by it.
    """
    config.validate()
    objs: List[Obj] = [namespace(config.namespace,
                                 labels={PART_OF_LABEL: config.name})]
    for spec in config.components:
        objs.extend(render_component(config, spec))
    for obj in objs:
        labels = obj.setdefault("metadata", {}).setdefault("labels", {})
        labels.setdefault(PART_OF_LABEL, config.name)
    return objs


def _ensure_builtins() -> None:
    """Import built-in component modules so their @register calls run."""
    from kubeflow_tpu.manifests import components  # noqa: F401
