"""Capacity-based MoE dispatch (GShard/Switch style) for expert parallelism.

The reference has no MoE/expert parallelism (SURVEY.md §2c: EP = "ABSENT").
The model zoo's default MoE path is exact dense top-k dispatch
(``kubeflow_tpu/models/transformer.py:MoeMlp``) — every expert sees every
token, masked. That is O(E) compute per token: fine for small E, wrong for
large E. This module is the capacity fast path: tokens are scattered into
per-expert buffers of static capacity C, experts run their FFN once over
(E, C, D), and results combine back weighted by router gates.

TPU-first details: everything is static-shaped einsums (dispatch/combine are
one-hot tensors — XLA maps them onto the MXU and, with the ``expert`` axis
sharded over the ``ep`` mesh group, inserts the AllToAll over ICI for the
scatter/gather automatically — the GSPMD MoE recipe). Tokens overflowing an
expert's capacity are dropped (contribute zero), the standard
Switch-Transformer trade; the auxiliary load-balance loss keeps drop rates
low.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp


def expert_capacity(n_tokens: int, n_experts: int, k: int,
                    capacity_factor: float, *, multiple_of: int = 8) -> int:
    """Static per-expert buffer size: cf · (tokens·k / E), padded up."""
    c = int(capacity_factor * n_tokens * k / n_experts) + 1
    return -(-c // multiple_of) * multiple_of


def capacity_dispatch(
    gate_logits: jnp.ndarray,  # (G, E) f32 router logits, G = flattened tokens
    k: int,
    capacity: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Build dispatch/combine tensors for top-k capacity routing.

    Returns (dispatch (G,E,C) bool-ish f32, combine (G,E,C) f32, aux_loss).
    Token t goes to its k chosen experts at the next free slot of each; slots
    past ``capacity`` drop. Priority is token order (lower t wins a slot),
    per expert-choice round: all k=0 choices are placed before k=1 choices,
    matching the GShard implementation.
    """
    G, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    weights, idx = jax.lax.top_k(probs, k)            # (G, K)
    # renormalize the kept top-k mass
    weights = weights / jnp.maximum(
        jnp.sum(weights, axis=-1, keepdims=True), 1e-9
    )

    dispatch = jnp.zeros((G, E, capacity), jnp.float32)
    combine = jnp.zeros((G, E, capacity), jnp.float32)
    used = jnp.zeros((E,), jnp.int32)  # slots consumed per expert so far
    for j in range(k):
        onehot = jax.nn.one_hot(idx[:, j], E, dtype=jnp.float32)  # (G, E)
        # position of each token within its expert's buffer this round
        pos_in_round = jnp.cumsum(onehot, axis=0) - onehot        # (G, E)
        pos = pos_in_round + used[None, :].astype(jnp.float32)
        keep = (pos < capacity).astype(jnp.float32) * onehot
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                              dtype=jnp.float32)                  # (G, E, C)
        dispatch = dispatch + keep[..., None] * slot
        combine = combine + (keep * weights[:, j:j + 1])[..., None] * slot
        used = used + jnp.sum(onehot, axis=0).astype(jnp.int32)

    # Switch-style load-balance aux: E · Σ_e (mean router prob)·(mean routed)
    density = jnp.mean(
        jax.nn.one_hot(idx[:, 0], E, dtype=jnp.float32), axis=0
    )
    mean_prob = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(density * mean_prob)
    return dispatch, combine, aux


def capacity_moe(
    x: jnp.ndarray,            # (G, D) flattened tokens
    gate_logits: jnp.ndarray,  # (G, E)
    expert_fn: Callable[[jnp.ndarray], jnp.ndarray],  # (E, C, D) -> (E, C, D')
    *,
    k: int,
    capacity_factor: float = 1.25,
    capacity: Optional[int] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Route → expert_fn over (E, C, D) buffers → combine. Returns (y, aux)."""
    G, D = x.shape
    E = gate_logits.shape[-1]
    C = capacity if capacity is not None else expert_capacity(
        G, E, k, capacity_factor
    )
    dispatch, combine, aux = capacity_dispatch(gate_logits, k, C)
    expert_in = jnp.einsum("gec,gd->ecd", dispatch.astype(x.dtype), x)
    expert_out = expert_fn(expert_in)
    y = jnp.einsum("gec,ecd->gd", combine.astype(expert_out.dtype), expert_out)
    return y, aux
