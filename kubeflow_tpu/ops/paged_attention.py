"""Pallas paged decode attention: page-table-native KV reads.

The paged engine's gather path
(``models/transformer.py:_paged_decode_attend``) materializes each
row's logical KV view back to a dense ``(B, max_seq_len, KH, Dh)``
tensor with ``jnp.take(pool, pages)`` — per layer, per decode step —
and ``gqa_repeat`` then widens it to QH heads, so a row with 100 live
tokens still reads and rewrites the full ``Smax`` footprint. On a part
where decode is bandwidth-read-bound, bytes per step is the number to
attack: this kernel reads K/V **directly through the per-row page
table**, so HBM traffic per step is proportional to live pages only
and nothing QH-wide is ever materialized.

Kernel shape (the flash kernels' streamed-grid pattern,
``ops/attention.py``):

- grid ``(B, n_logical_pages)`` with the page stream innermost; the
  page table and per-row positions ride ``PrefetchScalarGridSpec``
  scalar prefetch, so the K/V **index maps themselves** translate
  logical page ``j`` to its physical pool block — the gather never
  happens;
- causally-dead pages (``j·page_size > pos``) and sentinel/unmapped
  entries clamp the index map to an already-fetched block (a repeat
  fetch the pipeline elides) and gate compute with ``pl.when`` — they
  move and compute nothing, exactly the flash kernels' clamp trick;
- online-softmax ``(QH, Dh)``/``(QH, 1)`` f32 scratch accumulators:
  per-step VMEM holds one q row, one K/V page and the accumulators —
  independent of context length;
- GQA is handled in-kernel by slicing the q-head groups against their
  KV head (a static loop over ``KH``) — no ``gqa_repeat``, no QH-wide
  K/V copy.

Numerics: identical masking and scaling to the gather path (scores in
f32, scale applied post-dot, ``kv_pos <= pos`` causal bound); the
online softmax reorders the same f32 math, so greedy token streams
stay token-identical (the engine parity gate,
``tests/test_engine_paged.py``). ``interpret=None`` auto-selects the
Pallas interpreter off-TPU so CPU tests run the real kernel.

Safety contract (shared with the gather path and
``serving/kvpool.py``): a row's sentinel entries only occur at or
beyond its causal frontier (idle/disarmed rows are all-sentinel and
produce zeros nothing reads), and live pages below the frontier are
always mapped — the engine arms tables before any step that reads
them.

Tile legality (TPU001): every block dim is either 1 or a
shape-derived symbol (``page_size``/``KH``/``Dh``/``QH``) — the lane
axis is ``Dh``, the same lane layout the flash kernels run on chip.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import NEG_INF
from kubeflow_tpu.ops.autotune import resolve_paged


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return (jax.default_backend() != "tpu") if interpret is None else bool(
        interpret)


def _paged_decode_kernel(pages_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, m_ref, l_ref, *, page_size: int,
                         n_log: int, scale: float, n_kv_heads: int,
                         group: int, sentinel: int, head_block: int = 1):
    """One (row, logical-page) grid step of online-softmax attention.

    ``acc``/``m``/``l`` are the f32 running accumulators over the
    row's page stream; the emit at the final page normalizes. Each KV
    head attends its own q-head group (``group = QH // KH``) via
    static scratch slices — GQA without widening K/V.

    ``head_block`` (static, table-resolved — the "head-group blocking"
    knob of ROADMAP item 1's sweep) batches that many KV heads per
    compute step: at 1 the original per-head loop runs byte-identically
    (the parity oracle's path); above 1 the dots batch over the head
    axis so the MXU sees ``head_block·group × page_size`` work per
    issue instead of ``group × page_size``. VMEM residency is
    unchanged either way — the whole K/V page block is fetched
    regardless; the knob trades loop trips for batched-dot width.
    """
    import jax.experimental.pallas as pl  # deferred: envs without pallas

    b = pl.program_id(0)
    j = pl.program_id(1)
    pos = pos_ref[b]

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # skip causally-dead pages AND sentinel (unmapped) entries: the
    # index map clamped their fetch; the compute gate must agree
    live = (j * page_size <= pos) & (pages_ref[b, j] != sentinel)

    @pl.when(live)
    def _update():
        q = q_ref[0].astype(jnp.float32)       # (QH, Dh)
        kb = k_ref[0].astype(jnp.float32)      # (page_size, KH, Dh)
        vb = v_ref[0]
        kv_pos = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        dead = kv_pos > pos                    # per-position causal bound
        for h0 in range(0, n_kv_heads, head_block):
            if head_block == 1:
                _attend_one_head(q, kb, vb, dead, h0, group, scale,
                                 acc_ref, m_ref, l_ref)
            else:
                _attend_head_group(q, kb, vb, dead, h0, head_block,
                                   group, scale, acc_ref, m_ref, l_ref)

    @pl.when(j == n_log - 1)
    def _emit():
        # all-sentinel (idle/disarmed) rows never accumulate: l stays
        # 0 and the clamp emits finite zeros nothing reads
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _attend_one_head(q, kb, vb, dead, h, group, scale,
                     acc_ref, m_ref, l_ref):
    """The original per-KV-head online-softmax step (head_block=1) —
    kept verbatim as the bit-parity baseline the batched path and the
    gather oracle are gated against."""
    sl = slice(h * group, (h + 1) * group)
    s = jax.lax.dot_general(
        q[sl], kb[:, h, :], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                          # (group, page_size)
    s = jnp.where(dead, NEG_INF, s)
    m = m_ref[sl]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_ref[sl] = l_ref[sl] * alpha + jnp.sum(p, axis=-1,
                                            keepdims=True)
    acc_ref[sl] = acc_ref[sl] * alpha + jax.lax.dot_general(
        p.astype(vb.dtype), vb[:, h, :], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[sl] = m_new


def _attend_head_group(q, kb, vb, dead, h0, hb, group, scale,
                       acc_ref, m_ref, l_ref):
    """``hb`` KV heads per step: the score and value dots batch over
    the head axis (dot_general batch dims), so one issue carries
    ``hb·group`` q rows. Same f32 math per element as the per-head
    loop — only the batching changes."""
    sl = slice(h0 * group, (h0 + hb) * group)
    qh = q[sl].reshape(hb, group, q.shape[-1])
    # scores: batch hb, contract Dh → (hb, group, page_size)
    s = jax.lax.dot_general(
        qh, kb[:, h0:h0 + hb, :], (((2,), (2,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    ) * scale
    s = jnp.where(dead[None], NEG_INF, s)
    m = m_ref[sl].reshape(hb, group, 1)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = l_ref[sl].reshape(hb, group, 1) * alpha + jnp.sum(
        p, axis=-1, keepdims=True)
    l_ref[sl] = l_new.reshape(hb * group, 1)
    # values: batch hb, contract page_size → (hb, group, Dh)
    pv = jax.lax.dot_general(
        p.astype(vb.dtype), vb[:, h0:h0 + hb, :],
        (((2,), (0,)), ((0,), (1,))),
        preferred_element_type=jnp.float32,
    )
    acc_ref[sl] = (acc_ref[sl] * alpha.reshape(hb * group, 1)
                   + pv.reshape(hb * group, q.shape[-1]))
    m_ref[sl] = m_new.reshape(hb * group, 1)


def paged_decode_attention(q, k_pages, v_pages, pages, positions, *,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None,
                           head_block: Optional[int] = None):
    """Single-token decode attention straight off a paged KV pool.

    - ``q``: ``(B, QH, Dh)`` — one rotated query token per row;
    - ``k_pages``/``v_pages``: the shared pool,
      ``(pages_total, page_size, KH, Dh)``;
    - ``pages``: ``(B, n_logical)`` int32 per-row page table; the
      sentinel id ``pages_total`` marks unmapped entries;
    - ``positions``: ``(B,)`` int32 — each row's query position (KV
      positions ``<= positions[b]`` attend; the row's token for this
      step must already be written at that position).

    Returns ``(B, QH, Dh)`` in ``q.dtype``. HBM reads touch each
    row's live pages once — never the dense ``(B, Smax, ...)`` view,
    never a QH-wide GQA copy.

    ``head_block`` is the KV head-group compute knob: ``None`` resolves
    it from the committed tile table (kernel key ``paged_attn``,
    ``kubeflow_tpu/ops/autotune.py``; the safe fallback is the
    per-head loop, 1); an explicit value overrides and must divide the
    pool's KV head count.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, QH, Dh = q.shape
    P, page_size, KH, _ = k_pages.shape
    n_log = pages.shape[1]
    if QH % KH:
        raise ValueError(f"q heads {QH} must be a multiple of kv heads "
                         f"{KH}")
    cfg = resolve_paged(
        max_seq_len=n_log * page_size, page_size=page_size, n_heads=QH,
        n_kv_heads=KH, head_dim=Dh, dtype=q.dtype, head_block=head_block)
    head_block = cfg.head_block
    if head_block < 1 or KH % head_block:
        raise ValueError(f"head_block {head_block} must divide kv heads "
                         f"{KH}")
    scale = sm_scale if sm_scale is not None else Dh ** -0.5
    pages = pages.astype(jnp.int32)
    positions = positions.astype(jnp.int32)

    def q_map(b, j, pages_ref, pos_ref):
        return (b, 0, 0)

    def kv_map(b, j, pages_ref, pos_ref):
        # causal clamp: pages past the row's last live one re-fetch
        # the last live block (elided); sentinel entries clamp into
        # the pool — both are compute-gated off in the kernel
        jj = jnp.minimum(j, pos_ref[b] // page_size)
        return (jnp.minimum(pages_ref[b, jj], P - 1), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_log),
        in_specs=[
            pl.BlockSpec((1, QH, Dh), q_map),
            pl.BlockSpec((1, page_size, KH, Dh), kv_map),
            pl.BlockSpec((1, page_size, KH, Dh), kv_map),
        ],
        out_specs=pl.BlockSpec((1, QH, Dh), q_map),
        scratch_shapes=[
            pltpu.VMEM((QH, Dh), jnp.float32),
            pltpu.VMEM((QH, 1), jnp.float32),
            pltpu.VMEM((QH, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, page_size=page_size, n_log=n_log,
        scale=scale, n_kv_heads=KH, group=QH // KH, sentinel=P,
        head_block=head_block)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, QH, Dh), q.dtype),
        interpret=_resolve_interpret(interpret),
    )(pages, positions, q, k_pages, v_pages)
