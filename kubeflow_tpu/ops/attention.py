"""Attention ops: blockwise, Pallas flash kernel, and ring attention.

Long-context sequence/context parallelism is entirely absent from the
reference platform (SURVEY.md §5: "no ring attention, no context/sequence
parallel, no blockwise attention") — it never sees model internals. Here
they are framework ops:

- :func:`blockwise_attention` — online-softmax attention scanned over KV
  blocks: O(S) memory, differentiable, XLA-fusable. The inner compute for
  ring attention and the portable fallback everywhere.
- :func:`flash_attention` — Pallas TPU kernels for the forward AND backward
  pass (VMEM block tiles, MXU matmuls, f32 accumulators): the forward saves
  the per-row logsumexp, and dedicated dQ and dK/dV kernels replay blocks
  against it instead of recomputing the softmax; ``interpret=True`` runs the
  same kernels on CPU in tests.
- :func:`ring_attention` — sequence-parallel attention over a mesh axis:
  each device holds a sequence shard of Q/K/V and KV shards rotate around
  the ring via ``ppermute`` (one ICI hop per step when the axis is laid out
  on ICI neighbours — the scheduler's placement contract,
  ``kubeflow_tpu/scheduler/placement.py``), accumulating exactly as
  blockwise attention does. Causality is enforced from global block offsets.

All functions take ``(B, S, H, D)`` q/k/v (GQA repeat happens in the model)
and return ``(B, S, H, D)``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu import compat
from kubeflow_tpu.ops.autotune import resolve_flash

NEG_INF = -1e30


def _scale(q, sm_scale: Optional[float]) -> float:
    return sm_scale if sm_scale is not None else q.shape[-1] ** -0.5


def gqa_repeat(q, k, v):
    """Repeat grouped KV heads up to q's head count (no-op when equal)."""
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def reference_attention(q, k, v, *, causal: bool = True,
                        sm_scale: Optional[float] = None, kv_len=None):
    """Plain O(S²)-memory attention; the numerics oracle for the others.

    ``kv_len`` is an optional per-row valid-length ``(B,)`` int32 —
    KV positions at or past a row's length are masked out (the padding
    mask of the bidirectional/BERT path). The XLA parity oracle for the
    flash kernels' masked variant.
    """
    scale = _scale(q, sm_scale)
    logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * scale
    S, T = q.shape[1], k.shape[1]
    if causal:
        mask = jnp.arange(T)[None, :] <= jnp.arange(S)[:, None] + (T - S)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    if kv_len is not None:
        valid = jnp.arange(T)[None, :] < kv_len[:, None]        # (B, T)
        logits = jnp.where(valid[:, None, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


# ---------------------------------------------------------------------------
# Blockwise attention: online softmax over KV blocks
# ---------------------------------------------------------------------------


def _block_update(carry, kv_block, q, q_pos, kv_pos, scale, causal):
    """One online-softmax accumulation step over a KV block.

    carry: (o, l, m) f32 accumulators — o (B,Sq,H,D), l,m (B,Sq,H).
    kv_pos/q_pos: global position vectors for masking; negative kv_pos marks
    padding (excluded causal or not).
    """
    o, l, m = carry
    k, v = kv_block
    logits = jnp.einsum("bshd,bthd->bsht", q, k).astype(jnp.float32) * scale
    valid = kv_pos[None, :] >= 0
    if causal:
        valid = valid & (kv_pos[None, :] <= q_pos[:, None])  # (Sq, Skv)
    logits = jnp.where(valid[None, :, None, :], logits, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    p = jnp.exp(logits - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l = l * alpha + jnp.sum(p, axis=-1)
    o = o * alpha[..., None] + jnp.einsum(
        "bsht,bthd->bshd", p.astype(v.dtype), v
    ).astype(jnp.float32)
    return (o, l, m_new)


def blockwise_attention(q, k, v, *, causal: bool = True, block_k: int = 512,
                        sm_scale: Optional[float] = None):
    """Memory-efficient attention: ``lax.scan`` over KV blocks.

    Never materializes the (S, S) score matrix — peak activation memory is
    O(S · block_k). Fully differentiable (the scan transposes); XLA keeps
    the per-block einsums on the MXU.
    """
    B, Sq, H, D = q.shape
    T = k.shape[1]
    block_k = min(block_k, T)
    n_blocks = -(-T // block_k)
    pad = n_blocks * block_k - T
    if pad:
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    scale = _scale(q, sm_scale)
    q_pos = jnp.arange(Sq) + (T - Sq)  # align ends when Sq != T (decoding)

    ks = k.reshape(B, n_blocks, block_k, H, D).transpose(1, 0, 2, 3, 4)
    vs = v.reshape(B, n_blocks, block_k, H, D).transpose(1, 0, 2, 3, 4)

    def body(carry, blk):
        kb, vb, j = blk
        kv_pos = j * block_k + jnp.arange(block_k)
        kv_pos = jnp.where(kv_pos < T, kv_pos, -1)  # pad := masked out
        return (
            _block_update(carry, (kb, vb), q, q_pos, kv_pos, scale, causal),
            None,
        )

    # accumulators derive from q so they carry its varying-axes type when
    # running inside shard_map (e.g. ulysses_attention) — the vma checker
    # rejects unvarying zeros as a scan carry, exactly as in ring_attention
    o0 = (q * 0).astype(jnp.float32)
    l0 = o0[..., 0]
    init = (o0, l0, l0 + NEG_INF)
    (o, l, _), _ = jax.lax.scan(body, init, (ks, vs, jnp.arange(n_blocks)))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward kernel
# ---------------------------------------------------------------------------


def _last_live_kv(i, block_q: int, block_k: int):
    """Last kv-block index a causal q block ``i`` can see. The SAME
    expression drives the kv index-map clamp and the kernels' compute
    gates — they must agree exactly, or a fetched-but-skipped (or
    skipped-but-computed) step corrupts the accumulator."""
    return (i * block_q + block_q - 1) // block_k


def _first_live_q(j, block_q: int, block_k: int):
    """First q-block index that attends into causal kv block ``j`` —
    the dkv twin of :func:`_last_live_kv` (same agree-exactly contract
    between the q index map and the compute gate)."""
    return (j * block_k) // block_q


def _causal_block_mask(s, i, j, block_q: int, block_k: int):
    """Apply the per-position causal bound to one (block_q, block_k)
    score tile at q block ``i`` / kv block ``j``."""
    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)
    kv_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    return jnp.where(kv_pos <= q_pos, s, NEG_INF)


def _pad_mask(s, limit, j, block_k: int):
    """Mask KV positions at/past the row's valid length ``limit`` in
    one (block_q, block_k) score tile at kv block ``j`` — the padding
    mask of the bidirectional/BERT flash path. The SAME expression in
    the forward and both backward kernels, or the backward's
    recomputed P diverges from the forward's."""
    kv_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_k), 1)
    return jnp.where(kv_pos < limit, s, NEG_INF)


def _flash_fwd_kernel(*refs, block_q: int, block_k: int,
                      scale: float, causal: bool, n_kv: int,
                      masked: bool = False):
    """One (batch·head, q-block, kv-block) grid step.

    The KV stream is a GRID dimension (innermost), not an in-kernel
    loop over a full-sequence VMEM ref: per-step VMEM holds one q block,
    one k/v block, and the f32 (acc, m, l) online-softmax scratch —
    independent of sequence length, so the kernel compiles at any
    context the HBM can hold (the full-S residency variant died at
    seq 16k: 16.75 MB > the 16 MB scoped-vmem limit). Causal q blocks
    clamp their kv index map to the last needed block and gate compute
    with pl.when, so masked-out steps move and compute nothing. Emits
    the per-row logsumexp at the final kv step — the backward kernels
    recompute probabilities from it without a second online-softmax
    pass.

    ``masked`` (static) adds a per-row valid-length input (SMEM scalar
    per fused batch·head row) whose padding mask composes with the
    causal one; the unmasked argument list is byte-identical to the
    pre-mask kernel.
    """
    import jax.experimental.pallas as pl  # deferred: test envs without pallas

    if masked:
        q_ref, k_ref, v_ref, len_ref, o_ref, lse_ref, acc_ref, m_ref, \
            l_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = refs
        len_ref = None

    i = pl.program_id(1)  # q-block index
    j = pl.program_id(2)  # kv-block index

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # causal: kv blocks strictly after this q block contribute nothing
    live = (j <= _last_live_kv(i, block_q, block_k)) if causal else True

    @pl.when(live)
    def _update():
        q = q_ref[0].astype(jnp.float32) * scale
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0]
        s = jax.lax.dot_general(
            q, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        if causal:
            s = _causal_block_mask(s, i, j, block_q, block_k)
        if masked:
            s = _pad_mask(s, len_ref[0, 0], j, block_k)
        m = m_ref[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1,
                                                  keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = m_new

    @pl.when(j == n_kv - 1)
    def _emit():
        l = jnp.maximum(l_ref[...], 1e-30)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)
        # (1, block_q, 1): the trailing singleton keeps the TPU block
        # layout legal (last dims must divide (8, 128) or equal the
        # array's)
        lse_ref[0] = m_ref[...] + jnp.log(l)


def _fuse_heads(x):
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _causal_clamp_kv(block_q: int, block_k: int, causal: bool):
    """kv-block index map for (b, i, j) grids: under causality, blocks
    past the last one this q block can see are never fetched (the map
    clamps to the last live block — a repeat fetch the pipeline elides;
    the bound is the kernels' own compute-gate expression)."""
    if not causal:
        return lambda b, i, j: (b, j, 0)
    return lambda b, i, j: (
        b, jnp.minimum(j, _last_live_kv(i, block_q, block_k)), 0)


def _causal_clamp_q(block_q: int, block_k: int, causal: bool):
    """q-block index map for (b, j, i) grids — the dkv twin of
    :func:`_causal_clamp_kv`: under causality, q blocks before this kv
    block's first contributor are never fetched (the bound is the dkv
    kernel's own compute-gate expression, :func:`_first_live_q`)."""
    if not causal:
        return lambda b, j, i: (b, i, 0)
    return lambda b, j, i: (
        b, jnp.maximum(i, _first_live_q(j, block_q, block_k)), 0)


def _fused_lens(kv_len, H: int):
    """(B,) per-row valid lengths → (B·H, 1) int32 aligned with the
    kernels' fused batch·head grid axis."""
    return jnp.repeat(kv_len.astype(jnp.int32), H)[:, None]


def _len_spec(pl, pltpu):
    """One per-row length scalar per grid step, SMEM-resident (control
    values, not vector data)."""
    return pl.BlockSpec((1, 1), lambda b, i, j: (b, 0),
                        memory_space=pltpu.SMEM)


def _flash_fwd(q, k, v, *, causal: bool, block_q: Optional[int],
               block_k: Optional[int], sm_scale: Optional[float],
               interpret: bool, kv_len=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    cfg = resolve_flash(
        "flash_fwd", seq=S, head_dim=D, n_heads=H, n_kv_heads=k.shape[2],
        dtype=q.dtype, causal=causal, block_q=block_q, block_k=block_k)
    block_q = min(cfg.block_q, S)
    block_k = min(cfg.block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"seq_len {S} must divide by blocks {block_q}/{block_k}")
    scale = _scale(q, sm_scale)
    masked = kv_len is not None

    # fuse batch and heads into the grid's first axis; q blocks second,
    # kv stream innermost
    qf, kf, vf = _fuse_heads(q), _fuse_heads(k), _fuse_heads(v)
    n_kv = S // block_k

    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k, scale=scale,
        causal=causal, n_kv=n_kv, masked=masked,
    )
    kv_map = _causal_clamp_kv(block_q, block_k, causal)
    inputs = [qf, kf, vf]
    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), kv_map,
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((1, block_k, D), kv_map,
                     memory_space=pltpu.VMEM),
    ]
    if masked:
        inputs.append(_fused_lens(kv_len, H))
        in_specs.append(_len_spec(pl, pltpu))
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, S // block_q, n_kv),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, S, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3), lse


def _flash_bwd_dq_kernel(*refs, block_q: int, block_k: int,
                         scale: float, causal: bool, n_kv: int,
                         masked: bool = False):
    """dQ for one (batch·head, q-block, kv-block) grid step: the KV
    stream rides the innermost grid dimension (seq-independent VMEM,
    like the forward), recompute P from the saved logsumexp,
    accumulate dS·K in f32 scratch, emit at the last kv step."""
    import jax.experimental.pallas as pl

    if masked:
        q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, len_ref, \
            dq_ref, acc_ref = refs
    else:
        q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dq_ref, \
            acc_ref = refs
        len_ref = None

    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    live = (j <= _last_live_kv(i, block_q, block_k)) if causal else True

    @pl.when(live)
    def _update():
        qs = q_ref[0].astype(jnp.float32) * scale  # pre-scaled, as in fwd
        g = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0]    # (block_q, 1)
        delta = delta_ref[0]
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(qs, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_block_mask(s, i, j, block_q, block_k)
        if masked:
            s = _pad_mask(s, len_ref[0, 0], j, block_k)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(g, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
            ds, kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_kv - 1)
    def _emit():
        dq_ref[0] = (acc_ref[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, block_q: int,
                          block_k: int, scale: float, causal: bool,
                          n_q: int, masked: bool = False):
    """dK/dV for one (batch·head, kv-block, q-block) grid step: the Q
    stream rides the innermost grid dimension; causal steps before this
    kv block's first contributing q block move and compute nothing.
    Recompute P, accumulate Pᵀ·dO and dSᵀ·Q in f32 scratch, emit at
    the last q step (which causality never skips)."""
    import jax.experimental.pallas as pl

    if masked:
        q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, len_ref, \
            dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref, dk_ref, dv_ref, \
            dk_acc, dv_acc = refs
        len_ref = None

    j = pl.program_id(1)  # kv-block index
    i = pl.program_id(2)  # q-block index

    @pl.when(i == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (i >= _first_live_q(j, block_q, block_k)) if causal else True

    @pl.when(live)
    def _update():
        kb = k_ref[0].astype(jnp.float32)
        vb = v_ref[0].astype(jnp.float32)
        qs = q_ref[0].astype(jnp.float32) * scale
        g = g_ref[0].astype(jnp.float32)
        lse = lse_ref[0]    # (block_q, 1)
        delta = delta_ref[0]
        s = jax.lax.dot_general(qs, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            s = _causal_block_mask(s, i, j, block_q, block_k)
        if masked:
            s = _pad_mask(s, len_ref[0, 0], j, block_k)
        p = jnp.exp(s - lse)  # (block_q, block_k)
        dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
            p, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(g, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dK = dSᵀ·(q·scale) — the scale chains through the pre-scaled q
        dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
            ds, qs, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_q - 1)
    def _emit():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, *, causal: bool,
               block_q: Optional[int], block_k: Optional[int],
               sm_scale: Optional[float], interpret: bool, kv_len=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, S, H, D = q.shape
    # the dQ and dK/dV kernels stream opposite axes, so their optima
    # are INDEPENDENT shape classes — each resolves its own tile pair
    # (an explicit override pins both, the pre-PR behavior)
    shape_kw = dict(seq=S, head_dim=D, n_heads=H, n_kv_heads=k.shape[2],
                    dtype=q.dtype, causal=causal, block_q=block_q,
                    block_k=block_k)
    cfg_dq = resolve_flash("flash_bwd_dq", **shape_kw)
    cfg_kv = resolve_flash("flash_bwd_dkv", **shape_kw)
    bq_dq, bk_dq = min(cfg_dq.block_q, S), min(cfg_dq.block_k, S)
    bq_kv, bk_kv = min(cfg_kv.block_q, S), min(cfg_kv.block_k, S)
    for bq, bk in ((bq_dq, bk_dq), (bq_kv, bk_kv)):
        if S % bq or S % bk:
            raise ValueError(
                f"seq_len {S} must divide by blocks {bq}/{bk}")
    scale = _scale(q, sm_scale)
    masked = kv_len is not None

    qf, kf, vf = _fuse_heads(q), _fuse_heads(k), _fuse_heads(v)
    gf, of = _fuse_heads(g), _fuse_heads(o)
    # delta_r = Σ_d dO·O — one cheap fused elementwise+reduce in XLA;
    # trailing singleton for a legal TPU block layout (see lse)
    delta = jnp.sum(gf.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1, keepdims=True)
    lens = _fused_lens(kv_len, H) if masked else None

    n_q, n_kv = S // bq_dq, S // bk_dq
    blk_q = lambda b, i, j: (b, i, 0)  # noqa: E731
    kv_map = _causal_clamp_kv(bq_dq, bk_dq, causal)

    inputs = [qf, kf, vf, gf, lse, delta]
    in_specs = [
        pl.BlockSpec((1, bq_dq, D), blk_q, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk_dq, D), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk_dq, D), kv_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq_dq, D), blk_q, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq_dq, 1), blk_q, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq_dq, 1), blk_q, memory_space=pltpu.VMEM),
    ]
    if masked:
        inputs.append(lens)
        in_specs.append(_len_spec(pl, pltpu))
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=bq_dq,
                          block_k=bk_dq, scale=scale, causal=causal,
                          n_kv=n_kv, masked=masked),
        grid=(B * H, n_q, n_kv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq_dq, D), blk_q,
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq_dq, D), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    n_q, n_kv = S // bq_kv, S // bk_kv
    q_map = _causal_clamp_q(bq_kv, bk_kv, causal)
    blk_kv = lambda b, j, i: (b, j, 0)  # noqa: E731

    inputs = [qf, kf, vf, gf, lse, delta]
    in_specs = [
        pl.BlockSpec((1, bq_kv, D), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk_kv, D), blk_kv, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bk_kv, D), blk_kv, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq_kv, D), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq_kv, 1), q_map, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, bq_kv, 1), q_map, memory_space=pltpu.VMEM),
    ]
    if masked:
        inputs.append(lens)
        in_specs.append(pl.BlockSpec((1, 1), lambda b, j, i: (b, 0),
                                     memory_space=pltpu.SMEM))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=bq_kv,
                          block_k=bk_kv, scale=scale, causal=causal,
                          n_q=n_q, masked=masked),
        grid=(B * H, n_kv, n_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bk_kv, D), blk_kv, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk_kv, D), blk_kv, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bk_kv, D), jnp.float32),
                        pltpu.VMEM((bk_kv, D), jnp.float32)],
        interpret=interpret,
    )(*inputs)

    unfuse = lambda x: x.reshape(B, H, S, D).transpose(0, 2, 1, 3)  # noqa: E731
    return unfuse(dq), unfuse(dk), unfuse(dv)


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return (jax.default_backend() != "tpu") if interpret is None else interpret


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(q, k, v, causal: bool = True,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    sm_scale: Optional[float] = None,
                    interpret: Optional[bool] = None, kv_len=None):
    """Pallas flash attention: fwd AND bwd kernels (saved-LSE backward).

    The backward is the standard flash split — a dQ kernel streaming KV
    blocks and a dK/dV kernel streaming Q blocks — recomputing P from the
    forward's saved logsumexp, so training never materializes (S, S) and
    both passes run on the MXU from VMEM tiles.

    ``block_q``/``block_k`` are INDEPENDENT tile knobs. ``None`` (the
    default) resolves each kernel's tiles from the committed shape-keyed
    tile table — ``flash_fwd``, ``flash_bwd_dq`` and ``flash_bwd_dkv``
    are separate kernel keys, so the chip sweep can tune each pass —
    with an analytic VMEM-budget fallback when the shape class has no
    entry (``kubeflow_tpu/ops/autotune.py``). Explicit values override
    the table for every kernel (the pre-PR behavior).

    ``kv_len`` is an optional per-row valid-length ``(B,)`` int32: KV
    positions at/past a row's length are masked out in the forward AND
    both backward kernels — the padding mask of the bidirectional/BERT
    path (``reference_attention(kv_len=...)`` is the parity oracle).
    Rows whose cotangent is zero at padded positions get exact
    gradients; outputs AT padded q positions are unspecified (mask them
    downstream, as the MLM loss weights do).

    ``interpret=None`` auto-selects: compiled on TPU, interpreter elsewhere
    (so CPU tests execute the real kernels).
    """
    out, _ = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                        block_k=block_k, sm_scale=sm_scale,
                        interpret=_resolve_interpret(interpret),
                        kv_len=kv_len)
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k, sm_scale, interpret,
                   kv_len=None):
    out, lse = _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, sm_scale=sm_scale,
                          interpret=_resolve_interpret(interpret),
                          kv_len=kv_len)
    return out, (q, k, v, out, lse, kv_len)


def _flash_vjp_bwd(causal, block_q, block_k, sm_scale, interpret, res, g):
    q, k, v, out, lse, kv_len = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, causal=causal,
                            block_q=block_q, block_k=block_k,
                            sm_scale=sm_scale,
                            interpret=_resolve_interpret(interpret),
                            kv_len=kv_len)
    if kv_len is None:
        return dq, dk, dv, None
    # integer primal → float0 cotangent (the custom_vjp contract)
    return dq, dk, dv, np.zeros(kv_len.shape, dtype=jax.dtypes.float0)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# Ring attention: sequence-parallel over a mesh axis
# ---------------------------------------------------------------------------


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   sm_scale: Optional[float] = None, block_k: int = 512):
    """Sequence-parallel attention inside ``shard_map``: rotate KV via ppermute.

    Call within a ``shard_map`` region whose ``axis_name`` shards the
    sequence dim of q/k/v. Device i holds query block i; KV blocks rotate
    one ring hop per step so after n steps every query block has seen every
    KV block. Per-step masking uses global block offsets, so causality holds
    exactly; a KV block strictly AHEAD of this device's query block is
    skipped entirely via ``lax.cond`` (its contribution is fully masked),
    so causal rings do ~half the attention FLOPs — the ppermute still runs
    every step to keep the ring schedule uniform across devices.

    Gradients flow through ``lax.scan`` + ``ppermute`` + ``cond`` (all
    differentiable), so the same code path trains.
    """
    n = compat.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Sq, H, D = q.shape
    scale = _scale(q, sm_scale)
    q_pos = idx * Sq + jnp.arange(Sq)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(carry, step):
        o, l, m, k_cur, v_cur = carry
        src = (idx - step) % n  # who this KV block belongs to globally
        kv_pos = src * Sq + jnp.arange(k_cur.shape[1])

        def attend(acc):
            return _block_update(acc, (k_cur, v_cur), q, q_pos, kv_pos,
                                 scale, causal)

        if causal:
            # src > idx ⇒ every kv position is ahead of every query
            # position on this device: skip the whole block's compute
            o, l, m = jax.lax.cond(src > idx, lambda acc: acc, attend,
                                   (o, l, m))
        else:
            o, l, m = attend((o, l, m))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o, l, m, k_nxt, v_nxt), None

    # derive accumulators from q so they carry its varying-axes type (the
    # shard_map vma checker rejects unvarying zeros as a scan carry)
    o0 = q.astype(jnp.float32) * 0.0
    l0 = o0[..., 0]
    init = (o0, l0, l0 + NEG_INF, k, v)
    (o, l, _, _, _), _ = jax.lax.scan(body, init, jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      block_k: int = 512):
    """DeepSpeed-Ulysses-style sequence parallelism inside ``shard_map``.

    The ring's alternative collective pattern: instead of rotating KV
    shards (n-1 ``ppermute`` hops), two ``all_to_all``s re-shard
    sequence↔heads — q/k/v arrive sequence-sharded ``(B, S/n, H, D)``,
    leave the first all_to_all head-sharded with the FULL sequence
    ``(B, S, H/n, D)``, attend locally (blockwise: O(S) memory), and the
    second all_to_all restores sequence sharding. On TPU both all_to_alls
    ride ICI; Ulysses wins when heads divide evenly and S/n is small
    (fewer collective phases), ring wins at extreme S (no full-sequence
    residency).

    GQA: k/v may arrive with fewer heads than q (``KH < H``); the repeat
    to ``H`` happens AFTER the KV all_to_alls so the collectives carry
    only the distinct KV heads. Requires ``H % n == 0`` and
    ``KH % n == 0``.
    """
    n = compat.axis_size(axis_name)
    H, KH = q.shape[2], k.shape[2]
    if H % n or KH % n:
        raise ValueError(
            f"ulysses needs q heads {H} and kv heads {KH} divisible by "
            f"axis size {n}")

    def seq_to_heads(x):
        # (B, S/n, h, D) -> (B, S, h/n, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    kg, vg = gqa_repeat(qg, kg, vg)
    o = blockwise_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale,
                            block_k=block_k)
    return heads_to_seq(o)


def _sharded_seq_attention(core, q, k, v, mesh, seq_axis, batch_axis):
    """Shared shard_map wrapper for the sequence-parallel cores: filters
    ``batch_axis`` names absent from ``mesh`` (plain dp/tp meshes and the
    4-axis dcn mesh both work), shards the sequence dim over ``seq_axis``."""
    from jax.sharding import PartitionSpec as P

    if batch_axis is not None:
        axes = ((batch_axis,) if isinstance(batch_axis, str)
                else tuple(batch_axis))
        axes = tuple(a for a in axes if a in mesh.axis_names)
        batch_axis = (axes[0] if len(axes) == 1 else axes) if axes else None
    spec = P(batch_axis, seq_axis, None, None)
    fn = compat.shard_map(core, mesh=mesh, in_specs=(spec, spec, spec),
                          out_specs=spec)
    return fn(q, k, v)


def ulysses_attention_sharded(q, k, v, mesh, *, seq_axis: str = "tp",
                              batch_axis=("dcn", "dp"),
                              causal: bool = True,
                              sm_scale: Optional[float] = None):
    """``shard_map`` wrapper: full (B, S, H, D) arrays in, Ulysses
    all-to-all sequence parallelism over ``seq_axis``. Usable under jit."""
    return _sharded_seq_attention(
        functools.partial(ulysses_attention, axis_name=seq_axis,
                          causal=causal, sm_scale=sm_scale),
        q, k, v, mesh, seq_axis, batch_axis)


def ring_attention_sharded(q, k, v, mesh, *, seq_axis: str = "tp",
                           batch_axis=("dcn", "dp"), causal: bool = True,
                           sm_scale: Optional[float] = None):
    """``shard_map`` wrapper: full (B, S, H, D) arrays in, ring attention on
    sequence shards over ``seq_axis``. Usable directly under jit.

    ``batch_axis`` may be a name, a tuple of names, or None; names absent
    from ``mesh`` are dropped (see :func:`_sharded_seq_attention`)."""
    return _sharded_seq_attention(
        functools.partial(ring_attention, axis_name=seq_axis,
                          causal=causal, sm_scale=sm_scale),
        q, k, v, mesh, seq_axis, batch_axis)
