"""Compressed-activation training: int8 forward-saved tensors.

PERF.md's open ResNet lever: the train step is HBM-bound, and roughly
half the activation traffic is the backward pass re-reading forward
activations (every conv's input is saved for its weight gradient).
Storing those residuals in int8 (per-channel absmax scale) cuts their
HBM footprint and read traffic 2× vs bf16 / 4× vs f32, at the cost of a
bounded quantization error in the gradients — the ActNN/GACT recipe,
expressed the JAX way as a ``custom_vjp`` around the op:

- forward: run the op exactly (full precision); save the INPUT as
  ``(int8 values, per-channel scales)`` instead of the raw tensor;
- backward: dequantize and differentiate the op at the dequantized
  point (straight-through with respect to the rounding).

No reference counterpart (the reference never sees model internals);
technique reference: ActNN (arXiv:2104.14129) / MLPerf-era activation
compression. The loss-parity gate lives in ``tests/test_act_compress.py``.
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def quantize_int8(x: jnp.ndarray):
    """Per-channel (last axis) symmetric absmax int8 quantization.

    Returns ``(q int8, scale f32)`` with ``x ≈ q * scale``. Zero
    channels get scale 0 (and dequantize to exact zeros).
    """
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)),
                     axis=tuple(range(x.ndim - 1)), keepdims=True)
    scale = absmax / 127.0
    q = jnp.where(scale > 0, x.astype(jnp.float32) / jnp.where(
        scale > 0, scale, 1.0), 0.0)
    q = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return q.astype(jnp.float32) * scale


def int8_checkpoint(fn: Callable) -> Callable:
    """Wrap pure ``fn(params, x) -> y`` so the backward pass sees an
    int8-saved ``x``.

    The forward runs ``fn`` exactly; only the residual changes: ``x`` is
    saved quantized and the backward recomputes ``fn``'s VJP at the
    dequantized point. ``params`` is saved by reference (it is live in
    the optimizer anyway).
    """

    @jax.custom_vjp
    def wrapped(params, x):
        return fn(params, x)

    def fwd(params, x):
        y = fn(params, x)
        q, scale = quantize_int8(x)
        # residuals must be jax types; a 0-size array carries x's dtype
        return y, (params, q, scale, jnp.zeros((0,), x.dtype))

    def bwd(res, g):
        params, q, scale, dtype_token = res
        x = dequantize_int8(q, scale).astype(dtype_token.dtype)
        _, vjp = jax.vjp(fn, params, x)
        return vjp(g)

    wrapped.defvjp(fwd, bwd)
    return wrapped


class Int8Conv(nn.Module):
    """``nn.Conv``-shaped conv (no bias) whose backward reads its input
    from an int8 residual — drop-in for the HBM-bound ResNet blocks.

    Same param shape/name as ``nn.Conv`` (``kernel``: (KH, KW, Cin,
    Cout)), so checkpoints swap between compressed and plain configs.
    """

    features: int
    kernel_size: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kh, kw, x.shape[-1], self.features), self.param_dtype)

        def conv(k, xx):
            # no preferred_element_type: its transpose rejects the
            # mixed-dtype cotangent, and the MXU accumulates bf16
            # contractions in f32 regardless (nn.Conv semantics)
            return jax.lax.conv_general_dilated(
                xx, k.astype(self.dtype), self.strides, self.padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        return int8_checkpoint(conv)(kernel, x.astype(self.dtype))
