"""TPU compute ops: attention kernels (dense, flash, ring/ulysses,
paged decode), collectives, MoE dispatch, fused sampling, and the
kernel autotune plane (``ops/autotune.py``: shape-keyed tile tables
every tuned kernel resolves its blocks from)."""

from kubeflow_tpu.ops import autotune  # noqa: F401
from kubeflow_tpu.ops.attention import (  # noqa: F401
    blockwise_attention,
    flash_attention,
    reference_attention,
    ring_attention,
    ring_attention_sharded,
    ulysses_attention,
    ulysses_attention_sharded,
)
from kubeflow_tpu.ops.collectives import (  # noqa: F401
    CollectiveResult,
    all_gather,
    all_reduce,
    all_to_all,
    bench_all,
    bench_collective,
    ppermute_shift,
    reduce_scatter,
)
from kubeflow_tpu.ops.moe import (  # noqa: F401
    capacity_dispatch,
    capacity_moe,
    expert_capacity,
)
from kubeflow_tpu.ops.paged_attention import (  # noqa: F401
    paged_decode_attention,
)
from kubeflow_tpu.ops.sampling import fused_sample  # noqa: F401
