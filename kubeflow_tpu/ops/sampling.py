"""Fused temperature → top-k → top-p → sample kernel for the decode engine.

The serving sampler problem (BENCH_r05): EXACT top-k/top-p sampling via
:func:`kubeflow_tpu.models.decode.sample_logits`'s sort path pays a full
(B, V) descending vocab sort per decode step — at engine batch 32 that
is 32 vocab sorts per token, a ~2.4× throughput tax against the
``lax.top_k``-bounded sampler, which in turn silently truncates flat
nucleus distributions. This kernel removes the tradeoff: exact support
semantics at bounded-path cost.

How it is exact WITHOUT a sort: both filters reduce to per-row value
thresholds, and a threshold over floats can be found EXACTLY by binary
search on the *ordered-int* encoding of f32 (flip the low 31 bits of
negative floats and the int order equals the float order) — 32
count/mass reductions over a VMEM-resident row instead of an O(V log V)
sort with its (B, V) sorted materialization:

- **top-k**: the k-th largest value is the largest threshold ``t`` with
  ``count(scaled >= t) >= k``; keep ``scaled >= kth`` — identical tie
  behavior to the sort path (ties at the boundary are all kept);
- **top-p**: over the k-filtered renormalized distribution, the nucleus
  acceptance threshold is the smallest kept value ``v`` whose
  strictly-above mass ``sum(P[scaled > v])`` is ``< p``; keep
  ``scaled >= v``. This reproduces the sort path's final
  ``scaled >= p_thresh`` mask exactly, except for exact float TIES
  straddling the k boundary, where the sort path renormalizes over an
  arbitrary subset of the tied tokens and this kernel (tie-symmetric)
  uses all of them;
- **sample**: Gumbel-max over the masked row — exact categorical
  sampling, one argmax, no CDF inversion. Greedy rows
  (``temperature <= 0``) bypass everything with an argmax of the raw
  logits, bit-identical to the other samplers.

Like every sampler change, switching the engine to the fused path draws
different (identically distributed) streams for the same seed.

Tile legality (TPU001): blocks are ``(1, Vp)`` with the vocab padded to
a multiple of 128 lanes, and ``(1, 1)`` for per-row scalars/outputs —
size-1 dims are relayout-legal. ``interpret=None`` auto-selects the
Pallas interpreter off-TPU, so CPU tests run the same kernel.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import NEG_INF

LANE = 128
_SEARCH_ITERS = 32  # one per int32 bit: exact convergence
_INT_MIN = -(2 ** 31)
_INT_MAX = 2 ** 31 - 1


def _resolve_interpret(interpret: Optional[bool]) -> bool:
    return (jax.default_backend() != "tpu") if interpret is None else bool(
        interpret)


def _ordered_bits(x: jnp.ndarray) -> jnp.ndarray:
    """Map f32 to int32 such that int order == float order (no NaNs):
    non-negative floats keep their bits, negative floats flip the low
    31 bits (reversing their bit order to match their value order)."""
    b = jax.lax.bitcast_convert_type(x, jnp.int32)
    return jnp.where(b < 0, b ^ jnp.int32(0x7FFFFFFF), b)


def _mid(lo: jnp.ndarray, hi: jnp.ndarray) -> jnp.ndarray:
    """Overflow-safe int32 midpoint for lo <= hi spanning the full
    range (lo + (hi - lo) // 2 overflows when lo = INT_MIN)."""
    return (lo >> 1) + (hi >> 1) + (lo & hi & 1)


def _fused_sample_kernel(logits_ref, gumbel_ref, temp_ref, k_ref, p_ref,
                         out_ref, *, V: int):
    """One grid row: exact filtered sampling over a (1, Vp) block."""
    neg = jnp.float32(NEG_INF)
    valid = jax.lax.broadcasted_iota(
        jnp.int32, logits_ref.shape, 1) < V
    logits = jnp.where(valid, logits_ref[...].astype(jnp.float32), neg)
    temp = temp_ref[0, 0]
    k = k_ref[0, 0]
    p = p_ref[0, 0]
    greedy = temp <= 0.0
    scaled = jnp.where(valid,
                       logits / jnp.where(greedy, 1.0, temp), neg)
    ordered = _ordered_bits(scaled)

    # -- top-k: largest t with count(ordered >= t) >= k_eff -----------------
    k_eff = jnp.where(k <= 0, V, jnp.minimum(k, V))

    def k_step(_, carry):
        lo, hi = carry
        mid = _mid(lo, hi)
        cnt = jnp.sum((valid & (ordered >= mid)).astype(jnp.int32))
        ge = cnt >= k_eff
        return jnp.where(ge, mid, lo), jnp.where(ge, hi, mid)

    kth, _ = jax.lax.fori_loop(
        0, _SEARCH_ITERS, k_step,
        (jnp.int32(_INT_MIN), jnp.int32(_INT_MAX)))
    kmask = valid & (ordered >= kth)

    # -- top-p over the k-filtered renormalized distribution ----------------
    m = jnp.max(jnp.where(kmask, scaled, neg))
    e = jnp.where(kmask, jnp.exp(scaled - m), 0.0)
    z = jnp.sum(e)
    target = p * z

    # invariant: Q(t) = "strictly-above mass < p·z" is monotone in t,
    # Q(hi)=True (mass above the max is 0), Q(lo)=False for p < 1 (the
    # full mass z >= p·z); hi converges to the minimal int with Q
    def p_step(_, carry):
        lo, hi = carry
        mid = _mid(lo, hi)
        mass = jnp.sum(jnp.where(kmask & (ordered > mid), e, 0.0))
        below = mass < target
        return jnp.where(below, lo, mid), jnp.where(below, mid, hi)

    _, t0 = jax.lax.fori_loop(
        0, _SEARCH_ITERS, p_step,
        (jnp.int32(_INT_MIN), jnp.int32(_INT_MAX)))
    p_thresh = jnp.min(jnp.where(kmask & (ordered >= t0), ordered,
                                 jnp.int32(_INT_MAX)))
    pmask = kmask & (ordered >= p_thresh)
    mask = jnp.where(p >= 1.0, kmask, pmask)

    # -- Gumbel-max sample (exact categorical over the masked support) ------
    # argmax as max+min-index (first occurrence, matching jnp.argmax's
    # tie-break bitwise): plain reductions lower on every Mosaic version
    iota = jax.lax.broadcasted_iota(jnp.int32, logits_ref.shape, 1)
    score = jnp.where(mask, scaled + gumbel_ref[...], neg)
    sampled = jnp.min(jnp.where(score >= jnp.max(score), iota, V))
    top = jnp.min(jnp.where(logits >= jnp.max(logits), iota, V))
    out_ref[0, 0] = jnp.where(greedy, top, sampled).astype(jnp.int32)


def fused_sample(logits: jnp.ndarray, keys, *, temperature=1.0,
                 top_k=0, top_p=1.0,
                 interpret: Optional[bool] = None) -> jnp.ndarray:
    """Sample token ids from ``(B, V)`` logits, one fused kernel pass.

    Argument semantics match
    :func:`kubeflow_tpu.models.decode.sample_logits` (scalars or (B,)
    arrays; temperature<=0 → greedy argmax; top_k<=0 / top_p>=1 →
    filter off), with exact full-vocab support for both filters.
    ``keys`` is a PER-ROW key array (B,) — each row's draw depends only
    on its own key, so a request's stream is reproducible regardless of
    co-tenants (the engine's fold_in contract).
    """
    B, V = logits.shape
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32),
                            (B,)).reshape(B, 1)
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32),
                         (B,)).reshape(B, 1)
    p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32),
                         (B,)).reshape(B, 1)
    # per-row Gumbel noise outside the kernel (XLA fuses the PRNG); the
    # kernel's argmax over scaled+gumbel is then exact categorical
    u = jax.vmap(lambda kk: jax.random.uniform(
        kk, (V,), jnp.float32, minval=1e-20, maxval=1.0))(keys)
    g = -jnp.log(-jnp.log(u))

    Vp = -(-V // LANE) * LANE
    if Vp != V:
        pad = ((0, 0), (0, Vp - V))
        logits = jnp.pad(logits, pad)
        g = jnp.pad(g, pad)

    import functools

    import jax.experimental.pallas as pl

    out = pl.pallas_call(
        functools.partial(_fused_sample_kernel, V=V),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Vp), lambda b: (b, 0)),
            pl.BlockSpec((1, Vp), lambda b: (b, 0)),
            pl.BlockSpec((1, 1), lambda b: (b, 0)),
            pl.BlockSpec((1, 1), lambda b: (b, 0)),
            pl.BlockSpec((1, 1), lambda b: (b, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda b: (b, 0)),
        out_shape=jax.ShapeDtypeStruct((B, 1), jnp.int32),
        interpret=_resolve_interpret(interpret),
    )(logits, g, temp, k, p)
    return out[:, 0]
