"""Kernel autotune plane: shape-keyed tile tables for the Pallas kernels.

PERF.md r05 proved tile choice is a first-order lever (1024-edge flash
tiles ran the fwd+bwd pair 1.8× faster than 512 at seq 8192) AND that
the optimum is shape-dependent (2048 exceeds scoped VMEM; 256 loses the
MXU) — yet every kernel shipped ONE hardcoded default. This module is
the selection plane every tuned kernel consults instead of growing
another constant:

- a **kernel key** (``flash_fwd`` / ``flash_bwd_dq`` / ``flash_bwd_dkv``
  / ``paged_attn``) plus a **shape class** (seq bucket, head_dim,
  n_heads / n_kv_heads, dtype, causal, backend generation) maps to a
  measured tile config — ``(block_q, block_k)`` as independent knobs
  for the flash kernels, the KV ``head_block`` group for the paged
  kernel;
- the table is a versioned, committed JSON file
  (``kubeflow_tpu/ops/tile_table.json``) seeded with the r05-measured
  winners and regenerated on chip by ``scripts/tile_sweep.py``;
- an analytic VMEM-budget legality check is both the **load-time
  guard** (an illegal table row is rejected with a warning and never
  becomes a compile failure — the fallback is used instead) and the
  **fallback selector** when a shape class has no entry;
- every resolution can be recorded (:func:`record_resolutions`) so the
  bench artifact attributes a throughput move to a table change
  (``tile_config`` rows: resolved blocks + source
  ``table|fallback|override``).

The module keeps its top level stdlib-only on purpose: tpulint's TPU001
checker loads it standalone (without ``kubeflow_tpu.ops.__init__``'s
jax import) to lint the table itself at preflight. jax is imported
lazily inside :func:`backend_generation` only.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import warnings
from typing import Any, Dict, Iterator, List, Optional, Tuple

KERNELS = ("flash_fwd", "flash_bwd_dq", "flash_bwd_dkv", "paged_attn")

# the scoped-VMEM limit the r05 round hit at 16.75 MB of residency —
# the budget every analytic estimate is checked against
VMEM_BUDGET_BYTES = 16 * 1024 * 1024
# fallback tile cap: 1024 is the r05-measured optimum edge and 2048
# failed to compile (PERF.md "Flash attention: sequence-independent
# VMEM") — the analytic fallback never guesses past what measurement
# established
MAX_TILE_EDGE = 1024
MIN_SEQ_BUCKET = 128

LANE_MULTIPLE = 128
# Mosaic sublane tile floors per dtype (the TPU001 table); wildcard
# dtypes validate at the STRICTEST floor so a wildcard entry is legal
# for every dtype it can match
SUBLANE_FLOOR = {"float32": 8, "bfloat16": 16, "float16": 16,
                 "int8": 32, "float8_e4m3fn": 32, "float8_e5m2": 32}
SUBLANE_FLOOR_STRICTEST = 32
DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "int8": 1,
               "float8_e4m3fn": 1, "float8_e5m2": 1}

_WILDCARD = (None, "*")


def dtype_name(dtype: Any) -> str:
    """Canonical dtype string for table keys (``jnp.bfloat16``,
    ``np.dtype``, and plain strings all normalize the same way)."""
    if isinstance(dtype, str):
        return dtype
    name = getattr(dtype, "name", None)
    if name:
        return str(name)
    name = getattr(dtype, "__name__", None)
    if name:
        return str(name)
    return str(dtype)


def seq_bucket(seq: int) -> int:
    """Power-of-two shape-class bucket covering ``seq`` (min 128)."""
    b = MIN_SEQ_BUCKET
    while b < seq:
        b *= 2
    return b


def fit_block(seq: int, block: int) -> int:
    """Largest divisor of ``seq`` that is ≤ ``block`` — the flash
    kernels require blocks dividing the sequence, so a table value is
    fitted to the actual shape instead of failing the call."""
    block = max(1, min(int(block), int(seq)))
    for b in range(block, 0, -1):
        if seq % b == 0:
            return b
    return 1


def backend_generation() -> str:
    """Chip-generation component of the shape class: ``tpu_v4``-style
    for TPU backends (from ``device_kind``), the backend name
    otherwise. Deferred jax import — callers that only validate tables
    never pay it."""
    try:
        import jax
    except Exception:  # pragma: no cover - jax is always present in-tree
        return "cpu"
    backend = jax.default_backend()
    if backend != "tpu":
        return backend
    kind = jax.devices()[0].device_kind
    slug = "".join(ch if ch.isalnum() else "_" for ch in kind.lower())
    while "__" in slug:
        slug = slug.replace("__", "_")
    return slug.strip("_") or "tpu"


# ---------------------------------------------------------------------------
# Analytic VMEM estimates: the legality core shared by the load-time
# guard, the fallback selector, the sweep's skip-list, and TPU001
# ---------------------------------------------------------------------------


def flash_vmem_bytes(kernel: str, block_q: int, block_k: int,
                     head_dim: int, dtype_bytes: int) -> int:
    """Per-grid-step VMEM residency estimate for one flash kernel.

    I/O blocks are doubled for the grid pipeline's double buffering;
    the f32 score/probability tile (``block_q × block_k``) is the term
    that reproduces the r05 wall — it is exactly what pushes 2048-edge
    tiles past the 16 MB scoped budget while 1024 fits.
    """
    f32 = 4
    d = head_dim
    score = block_q * block_k * f32
    if kernel == "flash_fwd":
        # in: q, k, v; out: o, lse — scratch: f32 acc + m + l
        io = (2 * block_q * d + 2 * block_k * d) * dtype_bytes + block_q * f32
        scratch = (block_q * d + 2 * block_q) * f32
    elif kernel == "flash_bwd_dq":
        # in: q, k, v, g, lse, delta; out: dq — scratch: f32 acc
        io = ((3 * block_q * d + 2 * block_k * d) * dtype_bytes
              + 2 * block_q * f32)
        scratch = block_q * d * f32
    elif kernel == "flash_bwd_dkv":
        # in: q, k, v, g, lse, delta; out: dk, dv — scratch: 2× f32 acc
        io = ((2 * block_q * d + 4 * block_k * d) * dtype_bytes
              + 2 * block_q * f32)
        scratch = 2 * block_k * d * f32
    else:
        raise ValueError(f"unknown flash kernel {kernel!r}")
    return 2 * io + scratch + score


def paged_vmem_bytes(page_size: int, n_heads: int, n_kv_heads: int,
                     head_dim: int, dtype_bytes: int) -> int:
    """Per-grid-step VMEM residency for the paged decode kernel: one
    K/V page pair, one q row/out row, f32 accumulators. Independent of
    ``head_block`` (the whole page block is fetched either way — the
    knob changes compute batching, not residency)."""
    f32 = 4
    io = (2 * page_size * n_kv_heads * head_dim
          + 2 * n_heads * head_dim) * dtype_bytes
    scratch = (n_heads * head_dim + 2 * n_heads) * f32
    return 2 * io + scratch


# ---------------------------------------------------------------------------
# Table entries: schema, validation, matching
# ---------------------------------------------------------------------------

# Entry schema (one JSON object per shape class):
#   kernel      str, one of KERNELS                          (required)
#   seq_bucket  int pow2 — required for flash kernels, optional
#               (wildcard) for paged_attn
#   head_dim / n_heads / n_kv_heads   int or null (wildcard)
#   dtype       canonical dtype str or "*"/null
#   causal      bool or null
#   generation  backend_generation() slug or "*"/null
#   page_size   int or null — paged_attn only
#   block_q / block_k   int — flash kernels
#   head_block  int — paged_attn (KV heads per compute group)
#   provenance  str — where the numbers came from (r05 sweep, seed, …)

_MATCH_FIELDS = ("head_dim", "n_heads", "n_kv_heads", "dtype", "causal",
                 "generation", "page_size")


def entry_key(entry: Dict[str, Any]) -> str:
    """Compact human identity for messages and sweep output."""
    parts = [str(entry.get("kernel", "?"))]
    sb = entry.get("seq_bucket")
    parts.append(f"s{sb}" if sb else "s*")
    for field, tag in (("head_dim", "d"), ("n_heads", "h"),
                       ("n_kv_heads", "kv"), ("page_size", "p")):
        v = entry.get(field)
        if v not in _WILDCARD:
            parts.append(f"{tag}{v}")
    dt = entry.get("dtype")
    parts.append(dt if dt not in _WILDCARD else "*")
    causal = entry.get("causal")
    if causal is not None:
        parts.append("causal" if causal else "bidir")
    gen = entry.get("generation")
    if gen not in _WILDCARD:
        parts.append(str(gen))
    return "/".join(parts)


def _int_field(entry: Dict[str, Any], field: str,
               errs: List[str]) -> Optional[int]:
    v = entry.get(field)
    if v in _WILDCARD:
        return None
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        errs.append(f"{field} must be a positive int or null, got {v!r}")
        return None
    return v


def validate_entry(entry: Dict[str, Any],
                   budget: int = VMEM_BUDGET_BYTES) -> List[str]:
    """All the reasons ``entry`` is illegal (empty list = legal):
    divisibility, dtype-lane/sublane legality, and the analytic VMEM
    estimate vs the scoped budget. Shared verbatim by the loader's
    reject-with-warning path, ``tile_sweep.py --validate``, and the
    TPU001 table lint — one legality definition, three gates."""
    errs: List[str] = []
    kernel = entry.get("kernel")
    if kernel not in KERNELS:
        return [f"unknown kernel {kernel!r}; valid: {KERNELS}"]
    dtype = entry.get("dtype")
    if dtype in _WILDCARD:
        floor, nbytes = SUBLANE_FLOOR_STRICTEST, 4
    elif dtype in SUBLANE_FLOOR:
        floor, nbytes = SUBLANE_FLOOR[dtype], DTYPE_BYTES[dtype]
    else:
        errs.append(f"unknown dtype {dtype!r}; known: "
                    f"{sorted(SUBLANE_FLOOR)} or \"*\"")
        floor, nbytes = SUBLANE_FLOOR_STRICTEST, 4
    sb = _int_field(entry, "seq_bucket", errs)
    if sb is not None and sb & (sb - 1):
        errs.append(f"seq_bucket {sb} must be a power of two")
        sb = None
    head_dim = _int_field(entry, "head_dim", errs) or 128
    n_heads = _int_field(entry, "n_heads", errs) or 16
    n_kv = _int_field(entry, "n_kv_heads", errs)

    if kernel == "paged_attn":
        hb = entry.get("head_block", 1)
        if not isinstance(hb, int) or isinstance(hb, bool) or hb < 1:
            errs.append(f"head_block must be a positive int, got {hb!r}")
        elif hb > 1:
            if n_kv is None:
                errs.append("head_block > 1 requires a concrete "
                            "n_kv_heads (divisibility is unknowable "
                            "against a wildcard)")
            elif n_kv % hb:
                errs.append(f"head_block {hb} does not divide "
                            f"n_kv_heads {n_kv}")
        page_size = _int_field(entry, "page_size", errs) or 64
        vm = paged_vmem_bytes(page_size, n_heads, n_kv or n_heads,
                              head_dim, nbytes)
        if vm > budget:
            errs.append(f"VMEM estimate {vm} bytes exceeds the "
                        f"{budget}-byte scoped budget")
        return errs

    # flash kernels: (block_q, block_k) as independent knobs
    if sb is None and "seq_bucket must" not in " ".join(errs):
        errs.append(f"{kernel} entries require a concrete seq_bucket")
    bq = _int_field(entry, "block_q", errs)
    bk = _int_field(entry, "block_k", errs)
    if bq is None or bk is None:
        if "block_q" not in entry or "block_k" not in entry:
            errs.append(f"{kernel} entries require block_q and block_k")
        return errs
    if sb is not None:
        if sb % bq:
            errs.append(f"block_q {bq} does not divide seq_bucket {sb}")
        if sb % bk:
            errs.append(f"block_k {bk} does not divide seq_bucket {sb}")
    if bq % floor:
        errs.append(f"block_q {bq} is not a multiple of the "
                    f"{dtype or '*'} sublane floor {floor}")
    if bk % LANE_MULTIPLE:
        errs.append(f"block_k {bk} is not a multiple of the 128 lane "
                    "tile (the score tile's lane axis)")
    vm = flash_vmem_bytes(kernel, bq, bk, head_dim, nbytes)
    if vm > budget:
        errs.append(f"VMEM estimate {vm} bytes exceeds the "
                    f"{budget}-byte scoped budget (the r05 wall that "
                    "rejected 2048-edge tiles)")
    return errs


def _entry_sort_key(entry: Dict[str, Any]) -> Tuple:
    return (str(entry.get("kernel", "")),
            entry.get("seq_bucket") or 0,
            str(entry.get("dtype") or "*"),
            not bool(entry.get("causal")),
            str(entry.get("generation") or "*"),
            entry.get("head_dim") or 0,
            entry.get("n_heads") or 0)


@dataclasses.dataclass
class TileTable:
    """A loaded tile table: validated entries plus the rejects (kept so
    ``tile_sweep.py --validate`` and TPU001 can report them)."""

    entries: List[Dict[str, Any]]
    rejected: List[Tuple[Dict[str, Any], List[str]]]
    path: Optional[str] = None
    version: int = 1

    def lookup(self, kernel: str, *, seq: int, head_dim: int,
               n_heads: int, n_kv_heads: int, dtype: Any, causal: bool,
               generation: str,
               page_size: Optional[int] = None) -> Optional[Dict[str, Any]]:
        """Most-specific entry matching the shape class, or None.

        A field matches when the entry pins the same value or carries a
        wildcard; specificity = count of concretely-matched fields, so
        a chip-generation-pinned row outranks a ``"*"`` seed row.
        """
        bucket = seq_bucket(seq)
        want = {"head_dim": head_dim, "n_heads": n_heads,
                "n_kv_heads": n_kv_heads, "dtype": dtype_name(dtype),
                "causal": bool(causal), "generation": generation,
                "page_size": page_size}
        best, best_score = None, -1
        for e in self.entries:
            if e.get("kernel") != kernel:
                continue
            esb = e.get("seq_bucket")
            if esb is not None and esb != bucket:
                continue
            score = 1 if esb is not None else 0
            ok = True
            for field in _MATCH_FIELDS:
                ev = e.get(field)
                if ev in _WILDCARD:
                    continue
                if want[field] is None or ev != want[field]:
                    ok = False
                    break
                score += 1
            if ok and score > best_score:
                best, best_score = e, score
        return best

    def to_dict(self) -> Dict[str, Any]:
        entries = sorted(self.entries, key=_entry_sort_key)
        return {"version": self.version,
                "vmem_budget_bytes": VMEM_BUDGET_BYTES,
                "entries": entries}


DEFAULT_TABLE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "tile_table.json")


def load_table(path: Optional[str] = None, *, strict: bool = False,
               warn: bool = True) -> TileTable:
    """Load and validate a tile table.

    Non-strict (the runtime path): an unreadable file or an illegal
    entry is NEVER a failure — bad rows are dropped with a warning and
    the analytic fallback serves their shape classes. Strict (the
    ``tile_sweep.py --validate`` gate): any problem raises.
    """
    path = path or DEFAULT_TABLE_PATH
    if not os.path.exists(path):
        if strict:
            raise FileNotFoundError(f"tile table missing: {path}")
        return TileTable([], [], path=path)
    try:
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
    except (ValueError, OSError) as e:
        # unreadable (permissions, replaced by a directory) and
        # unparseable tables take the same never-fail path; the parse
        # failure rides `rejected` so the TPU001 table lint still sees
        # a broken commit (a missing-entries table lints green only
        # when it is GENUINELY empty)
        if strict:
            raise ValueError(f"tile table {path} is unreadable or not "
                             f"valid JSON: {e}")
        if warn:
            warnings.warn(f"tile table {path} unreadable ({e}); "
                          "falling back to analytic tile selection",
                          stacklevel=2)
        return TileTable([], [({}, [f"table unreadable or not valid "
                                    f"JSON: {e}"])], path=path)
    entries: List[Dict[str, Any]] = []
    rejected: List[Tuple[Dict[str, Any], List[str]]] = []
    for entry in raw.get("entries", []):
        errs = validate_entry(entry)
        if errs:
            if strict:
                raise ValueError(
                    f"tile table {path} entry {entry_key(entry)} is "
                    f"illegal: {'; '.join(errs)}")
            if warn:
                warnings.warn(
                    f"tile table entry {entry_key(entry)} rejected "
                    f"({'; '.join(errs)}); the analytic fallback serves "
                    "this shape class", stacklevel=2)
            rejected.append((entry, errs))
        else:
            entries.append(entry)
    return TileTable(entries, rejected, path=path,
                     version=int(raw.get("version", 1)))


def save_table(table: TileTable, path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(table.to_dict(), f, indent=2, sort_keys=True)
        f.write("\n")


_TABLE_CACHE: Optional[TileTable] = None


def active_table() -> TileTable:
    global _TABLE_CACHE
    if _TABLE_CACHE is None:
        _TABLE_CACHE = load_table()
    return _TABLE_CACHE


@contextlib.contextmanager
def table_override(table) -> Iterator[TileTable]:
    """Swap the active table for a test or an experiment: accepts a
    :class:`TileTable` or a path."""
    global _TABLE_CACHE
    prev = _TABLE_CACHE
    _TABLE_CACHE = table if isinstance(table, TileTable) else load_table(
        table)
    try:
        yield _TABLE_CACHE
    finally:
        _TABLE_CACHE = prev


# ---------------------------------------------------------------------------
# Resolution: kernel key + shape class -> TileConfig
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TileConfig:
    """One resolved tile choice plus where it came from (``table``:
    committed measurement, ``fallback``: analytic VMEM fit,
    ``override``: caller pinned it)."""

    kernel: str
    block_q: int = 0
    block_k: int = 0
    head_block: int = 0
    source: str = "fallback"

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"kernel": self.kernel, "source": self.source}
        if self.kernel == "paged_attn":
            d["head_block"] = self.head_block
        else:
            d["block_q"] = self.block_q
            d["block_k"] = self.block_k
        return d


_RECORDERS: List[List[Dict[str, Any]]] = []


@contextlib.contextmanager
def record_resolutions() -> Iterator[List[Dict[str, Any]]]:
    """Collect every tile resolution made inside the block — the bench
    harness wraps a config's run in this so the artifact row carries
    ``tile_config`` (resolved blocks + source) and an A/B round can
    attribute a throughput move to a table change."""
    buf: List[Dict[str, Any]] = []
    _RECORDERS.append(buf)
    try:
        yield buf
    finally:
        _RECORDERS.remove(buf)


def _record(cfg: TileConfig, shape: Dict[str, Any]) -> TileConfig:
    if _RECORDERS:
        d = cfg.as_dict()
        d["shape"] = shape
        for buf in _RECORDERS:
            buf.append(d)
    return cfg


def summarize_resolutions(buf: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Order-preserving dedup of a recorder buffer for the bench row."""
    seen, out = set(), []
    for d in buf:
        key = (d["kernel"], d.get("block_q"), d.get("block_k"),
               d.get("head_block"), d["source"])
        if key in seen:
            continue
        seen.add(key)
        out.append(d)
    return out


def _fallback_flash(kernel: str, seq: int, head_dim: int,
                    dtype: Any) -> Tuple[int, int]:
    """Analytic tile choice when the table has no entry: the largest
    square pow2 edge ≤ the measured cap that fits the VMEM budget."""
    nbytes = DTYPE_BYTES.get(dtype_name(dtype), 4)
    edge = min(MAX_TILE_EDGE, seq_bucket(seq))
    while edge > 1:
        if flash_vmem_bytes(kernel, edge, edge, head_dim,
                            nbytes) <= VMEM_BUDGET_BYTES:
            return edge, edge
        edge //= 2
    return 1, 1


def resolve_flash(kernel: str, *, seq: int, head_dim: int, n_heads: int,
                  n_kv_heads: int, dtype: Any, causal: bool,
                  block_q: Optional[int] = None,
                  block_k: Optional[int] = None,
                  generation: Optional[str] = None) -> TileConfig:
    """Resolve one flash kernel's ``(block_q, block_k)``.

    Explicit knobs win untouched (``source="override"`` — the kernel's
    own divisibility check stays the loud guard for a bad override);
    otherwise the table's most-specific entry, fitted to divisors of
    the actual ``seq``; otherwise the analytic VMEM fallback. A partial
    override pins one knob and resolves the other.
    """
    if kernel not in KERNELS or kernel == "paged_attn":
        raise ValueError(f"not a flash kernel key: {kernel!r}")
    shape = {"seq": seq, "head_dim": head_dim, "n_heads": n_heads,
             "n_kv_heads": n_kv_heads, "dtype": dtype_name(dtype),
             "causal": bool(causal)}
    if block_q is not None and block_k is not None:
        return _record(TileConfig(kernel, int(block_q), int(block_k),
                                  source="override"), shape)
    gen = generation or backend_generation()
    entry = active_table().lookup(
        kernel, seq=seq, head_dim=head_dim, n_heads=n_heads,
        n_kv_heads=n_kv_heads, dtype=dtype, causal=causal, generation=gen)
    if entry is not None:
        bq, bk, source = entry["block_q"], entry["block_k"], "table"
    else:
        bq, bk = _fallback_flash(kernel, seq, head_dim, dtype)
        source = "fallback"
    bq, bk = fit_block(seq, bq), fit_block(seq, bk)
    if block_q is not None:
        bq, source = int(block_q), "override"
    if block_k is not None:
        bk, source = int(block_k), "override"
    return _record(TileConfig(kernel, bq, bk, source=source), shape)


def resolve_paged(*, max_seq_len: int, page_size: int, n_heads: int,
                  n_kv_heads: int, head_dim: int, dtype: Any,
                  head_block: Optional[int] = None,
                  generation: Optional[str] = None) -> TileConfig:
    """Resolve the paged decode kernel's KV ``head_block`` group size.

    Same precedence as the flash path; a table entry whose head_block
    does not divide THIS shape's ``n_kv_heads`` degrades to the safe
    per-head loop (1) rather than raising — never a compile failure
    from a table row.
    """
    shape = {"max_seq_len": max_seq_len, "page_size": page_size,
             "n_heads": n_heads, "n_kv_heads": n_kv_heads,
             "head_dim": head_dim, "dtype": dtype_name(dtype)}
    if head_block is not None:
        return _record(TileConfig("paged_attn",
                                  head_block=int(head_block),
                                  source="override"), shape)
    gen = generation or backend_generation()
    entry = active_table().lookup(
        "paged_attn", seq=max_seq_len, head_dim=head_dim,
        n_heads=n_heads, n_kv_heads=n_kv_heads, dtype=dtype, causal=True,
        generation=gen, page_size=page_size)
    hb, source = 1, "fallback"
    if entry is not None:
        hb, source = int(entry.get("head_block", 1)), "table"
        if n_kv_heads % hb:
            hb, source = 1, "fallback"
    return _record(TileConfig("paged_attn", head_block=hb, source=source),
                   shape)
