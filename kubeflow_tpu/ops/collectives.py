"""Collective wrappers + microbenchmarks over mesh axes.

The reference's collective layer is external NCCL/gloo/gRPC wired by env
protocols (SURVEY.md §2d); its benchmark story for allreduce is the Horovod
image inside MPIJob (``/root/reference/kubeflow/mpi-job/``). Here collectives
are XLA primitives over ICI, and this module gives them a typed surface +
the bus-bandwidth-style microbenchmark BASELINE.md config 4 asks for.

All wrappers take the *full* (unsharded view) array and a mesh; ``shard_map``
partitions over the named axis so the collective pattern is explicit and
XLA lowers it onto the ICI ring of that axis.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Callable, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu import compat


@functools.lru_cache(maxsize=None)
def _mapped(op_key: str, mesh: Mesh, axis: str, shift: int = 0):
    """Build (once per op/mesh/axis) the jitted shard_map collective.

    Cached so repeated calls — the benchmark loop in particular — reuse one
    traced executable instead of recompiling per invocation. check_vma off:
    gather/permute outputs are replicated or shifted in ways the static
    varying-axes inference can't always prove.
    """
    if op_key == "all_reduce":
        op = functools.partial(jax.lax.psum, axis_name=axis)
        in_spec, out_spec = P(axis), P()
    elif op_key == "all_gather":
        op = functools.partial(jax.lax.all_gather, axis_name=axis, tiled=True)
        in_spec, out_spec = P(axis), P()
    elif op_key == "reduce_scatter":
        op = functools.partial(jax.lax.psum_scatter, axis_name=axis, tiled=True)
        in_spec, out_spec = P(None, axis), P(axis)
    elif op_key == "all_to_all":
        op = functools.partial(
            jax.lax.all_to_all, axis_name=axis, split_axis=1, concat_axis=0,
            tiled=True,
        )
        in_spec, out_spec = P(axis), P(None, axis)
    elif op_key == "ppermute":
        n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
        perm = [(j, (j + shift) % n) for j in range(n)]
        op = functools.partial(jax.lax.ppermute, axis_name=axis, perm=perm)
        in_spec, out_spec = P(axis), P(axis)
    else:
        raise ValueError(f"unknown collective {op_key!r}")
    return jax.jit(
        compat.shard_map(
            op, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
            check_vma=False,
        )
    )


def all_reduce(x, mesh: Mesh, axis: str = "dp"):
    """Sum over the axis; every shard returns the reduced value (replicated
    along that axis in the result)."""
    return _mapped("all_reduce", mesh, axis)(x)


def all_gather(x, mesh: Mesh, axis: str = "dp"):
    return _mapped("all_gather", mesh, axis)(x)


def reduce_scatter(x, mesh: Mesh, axis: str = "dp"):
    return _mapped("reduce_scatter", mesh, axis)(x)


def all_to_all(x, mesh: Mesh, axis: str = "dp"):
    """Transpose shard axis 0 against dim 1 (the MoE dispatch pattern)."""
    return _mapped("all_to_all", mesh, axis)(x)


def ppermute_shift(x, mesh: Mesh, axis: str = "dp", shift: int = 1):
    """Ring rotation by ``shift`` hops (the ring-attention primitive)."""
    return _mapped("ppermute", mesh, axis, shift)(x)


# ---------------------------------------------------------------------------
# Microbenchmark (BASELINE.md config 4: the NCCL-allreduce replacement)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CollectiveResult:
    op: str
    size_mb: float
    n_devices: int
    mean_s: float
    # algorithmic bus bandwidth, NCCL-tests convention: allreduce moves
    # 2(n-1)/n bytes per byte of payload over the slowest link
    bus_gb_s: float


_BUS_FACTOR = {
    "all_reduce": lambda n: 2 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / n,
    "ppermute": lambda n: 1.0,
}

_OPS: Dict[str, Callable] = {
    "all_reduce": all_reduce,
    "all_gather": all_gather,
    "reduce_scatter": reduce_scatter,
    "all_to_all": all_to_all,
    "ppermute": ppermute_shift,
}


def bench_collective(
    op: str, mesh: Mesh, axis: str = "dp", *, size_mb: float = 64.0,
    iters: int = 10, warmup: int = 2,
) -> CollectiveResult:
    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
    n_elem = int(size_mb * 1e6 / 4)
    n_elem -= n_elem % (n * n)  # divisible for scatter/a2a reshapes
    x = jnp.arange(n_elem, dtype=jnp.float32)
    if op in ("reduce_scatter",):
        x = x.reshape(n, -1)
    if op in ("all_to_all",):
        x = x.reshape(n, -1)
    fn = _OPS[op]
    for _ in range(warmup):
        jax.block_until_ready(fn(x, mesh, axis))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(x, mesh, axis)
    jax.block_until_ready(out)
    mean_s = (time.perf_counter() - t0) / iters
    payload = n_elem * 4
    bus = payload * _BUS_FACTOR[op](n) / mean_s / 1e9
    return CollectiveResult(op, payload / 1e6, n, mean_s, bus)


def bench_all(mesh: Mesh, axis: str = "dp", *, size_mb: float = 64.0,
              iters: int = 10) -> List[CollectiveResult]:
    return [
        bench_collective(op, mesh, axis, size_mb=size_mb, iters=iters)
        for op in _OPS
    ]
