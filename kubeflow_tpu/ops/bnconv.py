"""Fused BN-apply + ReLU + 1x1-conv for the ResNet bottleneck.

The r3 trace decomposition (PERF.md) shows exact-BN ResNet-50 training
at hbm_bound_fraction 0.96 with ~23 ms/step of pure normalize/ReLU
passes — each one a full read + write of an (N, H, W, C) activation.
The fusable site is ``relu(bn2(y)) -> conv3 (1x1, stride 1)``: a 1x1
conv is a GEMM over pixels, so the BN affine + ReLU can be applied
INLINE while the GEMM streams its input, eliminating the separate
normalize pass entirely (one read of the conv2 output instead of
read + write + read).

Autodiff boundary: the custom_vjp wraps only ``f(x, a, b, w)`` where
``a = gamma * rsqrt(var + eps)`` and ``b = beta - mean * a`` are plain
jnp values computed OUTSIDE the op — so the gradient chain through the
batch statistics (mean/var depend on x) is ordinary XLA autodiff; the
hand-written backward only covers the GEMM sandwich itself.

Reference analog: cuDNN's fused conv-bias-activation epilogues the
reference's CUDA stack gets from the framework (e.g. tf fused_batch_norm
+ conv autotuning); here the fusion is an explicit Pallas kernel because
XLA cannot fuse a producer BN-apply into a conv's input side.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from kubeflow_tpu.ops.attention import _resolve_interpret


def _pick_block(dim: int, want: int, floor: int = 8) -> int:
    """Largest power-of-two block <= want that divides dim (>= floor).

    ``floor`` encodes the TPU block-layout rule (ops/attention.py): a
    dimension that appears as a *lane* (last) axis of any kernel block
    needs tiles that are multiples of 128 — Mosaic rejects smaller lane
    tiles in compiled mode even though interpret-mode CPU tests accept
    them. K and N are lane axes here (x/a/b and w/o blocks), so their
    floor is 128; M only ever appears as a sublane axis (floor 8).
    Shapes with no legal block fall back to the XLA composition.

    tpulint rule TPU001 (docs/ANALYSIS.md) enforces the lane floor
    statically: dropping a ``floor=128`` from a lane-axis pick is a
    lint error, not a latent Mosaic crash.
    """
    b = want
    while b >= floor:
        if dim % b == 0:
            return b
        b //= 2
    return 0


def _tileable(M: int, K: int, N: int) -> bool:
    return bool(_pick_block(M, 512) and _pick_block(K, 256, floor=128)
                and _pick_block(N, 256, floor=128))


def _reference(x, a, b, w, act_dtype=None):
    """The unfused composition (also the fallback for untileable shapes).

    ``act_dtype`` reproduces the unfused model's normalize rounding: the
    BN output is materialized in ``bn_dtype`` there, so the fused path
    must round the activation through the same dtype before the GEMM or
    an A/B against the unfused model diverges whenever bn_dtype differs
    from the compute dtype."""
    y = jnp.maximum(x.astype(jnp.float32) * a + b, 0.0)
    y = y.astype(act_dtype if act_dtype is not None else x.dtype)
    return jax.lax.dot_general(y.astype(x.dtype), w,
                               (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32
                               ).astype(x.dtype)


def _fwd_kernel(x_ref, a_ref, b_ref, w_ref, o_ref, acc_ref, *, nk: int,
                act_dtype):
    import jax.experimental.pallas as pl

    kidx = pl.program_id(2)

    @pl.when(kidx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...].astype(jnp.float32)
    y = jnp.maximum(xb * a_ref[...] + b_ref[...], 0.0).astype(act_dtype)
    acc_ref[...] += jax.lax.dot_general(
        y.astype(x_ref.dtype), w_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(kidx == nk - 1)
    def _emit():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _dw_kernel(x_ref, a_ref, b_ref, g_ref, dw_ref, acc_ref, *, nm: int,
               act_dtype):
    """dW = relu(x*a+b)^T @ dz, recomputing the activation inline while
    streaming x — the backward never materializes y either."""
    import jax.experimental.pallas as pl

    midx = pl.program_id(2)

    @pl.when(midx == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xb = x_ref[...].astype(jnp.float32)
    y = jnp.maximum(xb * a_ref[...] + b_ref[...], 0.0).astype(act_dtype)
    acc_ref[...] += jax.lax.dot_general(
        y.astype(x_ref.dtype), g_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(midx == nm - 1)
    def _emit():
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def fused_scale_relu_matmul(x, a, b, w, interpret: Optional[bool] = None,
                            act_dtype: Optional[Any] = None):
    """``relu(x * a + b) @ w`` in one pass over ``x``.

    x: (M, K) activations (bf16/f32); a, b: (K,) f32 per-channel affine;
    w: (K, N) weights. Returns (M, N) in x.dtype. Shapes that don't
    tile (tiny test models) fall back to the XLA composition.
    ``act_dtype`` (default: x.dtype) is the dtype the normalized
    activation is rounded through before the GEMM — thread the model's
    ``bn_dtype`` here so the fused path matches the unfused BN's
    materialization numerics.
    """
    return _fused_fwd_impl(x, a, b, w, interpret, act_dtype)


def _fused_fwd_impl(x, a, b, w, interpret, act_dtype=None):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = x.shape
    N = w.shape[1]
    if act_dtype is None:
        act_dtype = x.dtype
    if not _tileable(M, K, N):
        return _reference(x, a, b, w, act_dtype)
    bm = _pick_block(M, 512)
    bk = _pick_block(K, 256, floor=128)
    bn = _pick_block(N, 256, floor=128)
    nk = K // bk
    return pl.pallas_call(
        functools.partial(_fwd_kernel, nk=nk, act_dtype=act_dtype),
        grid=(M // bm, N // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            pl.BlockSpec((1, bk), lambda m, n, k: (0, k)),
            pl.BlockSpec((1, bk), lambda m, n, k: (0, k)),
            pl.BlockSpec((bk, bn), lambda m, n, k: (k, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        out_shape=jax.ShapeDtypeStruct((M, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_resolve_interpret(interpret),
    )(x, a.astype(jnp.float32)[None, :], b.astype(jnp.float32)[None, :],
      w)


def _fused_vjp_fwd(x, a, b, w, interpret, act_dtype):
    return _fused_fwd_impl(x, a, b, w, interpret, act_dtype), (x, a, b, w)


def _fused_vjp_bwd(interpret, act_dtype, res, dz):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x, a, b, w = res
    M, K = x.shape
    N = w.shape[1]
    if act_dtype is None:
        act_dtype = x.dtype
    # chain through the activation: one elementwise recompute of xhat
    # (XLA fuses mask/dx/da/db into a single pass over x and dz@w.T)
    xf = x.astype(jnp.float32)
    xhat = xf * a.astype(jnp.float32) + b.astype(jnp.float32)
    dy = jax.lax.dot_general(dz, w, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dxhat = jnp.where(xhat > 0.0, dy, 0.0)
    dx = (dxhat * a.astype(jnp.float32)).astype(x.dtype)
    da = jnp.sum(dxhat * xf, axis=0).astype(a.dtype)
    db = jnp.sum(dxhat, axis=0).astype(b.dtype)

    if _tileable(M, K, N):
        bm = _pick_block(M, 512)
        bk = _pick_block(K, 256, floor=128)
        bn = _pick_block(N, 256, floor=128)
        nm = M // bm
        dw = pl.pallas_call(
            functools.partial(_dw_kernel, nm=nm, act_dtype=act_dtype),
            grid=(K // bk, N // bn, nm),
            in_specs=[
                pl.BlockSpec((bm, bk), lambda k, n, m: (m, k)),
                pl.BlockSpec((1, bk), lambda k, n, m: (0, k)),
                pl.BlockSpec((1, bk), lambda k, n, m: (0, k)),
                pl.BlockSpec((bm, bn), lambda k, n, m: (m, n)),
            ],
            out_specs=pl.BlockSpec((bk, bn), lambda k, n, m: (k, n)),
            out_shape=jax.ShapeDtypeStruct((K, N), jnp.float32),
            scratch_shapes=[pltpu.VMEM((bk, bn), jnp.float32)],
            interpret=_resolve_interpret(interpret),
        )(x, a.astype(jnp.float32)[None, :],
          b.astype(jnp.float32)[None, :], dz)
        dw = dw.astype(w.dtype)
    else:
        y = jnp.maximum(xhat, 0.0).astype(act_dtype).astype(x.dtype)
        dw = jax.lax.dot_general(y, dz, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32
                                 ).astype(w.dtype)
    return dx, da, db, dw


fused_scale_relu_matmul.defvjp(_fused_vjp_fwd, _fused_vjp_bwd)
