"""Reconcile-loop controller runtime (controller-runtime equivalent).

The reference's in-repo controllers are kubebuilder/controller-runtime Go
programs — watch + workqueue + Reconcile(key) with requeue-after
(``/root/reference/components/notebook-controller/.../notebook_controller.go:
59-307``). This module is that runtime shape on :class:`KubeClient`: watches
feed a deduplicating workqueue, a worker calls ``reconcile(namespace, name)``,
and a returned delay requeues. Everything is driven through the client
interface, so controllers run identically against the fake and a real API
server.
"""

from __future__ import annotations

import heapq
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.k8s.client import KubeClient, WatchEvent
from kubeflow_tpu.obs.trace import TRACER, Tracer
from kubeflow_tpu.utils import DEFAULT_REGISTRY

log = logging.getLogger(__name__)

# reconcile returns None (done) or a delay in seconds to requeue
ReconcileFn = Callable[[str, str], Optional[float]]

_reconciles_total = DEFAULT_REGISTRY.counter(
    "kftpu_controller_reconciles_total",
    "reconciles per controller on the shared workqueue runtime")


def make_condition(ctype: str, reason: str, message: str = "") -> dict:
    """Status condition in the k8s shape every operator here emits."""
    return {
        "type": ctype,
        "status": "True",
        "reason": reason,
        "message": message,
        "lastTransitionTime": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()),
    }


def set_phase_status(client: KubeClient, obj: dict, phase: str, *,
                     conditions: Optional[List[dict]] = None,
                     max_conditions: int = 10,
                     **fields) -> None:
    """Shared status writer: phase + fields + a deduped condition ring.

    Repeat conditions (same type+reason as the last entry) are dropped so
    a requeue loop neither churns status writes every few seconds nor
    evicts useful history from the ring. Writes only when something
    actually changed; a concurrently-deleted object is a no-op.
    """
    from kubeflow_tpu.k8s.helpers import update_status_ignore_missing

    status = dict(obj.get("status", {}))
    status["phase"] = phase
    status.update(fields)
    if conditions:
        existing = list(status.get("conditions", []))
        for cond in conditions:
            last = existing[-1] if existing else {}
            if (last.get("type") == cond["type"]
                    and last.get("reason") == cond["reason"]):
                continue
            existing.append(cond)
        status["conditions"] = existing[-max_conditions:]
    if status != obj.get("status"):
        obj["status"] = status
        update_status_ignore_missing(client, obj)


@dataclass(order=True)
class _Item:
    at: float
    key: Tuple[str, str] = field(compare=False)


class WorkQueue:
    """Deduplicating delayed workqueue with single-flight per key.

    A key queued with a delay is *promoted* when re-added sooner (a watch
    event must not be swallowed by a pending slow-poll requeue); the stale
    heap entry is skipped at pop time.

    Like the upstream k8s workqueue, a key handed to a worker is in-flight
    until :meth:`done`: re-adds meanwhile land in a dirty set and re-enqueue
    on completion, so two workers never reconcile the same key concurrently
    (which would race object creations against each other).
    """

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._heap: List[_Item] = []
        self._pending: Dict[Tuple[str, str], float] = {}
        self._processing: set = set()
        self._dirty: Dict[Tuple[str, str], float] = {}
        self._shutdown = False

    def add(self, key: Tuple[str, str], delay: float = 0.0) -> None:
        at = time.monotonic() + delay
        with self._cond:
            if key in self._processing:
                prev = self._dirty.get(key)
                if prev is None or at < prev:
                    self._dirty[key] = at
                return
            current = self._pending.get(key)
            if current is not None and current <= at:
                return  # already due no later than the new request
            self._pending[key] = at
            heapq.heappush(self._heap, _Item(at, key))
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Tuple[str, str]]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                if self._shutdown:
                    return None
                now = time.monotonic()
                while self._heap and self._heap[0].at <= now:
                    item = heapq.heappop(self._heap)
                    if self._pending.get(item.key) == item.at:
                        del self._pending[item.key]
                        self._processing.add(item.key)
                        return item.key
                    # stale entry superseded by a promotion; skip
                wait = self._heap[0].at - now if self._heap else None
                if deadline is not None:
                    remaining = deadline - now
                    if remaining <= 0:
                        return None
                    wait = remaining if wait is None else min(wait, remaining)
                self._cond.wait(wait)

    def done(self, key: Tuple[str, str]) -> None:
        """Worker finished this key; flush any re-adds that arrived mid-flight."""
        with self._cond:
            self._processing.discard(key)
            at = self._dirty.pop(key, None)
            if at is not None:
                current = self._pending.get(key)
                if current is None or at < current:
                    self._pending[key] = at
                    heapq.heappush(self._heap, _Item(at, key))
                    self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()


class Controller:
    """Watches primary (and owned) kinds, reconciles keys from a workqueue.

    This is the ONE reconcile runtime every control loop in the
    platform runs on — the tpujob operator, the workflow controller,
    the serving autoscaler's tick, and the scheduler queue's cycle —
    so every reconcile is uniformly traced (a ``controller.reconcile``
    span per invocation) and counted
    (``kftpu_controller_reconciles_total{controller=}``), whichever
    subsystem it belongs to.

    ``kind=None`` selects *periodic* mode (:meth:`periodic`): no watch,
    no resync — the controller seeds one synthetic key at start and the
    reconcile's returned delay drives the tick, through the same
    dedup/single-flight workqueue watch-driven controllers use.
    """

    def __init__(
        self,
        client: KubeClient,
        api_version: str,
        kind: Optional[str],
        reconcile: ReconcileFn,
        *,
        namespace: Optional[str] = None,
        name: str = "controller",
        resync_period_s: float = 300.0,
        tracer: Optional[Tracer] = None,
    ) -> None:
        self.client = client
        self.api_version = api_version
        self.kind = kind
        self.reconcile = reconcile
        self.namespace = namespace or None
        self.name = name
        self.resync_period_s = resync_period_s
        self.tracer = tracer if tracer is not None else TRACER
        self.queue = WorkQueue()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._owned: List[Tuple[str, str, Callable[[dict], Optional[Tuple[str, str]]]]] = []

    @classmethod
    def periodic(
        cls,
        reconcile: ReconcileFn,
        *,
        name: str = "periodic",
        tracer: Optional[Tracer] = None,
        client: Optional[KubeClient] = None,
    ) -> "Controller":
        """A watchless controller whose reconcile schedules itself by
        returning its next delay — the lift for loops that used to be
        hand-rolled ``while/sleep`` threads (autoscaler tick, scheduler
        queue cycle). The synthetic key is ``("", name)``; an external
        event can still ``queue.add`` it to force an immediate pass.
        ``client`` is optional: periodic mode never watches or lists."""
        return cls(client, "", None, reconcile, name=name,  # type: ignore[arg-type]
                   resync_period_s=0.0, tracer=tracer)

    def watch_owned(
        self,
        api_version: str,
        kind: str,
        key_fn: Callable[[dict], Optional[Tuple[str, str]]],
    ) -> None:
        """Watch a secondary kind; key_fn maps its objects to a primary key
        (e.g. via the job-name label), like controller-runtime's Owns()."""
        self._owned.append((api_version, kind, key_fn))

    def _pump(self, q: "queue.Queue[WatchEvent]",
              key_fn: Callable[[dict], Optional[Tuple[str, str]]]) -> None:
        while not self._stop.is_set():
            try:
                evt = q.get(timeout=0.2)
            except queue.Empty:
                continue
            key = key_fn(evt.object)
            if key is not None:
                self.queue.add(key)

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            ns, name = key
            # uniform reconcile tracing: one span per invocation, same
            # shape for every controller on this runtime, so scheduler
            # decisions, autoscaling ticks, and job status all read from
            # one trace surface
            with self.tracer.span(
                    "controller.reconcile",
                    attrs={"controller": self.name, "namespace": ns,
                           "name": name}) as sp:
                try:
                    requeue = self.reconcile(ns, name)
                except Exception:  # noqa: BLE001 — a controller never dies
                    log.exception("%s: reconcile %s/%s failed",
                                  self.name, ns, name)
                    sp.status = "ERROR: ReconcileException"
                    requeue = 5.0
                sp.attrs["requeueSeconds"] = requeue
            _reconciles_total.inc(controller=self.name)
            if requeue is not None:
                self.queue.add(key, delay=requeue)
            self.queue.done(key)

    def start(self, workers: int = 1) -> None:
        def primary_key(obj: dict) -> Tuple[str, str]:
            md = obj.get("metadata", {})
            return (md.get("namespace", ""), md["name"])

        if self.kind:
            q = self.client.watch(self.api_version, self.kind,
                                  self.namespace)
            t = threading.Thread(target=self._pump, args=(q, primary_key),
                                 daemon=True)
            t.start()
            self._threads.append(t)
            for (av, kind, key_fn) in self._owned:
                oq = self.client.watch(av, kind, self.namespace)
                t = threading.Thread(target=self._pump, args=(oq, key_fn),
                                     daemon=True)
                t.start()
                self._threads.append(t)
        else:
            # periodic mode: the reconcile's returned delay is the tick
            self.queue.add(("", self.name))
        for _ in range(workers):
            t = threading.Thread(target=self._worker, daemon=True)
            t.start()
            self._threads.append(t)
        if self.resync_period_s and self.kind:
            t = threading.Thread(target=self._resync_loop, daemon=True)
            t.start()
            self._threads.append(t)

    def _resync_loop(self) -> None:
        """Periodic full re-list: the safety net for lost watch events."""
        while not self._stop.wait(self.resync_period_s):
            try:
                for obj in self.client.list(self.api_version, self.kind,
                                            self.namespace):
                    md = obj.get("metadata", {})
                    self.queue.add((md.get("namespace", ""), md["name"]))
            except Exception:  # noqa: BLE001
                log.exception("%s: resync list failed", self.name)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shutdown()
        for t in self._threads:
            t.join(timeout=2)

    def run_forever(self) -> None:
        self.start()
        try:
            while True:  # park the main thread; workers do the work
                time.sleep(3600)  # tpulint: disable=TPU003,TPU005
        except KeyboardInterrupt:
            self.stop()
