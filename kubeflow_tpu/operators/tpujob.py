"""The TpuJob operator — one slice-aware job operator for all training.

Replaces the reference's per-framework operator family (tf-operator,
pytorch-operator, mpi-operator, …; CRDs in ``/root/reference/kubeflow/
{tf-training,pytorch-job,mpi-job}/``) with a single SPMD job semantics:

- a job asks for ``slices`` TPU slices × ``hostsPerSlice`` host workers;
- the whole gang is placed atomically (a slice is indivisible — SURVEY.md §7
  hard part (a)); placement maps worker index → (slice, host) with ICI
  adjacency via :mod:`kubeflow_tpu.scheduler`;
- the operator injects the ``jax.distributed`` env contract
  (:mod:`kubeflow_tpu.parallel.distributed`) instead of TF_CONFIG/hostfiles
  (reference wiring: ``tf-controller-examples/tf-cnn/launcher.py:68-80``,
  ``mpi-operator.libsonnet:287-289``);
- any worker failure fails the whole SPMD mesh: restart = delete the gang,
  re-place, and resume from the last in-framework checkpoint (hard part (b));
- status mirrors TFJob ergonomics: phase + conditions + per-state counts
  (``tf-job-operator.libsonnet:10-50`` validation, printer columns).
"""

from __future__ import annotations

import calendar
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubeflow_tpu.k8s import helpers
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import ApiError, KubeClient, register_plural
from kubeflow_tpu.manifests.components.tpujob_operator import (
    API_VERSION,
    TPUJOB_KIND,
    TPUJOB_PLURAL,
)
from kubeflow_tpu.obs import goodput as goodput_mod
from kubeflow_tpu.obs.steps import (
    DEFAULT_STRAGGLER_STEPS,
    ENV_JOB_UID,
    beacon_configmap_name,
    read_beacons,
    telemetry_view,
    tpujob_trace_ids,
)
from kubeflow_tpu.obs.trace import Tracer
from kubeflow_tpu.operators.controller import (
    Controller,
    make_condition as _condition,
)
from kubeflow_tpu.parallel import distributed as dist
from kubeflow_tpu.scheduler.inventory import (
    ASSIGNED_SLICE_LABEL,
    SLICE_INDEX_LABEL,
    GangScheduler,
)
from kubeflow_tpu.scheduler.placement import SlicePlacement, place_gang
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

register_plural(TPUJOB_KIND, TPUJOB_PLURAL)

PODGROUP_API = "scheduling.sigs.k8s.io/v1alpha1"
JOB_LABEL = "kubeflow-tpu.org/job-name"
SLICE_LABEL = "kubeflow-tpu.org/slice"
HOST_LABEL = "kubeflow-tpu.org/host"
# the gang topology a pod was built for; a live pod whose shape disagrees
# with the current spec marks an elastic resize (spec.slices edited on a
# running job) — the distributed env (world size, slice count) is baked
# into every worker, so a resize is a coordinated re-gang, never in-place
GANG_SHAPE_LABEL = "kubeflow-tpu.org/gang-shape"

PHASE_PENDING = "Pending"
PHASE_RUNNING = "Running"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"
PHASE_RESTARTING = "Restarting"

_reconciles = DEFAULT_REGISTRY.counter(
    "kftpu_operator_reconciles_total", "TpuJob reconcile invocations")
_restarts = DEFAULT_REGISTRY.counter(
    "kftpu_operator_gang_restarts_total", "whole-gang restarts")
_jobs_by_phase = DEFAULT_REGISTRY.gauge(
    "kftpu_operator_jobs", "jobs by phase")
_job_last_step = DEFAULT_REGISTRY.gauge(
    "kftpu_job_last_step", "max worker step observed per job")
_job_steps_per_sec = DEFAULT_REGISTRY.gauge(
    "kftpu_job_steps_per_sec", "median worker steps/sec per job")
_job_stragglers = DEFAULT_REGISTRY.gauge(
    "kftpu_job_stragglers", "workers >= K steps behind the gang median")
_job_resizes = DEFAULT_REGISTRY.counter(
    "kftpu_job_resizes_total",
    "elastic gang resizes completed, by direction (shrink|grow)")


@dataclass
class TpuJobSpec:
    """Typed view of a TpuJob CR's spec (CRD schema is open, this validates)."""

    image: str
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    slices: int = 1
    hosts_per_slice: int = 1
    chips_per_host: int = 4
    accelerator: str = "v5e-8"
    coordinator_port: int = 8476
    restart_policy: str = "OnFailure"  # Never | OnFailure
    max_restarts: int = 3
    gang_scheduling: bool = True
    # pod volumes + per-worker mounts (kubebench runs on a shared experiment
    # PVC: /root/reference/kubeflow/kubebench/kubebench-job.libsonnet:160-176)
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    volume_mounts: List[Dict[str, Any]] = field(default_factory=list)
    # pre-run data staging: each {"source": "gs://...", "target": "/data",
    # "image": <optional downloader image>} becomes an init container
    # copying the object tree into an emptyDir mounted at target — the
    # openmpi-controller's S3/GCS download role (/root/reference/kubeflow/
    # openmpi/ sidecar data staging), TPU-style. The downloader image
    # defaults per scheme (cloud-sdk for gs://, aws-cli for s3://).
    data_staging: List[Dict[str, str]] = field(default_factory=list)
    # straggler policy (docs/OBSERVABILITY.md): a worker this many steps
    # behind the gang's median beacon step is flagged in status
    straggler_steps: int = DEFAULT_STRAGGLER_STEPS
    # cluster scheduler plane (docs/SCHEDULER.md): priority classes
    # strictly dominate queue order; a preemptible job may be
    # checkpoint-preempted for a higher class when capacity is short.
    # totalSteps feeds the predictor's remaining-duration estimate
    # (0 = unknown — the queue keeps FIFO order, never guesses);
    # checkpointDir is where workers save/resume (restore_or_init).
    priority: int = 0
    preemptible: bool = True
    total_steps: int = 0
    checkpoint_dir: str = ""
    # elastic training (docs/ELASTIC.md): {"minSlices": a, "maxSlices": b}
    # declares the gang survives a live spec.slices edit within [a, b] —
    # the operator routes such resizes through snapshot→teardown→
    # re-gang→resume instead of the blind re-gang, and the scheduler
    # queue may OFFER a shrink-to-minSlices instead of preempting the
    # gang outright. None = fixed-shape job (the old behavior).
    elastic: Optional[Dict[str, int]] = None

    @property
    def is_elastic(self) -> bool:
        return self.elastic is not None

    @property
    def min_slices(self) -> Optional[int]:
        return self.elastic["minSlices"] if self.elastic else None

    @property
    def max_slices(self) -> Optional[int]:
        return self.elastic["maxSlices"] if self.elastic else None

    @property
    def num_workers(self) -> int:
        return self.slices * self.hosts_per_slice

    @property
    def chips(self) -> int:
        """The gang's chip footprint — the goodput rollup's weight and
        the queue's quota unit share this one definition."""
        return self.slices * self.hosts_per_slice * self.chips_per_host

    @classmethod
    def from_dict(cls, spec: Dict[str, Any]) -> "TpuJobSpec":
        out = cls(
            image=spec.get("image", ""),
            command=list(spec.get("command", []) or []),
            args=list(spec.get("args", []) or []),
            env=dict(spec.get("env", {}) or {}),
            slices=int(spec.get("slices", 1)),
            hosts_per_slice=int(spec.get("hostsPerSlice", 1)),
            chips_per_host=int(spec.get("chipsPerHost", 4)),
            accelerator=spec.get("accelerator", "v5e-8"),
            coordinator_port=int(spec.get("coordinatorPort", 8476)),
            restart_policy=spec.get("restartPolicy", "OnFailure"),
            max_restarts=int(spec.get("maxRestarts", 3)),
            gang_scheduling=bool(spec.get("gangScheduling", True)),
            volumes=list(spec.get("volumes", []) or []),
            volume_mounts=list(spec.get("volumeMounts", []) or []),
            data_staging=list(spec.get("dataStaging", []) or []),
            straggler_steps=int(spec.get("stragglerSteps",
                                         DEFAULT_STRAGGLER_STEPS)),
            priority=int(spec.get("priority", 0)),
            preemptible=bool(spec.get("preemptible", True)),
            total_steps=int(spec.get("totalSteps", 0)),
            checkpoint_dir=str(spec.get("checkpointDir", "") or ""),
            elastic=cls._parse_elastic(spec.get("elastic")),
        )
        out.validate()
        return out

    @staticmethod
    def _parse_elastic(raw: Any) -> Optional[Dict[str, int]]:
        if raw is None:
            return None
        if not isinstance(raw, dict):
            raise ValueError("spec.elastic must be an object with "
                             "minSlices/maxSlices")
        try:
            return {"minSlices": int(raw.get("minSlices", 1)),
                    "maxSlices": int(raw.get("maxSlices", raw.get(
                        "minSlices", 1)))}
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"spec.elastic bounds must be integers: {raw!r}") from e

    def validate(self) -> None:
        if not self.image:
            raise ValueError("spec.image is required")
        if self.slices < 1 or self.hosts_per_slice < 1:
            raise ValueError("slices and hostsPerSlice must be >= 1")
        if self.restart_policy not in ("Never", "OnFailure"):
            raise ValueError(f"invalid restartPolicy {self.restart_policy!r}")
        if self.straggler_steps < 1:
            raise ValueError("stragglerSteps must be >= 1")
        if self.total_steps < 0:
            raise ValueError("totalSteps must be >= 0")
        if self.elastic is not None:
            mn, mx = self.elastic["minSlices"], self.elastic["maxSlices"]
            if mn < 1:
                raise ValueError("elastic.minSlices must be >= 1")
            if mx < mn:
                raise ValueError(
                    f"elastic.maxSlices {mx} < minSlices {mn}")
            if not mn <= self.slices <= mx:
                raise ValueError(
                    f"slices {self.slices} outside elastic bounds "
                    f"[{mn}, {mx}]")
        for d in self.data_staging:
            if not d.get("source", "").startswith(("gs://", "s3://")):
                raise ValueError(
                    "dataStaging.source must be a gs:// or s3:// url")
            if not d.get("target", "").startswith("/"):
                raise ValueError("dataStaging.target must be an absolute path")


def tpujob(name: str, ns: str, spec: Dict[str, Any]) -> o.Obj:
    """Build a TpuJob CR dict (the user-facing prototype, ksonnet-generator
    equivalent of ``kubeflow/examples/prototypes/tf-job-simple-v1.jsonnet``)."""
    TpuJobSpec.from_dict(spec)
    return {
        "apiVersion": API_VERSION,
        "kind": TPUJOB_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


def worker_name(job_name: str, index: int) -> str:
    return f"{job_name}-w{index}"


def gang_shape(spec: "TpuJobSpec") -> str:
    return f"{spec.slices}x{spec.hosts_per_slice}"


def coordinator_address(job_name: str, ns: str, port: int) -> str:
    # headless Service gives each pod <hostname>.<service>.<ns>.svc DNS
    return f"{worker_name(job_name, 0)}.{job_name}.{ns}:{port}"


def build_service(job: o.Obj) -> o.Obj:
    name = job["metadata"]["name"]
    ns = job["metadata"]["namespace"]
    spec = TpuJobSpec.from_dict(job["spec"])
    svc = o.service(
        name, ns, {JOB_LABEL: name},
        [{"name": "coordinator", "port": spec.coordinator_port,
          "targetPort": spec.coordinator_port}],
        headless=True,
        labels={JOB_LABEL: name},
    )
    return o.set_owner(svc, job)


def build_podgroup(job: o.Obj) -> o.Obj:
    """Gang-scheduling PodGroup: the whole mesh or nothing (reference used
    optional kube-batch podgroups, ``tf-job-operator.libsonnet:268-277``)."""
    name = job["metadata"]["name"]
    ns = job["metadata"]["namespace"]
    spec = TpuJobSpec.from_dict(job["spec"])
    pg = {
        "apiVersion": PODGROUP_API,
        "kind": "PodGroup",
        "metadata": {"name": name, "namespace": ns,
                     "labels": {JOB_LABEL: name}},
        "spec": {"minMember": spec.num_workers},
    }
    return o.set_owner(pg, job)


def build_worker_pod(job: o.Obj, index: int, placement: SlicePlacement,
                     concrete_slice: Optional[str] = None) -> o.Obj:
    name = job["metadata"]["name"]
    ns = job["metadata"]["namespace"]
    spec = TpuJobSpec.from_dict(job["spec"])

    env = dict(spec.env)
    env.update({
        dist.ENV_COORDINATOR: coordinator_address(name, ns, spec.coordinator_port),
        dist.ENV_NUM_PROCESSES: str(spec.num_workers),
        dist.ENV_PROCESS_ID: str(index),
        dist.ENV_JOB_NAME: name,
        dist.ENV_NAMESPACE: ns,
        # CR identity for telemetry: workers derive the SAME training
        # trace id the operator does (obs.steps.tpujob_trace_ids)
        ENV_JOB_UID: job["metadata"].get("uid", ""),
        # TPU runtime topology hints (consumed by the TPU container runtime)
        "TPU_WORKER_ID": str(placement.host),
        "MEGASCALE_SLICE_ID": str(placement.slice_index),
        "MEGASCALE_NUM_SLICES": str(spec.slices),
    })
    if spec.checkpoint_dir:
        # the preemption contract: workers checkpoint here and resume
        # via CheckpointManager.restore_or_init, so a preempted gang
        # comes back with its step clock intact (docs/SCHEDULER.md)
        env.setdefault("KFTPU_CHECKPOINT_DIR", spec.checkpoint_dir)

    volumes = list(spec.volumes)
    mounts = list(spec.volume_mounts)
    init_containers: List[o.Obj] = []
    for k, staging in enumerate(spec.data_staging):
        vol = f"staged-{k}"
        volumes.append({"name": vol, "emptyDir": {}})
        mounts.append({"name": vol, "mountPath": staging["target"]})
        is_gcs = staging["source"].startswith("gs://")
        tool = "gcloud storage cp -r" if is_gcs else "aws s3 cp --recursive"
        default_image = ("google/cloud-sdk:slim" if is_gcs
                         else "amazon/aws-cli:2")
        init_containers.append(o.container(
            f"stage-{k}",
            staging.get("image", default_image),
            command=["sh", "-c",
                     f"{tool} '{staging['source']}' "
                     f"'{staging['target']}/'"],
            volume_mounts=[{"name": vol,
                            "mountPath": staging["target"]}],
        ))

    ctr = o.container(
        "worker",
        spec.image,
        command=spec.command or None,
        args=spec.args or None,
        env=env,
        ports=[spec.coordinator_port] if index == 0 else None,
        resources={"limits": {"google.com/tpu": spec.chips_per_host}},
        volume_mounts=mounts or None,
    )
    # node labels carry the GKE accelerator TYPE (tpu-v5-lite-podslice),
    # not the framework's shape name (v5e-8) — selecting on the shape name
    # would never match a real TPU node pool
    from kubeflow_tpu.platform.slices import slice_shape

    shape = slice_shape(spec.accelerator)
    pspec = o.pod_spec(
        [ctr],
        restart_policy="Never",  # the operator owns restart semantics: a
        # worker restarting alone cannot rejoin the SPMD mesh
        node_selector={
            "cloud.google.com/gke-tpu-accelerator": shape.accelerator,
            "cloud.google.com/gke-tpu-topology": placement.topology,
        },
        scheduler_name="kftpu-gang" if spec.gang_scheduling else None,
        volumes=volumes or None,
    )
    if init_containers:
        pspec["initContainers"] = init_containers
    pspec["hostname"] = worker_name(name, index)
    pspec["subdomain"] = name
    labels = {JOB_LABEL: name,
              SLICE_LABEL: str(placement.slice_index),
              HOST_LABEL: str(placement.host),
              GANG_SHAPE_LABEL: gang_shape(spec)}
    if concrete_slice:
        # the gang scheduler chose an exact cluster slice: pin to it and
        # record the claim so inventory accounting sees this host as busy
        labels[ASSIGNED_SLICE_LABEL] = concrete_slice
        pspec["nodeSelector"][SLICE_INDEX_LABEL] = (
            concrete_slice.rsplit("_", 1)[1])
    pod = o.pod(worker_name(name, index), ns, pspec, labels=labels)
    return o.set_owner(pod, job)


def _pod_phase(pod: o.Obj) -> str:
    return pod.get("status", {}).get("phase", "Pending")


def _parse_ts(stamp: str) -> Optional[float]:
    """Status timestamp -> epoch seconds (None on absent/garbage)."""
    try:
        return float(calendar.timegm(
            time.strptime(stamp, "%Y-%m-%dT%H:%M:%SZ")))
    except (TypeError, ValueError):
        return None




class PreemptionCheckpointer:
    """How the operator persists a victim's step clock at preemption.

    Production binds this to the job's ``spec.checkpointDir`` through
    :class:`kubeflow_tpu.train.checkpoint.CheckpointManager` (workers
    save on teardown, ``latest_step`` reads what landed); tests inject
    a fake and count ``save`` calls. Both methods return the persisted
    step, or ``None`` when nothing is known — the queue's victim-cost
    model treats ``None`` as maximal lost work.
    """

    def save(self, job: o.Obj) -> Optional[int]:
        """Ensure a checkpoint exists for this job; return its step."""
        raise NotImplementedError

    def latest_step(self, ns: str, name: str) -> Optional[int]:
        raise NotImplementedError


class TpuJobOperator:
    """Reconciles TpuJob CRs into gangs of worker pods + a headless Service.

    With ``queue`` (a :class:`kubeflow_tpu.scheduler.queue.GangQueue`)
    attached, gang creation flows through the cluster scheduler plane:
    jobs submit to the queue (tenancy-quota admission), wait for a
    priority/predicted-ordering placement grant, and honor preemption
    signals by checkpointing (``checkpointer``), tearing the gang down,
    and confirming the requeue (docs/SCHEDULER.md). Without a queue the
    operator keeps its original first-come placement."""

    def __init__(self, client: KubeClient, namespace: Optional[str] = None,
                 gang_scheduling: bool = True,
                 clock: Optional[Clock] = None,
                 tracer: Optional[Tracer] = None,
                 queue: Optional[Any] = None,
                 checkpointer: Optional[PreemptionCheckpointer] = None,
                 tsdb: Optional[Any] = None,
                 tsdb_window_s: float = 300.0
                 ) -> None:
        self.client = client
        self.namespace = namespace
        self.gang_scheduling = gang_scheduling
        self.queue = queue
        self.checkpointer = checkpointer
        # a monitoring-tier TimeSeriesStore (kubeflow_tpu/obs/tsdb.py):
        # when attached, the scheduler predictor is fed the job's
        # stepsPerSec series averaged over tsdb_window_s instead of the
        # instantaneous CR-status view, so prediction quality no longer
        # depends on reconcile timing; absent (or series missing) the
        # CR-status path is unchanged
        self.tsdb = tsdb
        self.tsdb_window_s = float(tsdb_window_s)
        # epoch-seconds clock (wall, not monotonic: the terminal job span
        # closes against startTime timestamps persisted in CR status) +
        # a tracer sharing it, so the training-job root span stays
        # deterministic under a fake clock (the workflow-controller shape)
        self.clock: Clock = clock if clock is not None else time.time
        self.tracer = tracer if tracer is not None else Tracer(
            clock=self.clock)
        # placement is read-inventory-then-create: without serialization,
        # two workers reconciling DIFFERENT jobs concurrently both see the
        # same slice free and double-book it (kube-scheduler likewise runs
        # one scheduling cycle at a time)
        self._placement_lock = threading.Lock()
        # goodput ledger export (docs/OBSERVABILITY.md "Goodput"):
        # ledger state itself lives in CR status.goodput — the exporter
        # only turns totals into monotone counters
        self._goodput = goodput_mod.GoodputExporter()

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, ns: str, name: str) -> Optional[float]:
        _reconciles.inc()
        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND, ns, name)
        if job is None:
            self._clear_job_gauges(ns, name)
            self._goodput.clear(ns, name)
            self._queue_release(ns, name)
            return None  # deleted; cascade GC cleans children
        try:
            spec = TpuJobSpec.from_dict(job["spec"])
        except ValueError as e:
            self._set_status(job, PHASE_FAILED,
                             conditions=[_condition("Failed", "InvalidSpec", str(e))])
            self._queue_release(ns, name)
            return None

        phase = job.get("status", {}).get("phase", PHASE_PENDING)
        if phase in (PHASE_SUCCEEDED, PHASE_FAILED):
            # the export lags one pass behind the persisted ledger by
            # design; a terminal job never folds again, so catch the
            # final persisted state up here
            self._goodput.export(ns, name, spec.chips,
                                 job.get("status", {}).get("goodput"))
            self._queue_release(ns, name)
            return None

        pods = self.client.list("v1", "Pod", ns, label_selector={JOB_LABEL: name})
        terminating = [p for p in pods
                       if p.get("metadata", {}).get("deletionTimestamp")]
        pods = [p for p in pods
                if not p.get("metadata", {}).get("deletionTimestamp")]

        # one beacon aggregation per reconcile, hoisted so the goodput
        # fold and the status update read the SAME observation
        telemetry = (self._job_telemetry(ns, name, spec) if pods
                     else None)
        # the goodput ledger (docs/OBSERVABILITY.md): fold the window
        # since the last reconcile into status.goodput BEFORE any
        # branch acts, so teardown/requeue passes are attributed too;
        # the fold is a replay-safe no-op when the clock has not moved
        self._fold_goodput(job, spec, pods, telemetry)

        if phase == PHASE_RESTARTING and (pods or terminating):
            # old gang still tearing down: wait, do NOT burn another restart
            if pods:
                self._delete_pods(ns, pods)
            return 1.0

        # scheduler-plane preemption: the queue picked this gang as the
        # minimum-cost victim for a higher-priority gang — checkpoint,
        # tear down, confirm the head-of-queue requeue
        if self.queue is not None and self.queue.preemption_requested(
                ns, name):
            return self._handle_preemption(job, spec, pods,
                                           telemetry=telemetry)

        # scheduler-plane shrink offer: the queue asked this elastic
        # gang to release slices instead of evicting it (cheaper than
        # preemption — the run keeps making progress at minSlices).
        # spec.elastic is the consent; the operator applies the spec
        # edit and the normal elastic-resize path does the rest.
        if (self.queue is not None and spec.is_elastic
                and getattr(self.queue, "shrink_requested", None)):
            target = self.queue.shrink_requested(ns, name)
            if target is not None and target < spec.slices:
                return self._apply_shrink_offer(job, spec, target)

        if not pods:
            if self.queue is not None:
                return self._reconcile_queued_create(job, spec)
            if not self._create_gang(job, spec):
                # concrete inventory exists but no free slice window: hold
                # the whole gang (never partial pods) and retry
                self._set_status(
                    job, PHASE_PENDING,
                    conditions=[_condition("Unschedulable", "NoFreeSlices",
                                           f"need {spec.slices} free "
                                           f"{spec.accelerator} slice(s)")])
                return 15.0
            resize, resize_conds = self._resize_completion(job, spec)
            self._set_status(job, PHASE_PENDING, restarts=self._restarts(job),
                             resize=resize,
                             conditions=[_condition("Created", "GangCreated")]
                             + resize_conds)
            return 1.0

        counts = {"Pending": 0, "Running": 0, "Succeeded": 0, "Failed": 0}
        for pod in pods:
            counts[_pod_phase(pod)] = counts.get(_pod_phase(pod), 0) + 1

        status_update: Dict[str, Any] = {"workers": counts}
        if telemetry is not None:
            status_update["telemetry"] = telemetry

        # elastic resize: spec.slices / hostsPerSlice edited under a live
        # gang. Every worker bakes the world size + slice count into its
        # env, so the whole gang re-places at the new shape; this does NOT
        # consume a failure restart. Pods predating the shape label are
        # left alone (their shape is unknowable). Jobs declaring
        # spec.elastic route through snapshot→teardown→re-gang→resume
        # (docs/ELASTIC.md) so the run survives; fixed-shape jobs keep
        # the original blind re-gang.
        shape = gang_shape(spec)
        stale = [p for p in pods
                 if (p.get("metadata", {}).get("labels", {}) or {})
                 .get(GANG_SHAPE_LABEL, shape) != shape]
        if stale:
            if spec.is_elastic:
                return self._handle_resize(job, spec, pods, stale,
                                           telemetry=telemetry)
            self._delete_pods(ns, pods)
            self._set_status(
                job, PHASE_RESTARTING,
                conditions=[_condition("Resizing", "ElasticResize",
                                       f"re-gang to {shape}")])
            log.info("elastic resize for %s/%s: re-gang to %s", ns, name,
                     shape)
            return 1.0

        if counts["Failed"] > 0:
            return self._handle_failure(job, spec, pods,
                                        telemetry=telemetry)

        if len(pods) < spec.num_workers:
            # a worker went missing (eviction, manual delete): the SPMD mesh
            # cannot proceed without it — recreate absent members in place
            # (under a queue, on the slices the queue already granted)
            granted = (self.queue.placement_for(ns, name)
                       if self.queue is not None else None)
            if not self._create_gang(job, spec,
                                     forced_concrete=granted or None):
                self._set_status(
                    job, PHASE_PENDING,
                    conditions=[_condition("Unschedulable", "NoFreeSlices",
                                           "cannot re-place lost worker")])
                return 15.0
            return 2.0
        if counts["Succeeded"] == spec.num_workers:
            self._set_status(job, PHASE_SUCCEEDED,
                             completion=True, **status_update,
                             conditions=[_condition("Succeeded", "AllWorkersDone")])
            self._record_job_span(job, PHASE_SUCCEEDED,
                                  telemetry=telemetry)
            self._clear_job_gauges(ns, name)
            self._queue_release(ns, name)
            return None
        if counts["Running"] == spec.num_workers:
            conds = ([_condition("Running", "GangRunning")]
                     if phase != PHASE_RUNNING else [])
            if telemetry and telemetry.get("stragglers"):
                # health, not failure: the SPMD gang still runs, but its
                # throughput is gated by these workers — surface them
                # (condition dedup keeps the list from growing per poll)
                conds.append(_condition(
                    "Straggling", "WorkerBehindMedian",
                    f"worker(s) {', '.join(telemetry['stragglers'])} >= "
                    f"{spec.straggler_steps} steps behind median step "
                    f"{telemetry.get('medianStep')}"))
            self._set_status(job, PHASE_RUNNING,
                             start=(phase != PHASE_RUNNING),
                             **status_update,
                             conditions=conds or None)
            return 10.0
        # partially scheduled/running: keep current phase, poll again
        self._set_status(job, phase if phase != PHASE_RESTARTING else PHASE_PENDING,
                         **status_update)
        return 2.0

    # -- scheduler-plane integration ---------------------------------------

    def _queue_release(self, ns: str, name: str) -> None:
        if self.queue is not None:
            self.queue.release(ns, name)

    def _reconcile_queued_create(self, job: o.Obj,
                                 spec: TpuJobSpec) -> Optional[float]:
        """Gang creation through the cluster queue: submit (quota
        admission), run a scheduling cycle, and create pods only on a
        placement grant — whole gangs wait, never partial pods."""
        from kubeflow_tpu.scheduler.queue import BLOCKED, request_from_spec

        ns = job["metadata"]["namespace"]
        name = job["metadata"]["name"]
        self.queue.submit(request_from_spec(
            ns, name, spec, uid=job["metadata"].get("uid", "")))
        self.queue.schedule()
        granted = self.queue.placement_for(ns, name)
        if granted is None:
            if self.queue.state_of(ns, name) == BLOCKED:
                reason = "QuotaExceeded"
                message = self.queue.blocked_reason(ns, name)
            else:
                reason = "AwaitingCapacity"
                message = (f"queued at priority {spec.priority} for "
                           f"{spec.slices} {spec.accelerator} slice(s)")
            self._set_status(job, PHASE_PENDING,
                             conditions=[_condition("Queued", reason,
                                                    message)])
            return 5.0
        if not self._create_gang(job, spec,
                                 forced_concrete=granted or None):
            # the grant went stale (an actor outside the queue claimed
            # the slices between cycles): hand it back and re-place
            self.queue.invalidate_placement(ns, name)
            self._set_status(
                job, PHASE_PENDING,
                conditions=[_condition("Unschedulable", "PlacementStale",
                                       "granted slices no longer free; "
                                       "requeued")])
            return 5.0
        resize, resize_conds = self._resize_completion(job, spec)
        self._set_status(job, PHASE_PENDING, restarts=self._restarts(job),
                         resize=resize,
                         conditions=[_condition("Created", "GangCreated")]
                         + resize_conds)
        return 1.0

    def _handle_preemption(self, job: o.Obj, spec: TpuJobSpec,
                           pods: List[o.Obj], *,
                           telemetry: Optional[Dict[str, Any]] = None
                           ) -> Optional[float]:
        """Checkpoint-preempt-requeue: persist the step clock, tear the
        gang down, mark the CR, confirm the head-of-queue re-admission.
        The checkpoint save happens exactly once per preemption — the
        queue flips the victim out of ``Preempting`` on confirm, so
        this path cannot re-enter for the same eviction."""
        ns = job["metadata"]["namespace"]
        name = job["metadata"]["name"]
        step: Optional[int] = None
        if self.checkpointer is not None:
            try:
                step = self.checkpointer.save(job)
            except Exception:  # noqa: BLE001 — a broken checkpoint sink
                # must not wedge the preemption; capacity is owed to a
                # higher priority NOW, the victim just loses more work
                log.exception("preemption checkpoint for %s/%s failed",
                              ns, name)
        if step is None:
            # fall back to THIS pass's beacon aggregation (fresher than
            # the last status write), then the persisted status copy
            tel = (telemetry if telemetry is not None
                   else job.get("status", {}).get("telemetry") or {})
            step = tel.get("lastStep")
        if pods:
            self._delete_pods(ns, pods)
        preemption = dict(job.get("status", {}).get("preemption") or {})
        by = preemption.get("by", "")
        preemption.update({"requested": False,
                           "lastCheckpointStep": step,
                           "count": int(preemption.get("count", 0)) or 1})
        self._set_status(
            job, PHASE_PENDING, preemption=preemption,
            conditions=[_condition(
                "Preempted", "RequeuedForPriority",
                f"preempted for {by or 'a higher-priority gang'}; "
                f"checkpointed at step {step}; requeued at queue head")])
        log.info("preempted %s/%s for %s (checkpoint step %s)",
                 ns, name, by, step)
        self.queue.confirm_preempted(ns, name, step)
        return 1.0

    # -- elastic resize (docs/ELASTIC.md) ----------------------------------

    def _apply_shrink_offer(self, job: o.Obj, spec: TpuJobSpec,
                            target: int) -> Optional[float]:
        """Accept the queue's shrink offer by editing ``spec.slices``
        down to ``target`` — the resize then flows through the same
        snapshot→teardown→re-gang→resume path a user edit takes. The
        condition records WHY the shape changed (nobody edited the CR)."""
        ns = job["metadata"]["namespace"]
        name = job["metadata"]["name"]
        updated = dict(job)
        updated["spec"] = {**dict(job.get("spec", {})), "slices": target}
        try:
            self.client.update(updated)
        except ApiError as e:
            if e.code != 404:
                raise
            return None
        self._set_status(
            updated, job.get("status", {}).get("phase", PHASE_PENDING),
            conditions=[_condition(
                "Resizing", "ShrinkOffered",
                f"scheduler offered shrink to {target} slice(s) in "
                f"place of preemption")])
        log.info("shrink offer accepted for %s/%s: slices -> %d",
                 ns, name, target)
        return 1.0

    def _handle_resize(self, job: o.Obj, spec: TpuJobSpec,
                       pods: List[o.Obj],
                       stale: List[o.Obj], *,
                       telemetry: Optional[Dict[str, Any]] = None
                       ) -> Optional[float]:
        """Checkpoint-reshard-resume, operator side. Two passes:

        1. **nudge** — write ``status.resize.requested`` (the workers'
           cue to barrier + snapshot, mirroring the preemption nudge)
           and hold one reconcile so a live gang can save before its
           pods die;
        2. **snapshot + teardown** — ensure a checkpoint step is known
           (``checkpointer.save``, exactly once per resize — the
           ``checkpointed`` flag survives re-entry), tear the gang
           down, and let the normal create path re-gang at the new
           shape. The re-gang completion (:meth:`_resize_completion`)
           emits the ``Resized`` condition and counts the resize.
        """
        ns = job["metadata"]["namespace"]
        name = job["metadata"]["name"]
        shape = gang_shape(spec)
        old_shape = (stale[0].get("metadata", {}).get("labels", {})
                     or {}).get(GANG_SHAPE_LABEL, "")
        resize = dict(job.get("status", {}).get("resize") or {})
        if not resize.get("requested"):
            try:
                old_workers = int(old_shape.split("x")[0]) * int(
                    old_shape.split("x")[1])
            except (ValueError, IndexError):
                old_workers = spec.num_workers
            resize = {
                # keep the queue's offer provenance (who asked, to
                # what) next to the resize it caused
                **{k: v for k, v in resize.items()
                   if k in ("offered", "by")},
                "requested": True,
                "from": old_shape,
                "to": shape,
                "direction": ("shrink"
                              if spec.num_workers < old_workers
                              else "grow"),
                "count": int(resize.get("count", 0)) + 1,
            }
            self._set_status(
                job, job.get("status", {}).get("phase", PHASE_PENDING),
                resize=resize,
                conditions=[_condition(
                    "Resizing", "ElasticResize",
                    f"resize {old_shape or '?'} -> {shape}: snapshot "
                    f"requested")])
            log.info("elastic resize for %s/%s: %s -> %s (nudged)",
                     ns, name, old_shape, shape)
            return 1.0
        step: Optional[int] = None
        if not resize.get("checkpointed"):
            if self.checkpointer is not None:
                try:
                    step = self.checkpointer.save(job)
                except Exception:  # noqa: BLE001 — a broken checkpoint
                    # sink must not wedge the resize; the gang just
                    # resumes from an older step (or step 0)
                    log.exception("resize checkpoint for %s/%s failed",
                                  ns, name)
            if step is None:
                tel = (telemetry if telemetry is not None
                       else job.get("status", {}).get("telemetry") or {})
                step = tel.get("lastStep")
            resize = {**resize, "checkpointed": True,
                      "lastCheckpointStep": step}
        self._delete_pods(ns, pods)
        self._set_status(
            job, PHASE_RESTARTING, resize=resize,
            conditions=[_condition(
                "Resizing", "ElasticResize",
                f"re-gang {resize.get('from') or '?'} -> {shape}; "
                f"checkpointed at step "
                f"{resize.get('lastCheckpointStep')}")])
        log.info("elastic resize for %s/%s: torn down for re-gang to %s "
                 "(checkpoint step %s)", ns, name, shape,
                 resize.get("lastCheckpointStep"))
        return 1.0

    def _resize_completion(self, job: o.Obj, spec: TpuJobSpec
                           ) -> tuple:
        """On gang (re-)creation: if a resize was in flight, close it —
        flip ``requested`` off, count it by direction, and emit the
        ``Resized`` condition exactly once (the flag flips exactly once
        per resize, the ``Preempted`` dedup discipline)."""
        resize = dict(job.get("status", {}).get("resize") or {})
        if not resize.get("requested"):
            return None, []
        resize["requested"] = False
        resize.pop("checkpointed", None)
        direction = resize.get("direction", "shrink")
        _job_resizes.inc(direction=direction)
        cond = _condition(
            "Resized", "ElasticResize",
            f"resized {resize.get('from') or '?'} -> "
            f"{resize.get('to') or gang_shape(spec)} ({direction}); "
            f"resuming from step {resize.get('lastCheckpointStep')}")
        return resize, [cond]

    # -- helpers -----------------------------------------------------------

    def _restarts(self, job: o.Obj) -> int:
        return int(job.get("status", {}).get("restarts", 0))

    def _job_telemetry(self, ns: str, name: str,
                       spec: TpuJobSpec) -> Optional[Dict[str, Any]]:
        """Aggregate the workers' beacon ConfigMaps into the CR-status
        telemetry shape (None when no worker has beaconed yet — a job
        that never emits telemetry keeps a telemetry-free status).
        Beacons beyond the CURRENT world size (an elastic downsize left
        them behind) are excluded from aggregation and deleted
        best-effort, or the departed workers' frozen step counters would
        drag the gang median and flag every live worker a straggler."""
        try:
            beacons = read_beacons(self.client, ns, name)
        except ApiError:
            return None
        for w in [w for w in beacons if w >= spec.num_workers]:
            beacons.pop(w)
            try:
                self.client.delete("v1", "ConfigMap", ns,
                                   beacon_configmap_name(name, w))
            except ApiError:
                pass  # cleanup is best-effort; the filter is the guard
        if not beacons:
            return None
        view = telemetry_view(beacons, spec.straggler_steps)
        _job_last_step.set(view["lastStep"], namespace=ns, job=name)
        _job_steps_per_sec.set(view["stepsPerSec"], namespace=ns, job=name)
        _job_stragglers.set(len(view["stragglers"]), namespace=ns, job=name)
        if self.queue is not None:
            # the scheduling loop PR 5 built this telemetry for: every
            # aggregation feeds the queue's throughput predictor
            self.queue.predictor.observe(
                ns, name,
                steps_per_sec=self._predictor_rate(
                    ns, name, view["stepsPerSec"]),
                last_step=view["lastStep"],
                accelerator=spec.accelerator, slices=spec.slices)
        return view

    def _predictor_rate(self, ns: str, name: str,
                        instant_rate: float) -> float:
        """The rate the throughput predictor learns from: the tsdb's
        ``kftpu_job_steps_per_sec`` series averaged over the monitoring
        window when a store is attached and the series has in-window
        points, else the instantaneous CR-status view unchanged
        (absent-never-wrong: a missing series can only fall back, never
        fabricate — and a non-positive windowed average falls back too,
        since ``observe`` discards non-positive rates)."""
        if self.tsdb is None:
            return instant_rate
        try:
            averaged = self.tsdb.avg("kftpu_job_steps_per_sec",
                                     {"namespace": ns, "job": name},
                                     window_s=self.tsdb_window_s)
        except Exception:  # noqa: BLE001 — monitoring must not fail jobs
            log.exception("tsdb stepsPerSec read failed for %s/%s",
                          ns, name)
            return instant_rate
        rates = [v for _labels, v in averaged if v > 0]
        if not rates:
            return instant_rate
        # multiple matching series (e.g. scraped from several targets)
        # agree on one number the same way the beacon view does: mean
        return sum(rates) / len(rates)

    # -- goodput ledger (docs/OBSERVABILITY.md "Goodput") ------------------

    def _fold_goodput(self, job: o.Obj, spec: TpuJobSpec,
                      pods: List[o.Obj],
                      telemetry: Optional[Dict[str, Any]]
                      ) -> Dict[str, Any]:
        """Fold this reconcile's observation into ``status.goodput``
        and export the counters. All ledger state lives in the CR, so
        a crash-restarted operator continues exactly where the status
        says — and a replayed reconcile (clock not advanced past
        ``asOf``) changes nothing, writes nothing."""
        ns = job["metadata"]["namespace"]
        name = job["metadata"]["name"]
        status = job.get("status", {}) or {}
        prev = status.get("goodput")
        # export the PERSISTED ledger only (the counters lag the CR by
        # at most one pass; the terminal branch catches the last state
        # up): exporting an unpersisted fold would let a skipped write
        # re-derive the window differently than what was counted, and
        # a monotone counter cannot take it back — the CR fractions
        # and the exported series must never disagree
        self._goodput.export(ns, name, spec.chips, prev)
        new = goodput_mod.fold(
            prev, self._goodput_signals(job, ns, name, pods, telemetry))
        if new != prev:
            # mutate the in-hand CR copy so every later _set_status in
            # this pass carries the folded ledger forward for free
            job["status"] = {**status, "goodput": new}
            # write-through ONLY on an attribution-state change or a
            # 60s staleness cap: the operator's own status write emits
            # a MODIFIED watch event that re-enqueues this job, so an
            # unconditional per-pass write would turn every quiet hold
            # loop (queued, preempted, restarting) into a hot one. A
            # skipped write loses nothing — the next fold re-derives
            # the identical merged interval from the persisted asOf
            # (the fold is a deterministic function of CR + clock)
            if self._goodput_flush_due(prev, new):
                try:
                    self.client.update_status(job)
                except ApiError as e:
                    if e.code != 404:
                        raise
        return new

    _GOODPUT_FLUSH_S = 60.0

    @staticmethod
    def _goodput_flush_due(prev: Optional[Dict[str, Any]],
                           new: Dict[str, Any]) -> bool:
        if not prev:
            return True
        p_ivs = prev.get("intervals") or []
        n_ivs = new.get("intervals") or []
        p_last = p_ivs[-1]["state"] if p_ivs else None
        n_last = n_ivs[-1]["state"] if n_ivs else None
        if p_last != n_last:
            return True
        return (float(new.get("asOf", 0.0))
                - float(prev.get("asOf", 0.0))
                >= TpuJobOperator._GOODPUT_FLUSH_S)

    def _goodput_signals(self, job: o.Obj, ns: str, name: str,
                         pods: List[o.Obj],
                         telemetry: Optional[Dict[str, Any]]
                         ) -> goodput_mod.GoodputSignals:
        """This reconcile's observation, from signals that already
        exist: CR conditions/status, the queue's state, the beacon
        aggregation, and the worker-side checkpoint-save histogram."""
        status = job.get("status", {}) or {}
        tel = (telemetry if telemetry is not None
               else (status.get("telemetry") or {}))
        resize = status.get("resize") or {}
        preemption = status.get("preemption") or {}
        restore_step: Optional[int] = None
        for raw in (resize.get("lastCheckpointStep"),
                    preemption.get("lastCheckpointStep")):
            try:
                step = int(raw)
            except (TypeError, ValueError):
                continue
            restore_step = (step if restore_step is None
                            else max(restore_step, step))
        return goodput_mod.GoodputSignals(
            now=self.clock(),
            has_pods=bool(pods),
            resize_requested=bool(resize.get("requested")),
            preemption_requested=bool(preemption.get("requested")),
            preemptions=int(preemption.get("count", 0) or 0),
            last_step=int(tel.get("lastStep", 0) or 0),
            recompiles=int(tel.get("recompiles", 0) or 0),
            stragglers=bool(tel.get("stragglers")),
            restore_step=restore_step,
            ckpt_save_seconds=self._ckpt_save_seconds(ns, name),
            compile_seconds=self._compile_seconds(ns, name),
        )

    def _ckpt_save_seconds(self, ns: str, name: str) -> float:
        """Cumulative worker snapshot seconds for one job — the
        ledger's ``checkpoint_save`` source. A deployed operator reads
        the scraped ``kftpu_checkpoint_save_seconds_sum`` through the
        tsdb (the workers run in other processes); without a store —
        or without the series — the in-process registry covers the
        all-in-one-process tier."""
        if self.tsdb is not None:
            try:
                pts = self.tsdb.latest(
                    "kftpu_checkpoint_save_seconds_sum",
                    {"namespace": ns, "job": name, "source": "worker"})
            except Exception:  # noqa: BLE001 — monitoring never fails jobs
                log.exception("tsdb checkpoint-save read failed for "
                              "%s/%s", ns, name)
                pts = []
            if pts:
                # MAX across series, never sum: a gang-synchronized
                # snapshot is observed by every worker (one scraped
                # series per target) at ~the same wall time — the
                # job's wall-clock cost is its slowest worker, and
                # summing would carve N× phantom save seconds
                return max(p.value for _labels, p in pts)
        return goodput_mod.checkpoint_save_seconds(ns, name)

    def _compile_seconds(self, ns: str, name: str) -> Optional[float]:
        """Cumulative event-sourced XLA compile seconds for one job —
        the ledger's ground-truth ``startup_compile``/``recompile``
        source. Reads the scraped ``kftpu_compile_seconds_sum``
        through the tsdb (SUM across series: each is one module ×
        shape class, disjoint wall time; a gang's workers emit
        identical label sets so cross-worker samples merge instead of
        multiplying), else the in-process xprof totals. None — no
        ledger anywhere for this job — keeps the fold on beacon
        inference: absence of evidence is not zero compile seconds."""
        if self.tsdb is not None:
            try:
                pts = self.tsdb.latest(
                    "kftpu_compile_seconds_sum",
                    {"namespace": ns, "job": name})
            except Exception:  # noqa: BLE001 — monitoring never fails jobs
                log.exception("tsdb compile-seconds read failed for "
                              "%s/%s", ns, name)
                pts = []
            if pts:
                return sum(p.value for _labels, p in pts)
        from kubeflow_tpu.obs import xprof

        return xprof.job_compile_seconds(ns, name)

    def _clear_job_gauges(self, ns: str, name: str) -> None:
        """Terminal/deleted jobs must not export their last telemetry
        forever (the _update_phase_gauge staleness rule, applied to the
        per-job label rows)."""
        for g in (_job_last_step, _job_steps_per_sec, _job_stragglers):
            g.remove(namespace=ns, job=name)

    def _record_job_span(self, job: o.Obj, phase: str, *,
                         telemetry: Optional[Dict[str, Any]] = None
                         ) -> None:
        """Terminal training-job root span, identity-derived like the
        workflow controller's: trace/span ids from (ns, name, uid), so
        the workers' per-N-step child spans (same derivation, via
        KFTPU_JOB_UID) land under it in one tree. Terminal-only: the
        reconcile loop returns early on terminal phases, so the span
        records exactly once. ``telemetry`` is THIS pass's aggregation
        (the CR copy in hand predates the final status write)."""
        md = job.get("metadata", {})
        ns = md.get("namespace", "")
        name = md.get("name", "")
        trace_id, root_id = tpujob_trace_ids(ns, name, md.get("uid", ""))
        end = self.clock()
        start = _parse_ts(job.get("status", {}).get("startTime", ""))
        if start is None or start > end:
            # startTime is stamped by make_condition's REAL wall clock;
            # under an injected fake clock (or skew) it can land after
            # ``end`` — clamp to a zero-duration span rather than
            # recording a negative one
            start = end
        status = job.get("status", {})
        if telemetry is None:
            telemetry = status.get("telemetry") or {}
        self.tracer.record(
            f"tpujob/{name}", start=start if start is not None else end,
            end=end, trace_id=trace_id, span_id=root_id,
            attrs={"namespace": ns, "phase": phase,
                   "restarts": int(status.get("restarts", 0)),
                   "lastStep": telemetry.get("lastStep", 0)},
            status="OK" if phase == PHASE_SUCCEEDED else f"ERROR: {phase}")

    def _create_gang(self, job: o.Obj, spec: TpuJobSpec,
                     forced_concrete: Optional[List[str]] = None) -> bool:
        """Create the whole gang atomically. Returns False (creating
        nothing) when a concrete slice inventory exists but has no
        feasible free window — partial gangs would deadlock the mesh.
        ``forced_concrete`` pins the gang to slices the scheduler queue
        granted instead of running first-come assignment."""
        with self._placement_lock:
            return self._create_gang_locked(job, spec, forced_concrete)

    def _create_gang_locked(self, job: o.Obj, spec: TpuJobSpec,
                            forced_concrete: Optional[List[str]] = None
                            ) -> bool:
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        placements = place_gang(
            slices=spec.slices,
            hosts_per_slice=spec.hosts_per_slice,
            accelerator=spec.accelerator,
        )
        concrete: Optional[List[str]] = None
        scheduler = GangScheduler(self.client)
        if forced_concrete is not None:
            concrete = self._verify_grant(ns, name, spec, scheduler,
                                          forced_concrete)
            if concrete is None:
                return False
        else:
            inv = scheduler.inventory(spec.accelerator)
            if inv:
                # adopt slices already claimed by this job's surviving
                # pods so recreate-absent-members keeps siblings on their
                # slice; a logical slice whose pods ALL died is fully
                # free again and assignable fresh
                claimed = self._existing_assignment(ns, name)
                missing = [k for k in range(spec.slices)
                           if k not in claimed]
                if missing:
                    fresh = scheduler.assign(
                        spec.accelerator, len(missing),
                        spec.hosts_per_slice, inventory=inv)
                    if fresh is None:
                        return False
                    claimed.update(zip(missing, fresh))
                concrete = [claimed[k] for k in range(spec.slices)]
        self._create_if_absent(build_service(job))
        if spec.gang_scheduling and self.gang_scheduling:
            pg = build_podgroup(job)
            live_pg = self.client.get_or_none(PODGROUP_API, "PodGroup", ns,
                                              name)
            if live_pg is None:
                self._create_if_absent(pg)
            elif (live_pg.get("spec", {}).get("minMember")
                  != pg["spec"]["minMember"]):
                # elastic resize: the gang barrier must match the new shape
                live_pg = dict(live_pg)
                live_pg["spec"] = {**live_pg.get("spec", {}),
                                   "minMember": pg["spec"]["minMember"]}
                self.client.update(live_pg)
        for i in range(spec.num_workers):
            chosen = (concrete[placements[i].slice_index]
                      if concrete else None)
            self._create_if_absent(build_worker_pod(job, i, placements[i],
                                                    concrete_slice=chosen))
        log.info("created gang for %s/%s: %d workers over %d slice(s)%s",
                 ns, name, spec.num_workers, spec.slices,
                 f" on {concrete}" if concrete else "")
        return True

    def _verify_grant(self, ns: str, name: str, spec: TpuJobSpec,
                      scheduler: GangScheduler,
                      granted: List[str]) -> Optional[List[str]]:
        """Map the queue's slice grant onto logical slice ordinals,
        keeping surviving pods' claims, and verify every freshly-used
        slice is still fully free (the grant can go stale if an actor
        outside the queue claimed it). None = stale, re-place."""
        inv = {s.slice_id: s
               for s in scheduler.inventory(spec.accelerator)}
        claimed = self._existing_assignment(ns, name)
        fresh = [sid for sid in granted if sid not in claimed.values()]
        for k in range(spec.slices):
            if k in claimed:
                continue
            if not fresh:
                return None
            sid = fresh.pop(0)
            info = inv.get(sid)
            if info is None or info.free_hosts != info.hosts:
                return None
            claimed[k] = sid
        return [claimed[k] for k in range(spec.slices)]

    def _existing_assignment(self, ns: str, name: str) -> Dict[int, str]:
        """logical slice ordinal -> concrete slice id already claimed by
        this job's live pods (empty when nothing is claimed)."""
        by_ordinal: Dict[int, str] = {}
        for pod in self.client.list("v1", "Pod", ns,
                                    label_selector={JOB_LABEL: name}):
            labels = pod.get("metadata", {}).get("labels", {}) or {}
            assigned = labels.get(ASSIGNED_SLICE_LABEL)
            # only live pods hold a claim — the same filter inventory's
            # busy accounting uses, or an adopted slice could simultaneously
            # be handed out as free
            phase = pod.get("status", {}).get("phase", "Pending")
            if assigned and phase in ("Pending", "Running"):
                by_ordinal[int(labels.get(SLICE_LABEL, "0"))] = assigned
        return by_ordinal

    def _delete_pods(self, ns: str, pods: List[o.Obj]) -> None:
        for pod in pods:
            try:
                self.client.delete("v1", "Pod", ns, pod["metadata"]["name"])
            except ApiError as e:
                if e.code != 404:
                    raise

    def _create_if_absent(self, obj: o.Obj) -> None:
        helpers.create_if_absent(self.client, obj)

    def _handle_failure(self, job: o.Obj, spec: TpuJobSpec,
                        pods: List[o.Obj],
                        telemetry: Optional[Dict[str, Any]] = None
                        ) -> Optional[float]:
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        restarts = self._restarts(job)
        if spec.restart_policy == "Never" or restarts >= spec.max_restarts:
            self._set_status(
                job, PHASE_FAILED, completion=True,
                conditions=[_condition(
                    "Failed", "WorkerFailed",
                    f"gang failed after {restarts} restart(s)")])
            self._record_job_span(job, PHASE_FAILED, telemetry=telemetry)
            self._clear_job_gauges(job["metadata"].get("namespace", ""),
                                   job["metadata"].get("name", ""))
            self._queue_release(ns, name)
            return None
        # SPMD all-or-nothing: tear the whole gang down and re-place it
        _restarts.inc()
        self._delete_pods(ns, pods)
        self._set_status(
            job, PHASE_RESTARTING, restarts=restarts + 1,
            conditions=[_condition("Restarting", "GangRestart",
                                   f"restart {restarts + 1}/{spec.max_restarts}")])
        log.warning("gang %s/%s failed; restart %d/%d",
                    ns, name, restarts + 1, spec.max_restarts)
        return 1.0

    def _set_status(self, job: o.Obj, phase: str, *, restarts: Optional[int] = None,
                    start: bool = False, completion: bool = False,
                    conditions: Optional[List[Dict[str, Any]]] = None,
                    workers: Optional[Dict[str, int]] = None,
                    telemetry: Optional[Dict[str, Any]] = None,
                    preemption: Optional[Dict[str, Any]] = None,
                    resize: Optional[Dict[str, Any]] = None) -> None:
        status = dict(job.get("status", {}))
        changed = status.get("phase") != phase
        status["phase"] = phase
        if restarts is not None:
            status["restarts"] = restarts
        if workers is not None:
            status["workers"] = workers
        if telemetry is not None:
            changed = changed or status.get("telemetry") != telemetry
            status["telemetry"] = telemetry
        if preemption is not None:
            changed = changed or status.get("preemption") != preemption
            status["preemption"] = preemption
        if resize is not None:
            changed = changed or status.get("resize") != resize
            status["resize"] = resize
        if start and "startTime" not in status:
            status["startTime"] = _condition("", "")["lastTransitionTime"]
        if completion and "completionTime" not in status:
            status["completionTime"] = _condition("", "")["lastTransitionTime"]
        appended = False
        if conditions:
            existing = status.setdefault("conditions", [])
            for cond in conditions:
                last = existing[-1] if existing else {}
                # dedup repeats (e.g. the 15s Unschedulable hold) or the
                # conditions list grows without bound while a job waits
                if (last.get("type") == cond["type"]
                        and last.get("reason") == cond["reason"]):
                    continue
                existing.append(cond)
                appended = True
        if changed or appended or workers is not None:
            job = dict(job)
            job["status"] = status
            try:
                self.client.update_status(job)
            except ApiError as e:
                if e.code != 404:
                    raise
        self._update_phase_gauge()

    def _update_phase_gauge(self) -> None:
        """Recompute jobs-by-phase from a list snapshot so stale labels clear."""
        try:
            jobs = self.client.list(API_VERSION, TPUJOB_KIND, self.namespace)
        except ApiError:
            return
        counts: Dict[str, int] = {p: 0 for p in (
            PHASE_PENDING, PHASE_RUNNING, PHASE_SUCCEEDED, PHASE_FAILED,
            PHASE_RESTARTING)}
        for j in jobs:
            p = j.get("status", {}).get("phase", PHASE_PENDING)
            counts[p] = counts.get(p, 0) + 1
        for p, n in counts.items():
            _jobs_by_phase.set(n, phase=p)

    # -- runtime -----------------------------------------------------------

    def build_controller(self) -> Controller:
        ctrl = Controller(
            self.client, API_VERSION, TPUJOB_KIND, self.reconcile,
            namespace=self.namespace, name="tpujob-operator",
            tracer=self.tracer,
        )

        def pod_to_job(pod: o.Obj):
            labels = pod.get("metadata", {}).get("labels", {}) or {}
            job = labels.get(JOB_LABEL)
            if job:
                return (pod["metadata"].get("namespace", ""), job)
            return None

        ctrl.watch_owned("v1", "Pod", pod_to_job)
        return ctrl


def main() -> None:
    from kubeflow_tpu.k8s.client import HttpKubeClient
    from kubeflow_tpu.utils import serve_metrics

    logging.basicConfig(level=logging.INFO)
    ns = os.environ.get("KFTPU_OPERATOR_NAMESPACE") or None
    gang = os.environ.get("KFTPU_GANG_SCHEDULING", "true") == "true"
    port = int(os.environ.get("KFTPU_MONITORING_PORT", "8443"))
    serve_metrics(port)
    operator = TpuJobOperator(HttpKubeClient(), namespace=ns, gang_scheduling=gang)
    operator.build_controller().run_forever()


if __name__ == "__main__":
    main()
