"""Application aggregator — grouped health for a deployed stack.

The reference's application package deploys the SIG-Apps Application CRD
plus a metacontroller sync that assembles one status over a label-selected
group of resources (``/root/reference/kubeflow/application/
application.libsonnet:213-345``: componentKinds + selector → assembled
Application CR). Same contract here, as a native reconcile loop:

- an ``Application`` CR declares a label ``selector`` and the
  ``componentKinds`` it owns (every manifest object carries
  ``app.kubernetes.io/part-of`` via
  :func:`kubeflow_tpu.manifests.registry.render_all`);
- the controller lists matching resources per kind and derives each
  component's readiness (Deployments/StatefulSets: ready==desired
  replicas; Pods: phase; anything else: exists);
- status aggregates: total/ready counts, per-component table, and a
  single Ready/Progressing condition — the dashboard's one-look answer
  to "is the platform healthy".
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import KubeClient, register_plural
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.operators.controller import (
    Controller,
    make_condition,
    set_phase_status,
)

log = logging.getLogger(__name__)

API_VERSION = f"{GROUP}/{VERSION}"
APPLICATION_KIND = "Application"
APPLICATION_PLURAL = "applications"
register_plural(APPLICATION_KIND, APPLICATION_PLURAL)

PHASE_READY = "Ready"
PHASE_PROGRESSING = "Progressing"

# kind -> apiVersion for the component kinds the aggregator understands;
# mirrors the reference's componentKinds entries (application.libsonnet
# emits {group, kind} pairs for exactly this set plus its CRDs)
KIND_API: Dict[str, str] = {
    "Deployment": "apps/v1",
    "StatefulSet": "apps/v1",
    "Service": "v1",
    "Pod": "v1",
    "ConfigMap": "v1",
    "Secret": "v1",
    "ServiceAccount": "v1",
    "PersistentVolumeClaim": "v1",
}


def application_crd() -> o.Obj:
    return o.crd(
        APPLICATION_PLURAL, GROUP, APPLICATION_KIND,
        versions=(VERSION,),
        short_names=("app",),
        printer_columns=(
            {"name": "Phase", "type": "string", "jsonPath": ".status.phase"},
            {"name": "Ready", "type": "string",
             "jsonPath": ".status.ready"},
        ),
    )


def application(name: str, ns: str, *,
                selector: Dict[str, str],
                component_kinds: Optional[List[str]] = None,
                descriptor: Optional[Dict[str, Any]] = None) -> o.Obj:
    """Build an Application CR (the app.k8s.io shape, framework group)."""
    kinds = component_kinds or ["Deployment", "StatefulSet", "Service"]
    unknown = [k for k in kinds if k not in KIND_API]
    if unknown:
        raise ValueError(f"unsupported componentKinds {unknown}; "
                         f"known: {sorted(KIND_API)}")
    return {
        "apiVersion": API_VERSION,
        "kind": APPLICATION_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "selector": {"matchLabels": dict(selector)},
            "componentKinds": list(kinds),
            "descriptor": dict(descriptor or {}),
        },
    }


def _readiness(obj: o.Obj) -> Tuple[bool, str]:
    """(ready, human detail) for one component resource."""
    kind = obj.get("kind", "")
    status = obj.get("status", {}) or {}
    if kind in ("Deployment", "StatefulSet"):
        want = int(obj.get("spec", {}).get("replicas", 1))
        have = int(status.get("readyReplicas", 0))
        return have >= want, f"{have}/{want} replicas"
    if kind == "Pod":
        phase = status.get("phase", "Pending")
        return phase in ("Running", "Succeeded"), phase
    # config-shaped objects are ready by existing
    return True, "exists"


class ApplicationController:
    """Reconciles Application CRs into an aggregated component status."""

    def __init__(self, client: KubeClient,
                 namespace: Optional[str] = None) -> None:
        self.client = client
        self.namespace = namespace

    def reconcile(self, ns: str, name: str) -> Optional[float]:
        app = self.client.get_or_none(API_VERSION, APPLICATION_KIND, ns, name)
        if app is None:
            return None
        spec = app.get("spec", {})
        selector = (spec.get("selector", {}) or {}).get("matchLabels", {})
        kinds = [k for k in spec.get("componentKinds", []) if k in KIND_API]

        components: List[Dict[str, Any]] = []
        ready_n = 0
        for kind in kinds:
            for obj in self.client.list(KIND_API[kind], kind, ns,
                                        label_selector=selector or None):
                ready, detail = _readiness(obj)
                ready_n += int(ready)
                components.append({
                    "kind": kind,
                    "name": obj["metadata"]["name"],
                    "ready": ready,
                    "detail": detail,
                })

        total = len(components)
        phase = PHASE_READY if ready_n == total else PHASE_PROGRESSING
        cond = (make_condition("Ready", "AllComponentsReady")
                if phase == PHASE_READY else
                make_condition("Progressing", "ComponentsNotReady",
                               f"{total - ready_n} of {total} not ready"))
        set_phase_status(
            self.client, app, phase,
            ready=f"{ready_n}/{total}",
            components=components,
            conditions=[cond])
        # components change as pods roll; keep the status fresh
        return 15.0

    def controller(self) -> Controller:
        return Controller(self.client, API_VERSION, APPLICATION_KIND,
                          self.reconcile, namespace=self.namespace,
                          name="application-controller")


def main() -> None:  # pragma: no cover - container entrypoint
    import os

    from kubeflow_tpu.k8s.client import HttpKubeClient

    client = HttpKubeClient.in_cluster()
    ns = os.environ.get("KFTPU_APPLICATION_NAMESPACE") or None
    ApplicationController(client, namespace=ns).controller().run_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
