"""DataPrepJob operator — distributed batch data preparation.

The reference deploys the spark-operator for this role: a SparkApplication
CRD whose driver coordinates executor pods over input partitions
(``/root/reference/kubeflow/spark/all.libsonnet``, operator Deployment +
CRD + RBAC). A TPU platform has no JVM cluster to host; the shape that
survives is *partitioned map + single reduce over shard files*:

- a job declares ``numShards`` input partitions and ``workers`` mapper
  pods; each mapper receives a contiguous shard range through the
  ``KFTPU_PREP_*`` env contract (:mod:`kubeflow_tpu.data.prep` is the
  in-container side, the executor role);
- mappers are independent (no gang): a failed mapper is retried alone up
  to ``maxRetries`` — unlike :class:`~kubeflow_tpu.operators.tpujob.
  TpuJobOperator`, whose SPMD semantics force whole-gang restarts;
- when every mapper succeeds an optional ``reduce`` pod runs once over
  the combined output (the Spark driver's collect stage);
- status mirrors SparkApplication ergonomics: phase + per-state worker
  counts + per-worker retry counts.

Shard files are the framework's native record format
(:func:`kubeflow_tpu.data.loader.write_shards`), so prepared data feeds
the training loader with no conversion step.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubeflow_tpu.k8s import helpers
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.k8s.client import KubeClient, register_plural
from kubeflow_tpu.manifests.components.tpujob_operator import GROUP, VERSION
from kubeflow_tpu.operators.controller import (
    Controller,
    make_condition as _condition,
    set_phase_status,
)
from kubeflow_tpu.utils import DEFAULT_REGISTRY

log = logging.getLogger(__name__)

API_VERSION = f"{GROUP}/{VERSION}"
DATAPREP_KIND = "DataPrepJob"
DATAPREP_PLURAL = "dataprepjobs"
register_plural(DATAPREP_KIND, DATAPREP_PLURAL)

JOB_LABEL = "kubeflow-tpu.org/dataprep-name"
ROLE_LABEL = "kubeflow-tpu.org/dataprep-role"
WORKER_LABEL = "kubeflow-tpu.org/dataprep-worker"
ATTEMPT_LABEL = "kubeflow-tpu.org/dataprep-attempt"
# fingerprint of the assignment inputs each pod's shard range and env
# were computed from (workers × numShards); a live pod whose fingerprint
# disagrees with the spec marks a mid-run resize — shard coverage is a
# pure function of (id, workers, shards), so the whole map stage
# re-fans-out at the new shape (shard-level idempotence makes this safe)
ASSIGNMENT_LABEL = "kubeflow-tpu.org/dataprep-assignment"

PHASE_PENDING = "Pending"
PHASE_MAPPING = "Mapping"
PHASE_REDUCING = "Reducing"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"

ENV_WORKER_ID = "KFTPU_PREP_WORKER_ID"
ENV_NUM_WORKERS = "KFTPU_PREP_NUM_WORKERS"
ENV_NUM_SHARDS = "KFTPU_PREP_NUM_SHARDS"
ENV_INPUT = "KFTPU_PREP_INPUT"
ENV_OUTPUT = "KFTPU_PREP_OUTPUT"

_retries = DEFAULT_REGISTRY.counter(
    "kftpu_dataprep_worker_retries_total", "dataprep mapper retries")


@dataclass
class DataPrepSpec:
    """Typed view of a DataPrepJob CR's spec."""

    image: str
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    input: str = ""
    output: str = ""
    num_shards: int = 1
    workers: int = 1
    max_retries: int = 3
    # optional reduce stage: {"command": [...], "args": [...]}; image
    # defaults to the mapper image
    reduce: Optional[Dict[str, Any]] = None
    volumes: List[Dict[str, Any]] = field(default_factory=list)
    volume_mounts: List[Dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_dict(cls, spec: Dict[str, Any]) -> "DataPrepSpec":
        out = cls(
            image=spec.get("image", ""),
            command=list(spec.get("command", []) or []),
            args=list(spec.get("args", []) or []),
            env=dict(spec.get("env", {}) or {}),
            input=spec.get("input", ""),
            output=spec.get("output", ""),
            num_shards=int(spec.get("numShards", 1)),
            workers=int(spec.get("workers", 1)),
            max_retries=int(spec.get("maxRetries", 3)),
            reduce=spec.get("reduce"),
            volumes=list(spec.get("volumes", []) or []),
            volume_mounts=list(spec.get("volumeMounts", []) or []),
        )
        out.validate()
        return out

    def validate(self) -> None:
        if not self.image:
            raise ValueError("spec.image is required")
        if self.num_shards < 1:
            raise ValueError("numShards must be >= 1")
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        if self.workers > self.num_shards:
            raise ValueError(
                f"workers ({self.workers}) > numShards ({self.num_shards}): "
                "a mapper with zero shards is a wasted pod")
        if self.reduce is not None and not isinstance(self.reduce, dict):
            raise ValueError("reduce must be a mapping with command/args")


def dataprep_crd() -> o.Obj:
    return o.crd(
        DATAPREP_PLURAL, GROUP, DATAPREP_KIND,
        versions=(VERSION,),
        short_names=("dpj",),
        printer_columns=(
            {"name": "Phase", "type": "string", "jsonPath": ".status.phase"},
            {"name": "Workers", "type": "string",
             "jsonPath": ".status.workers.Succeeded"},
        ),
    )


def dataprep_job(name: str, ns: str, spec: Dict[str, Any]) -> o.Obj:
    DataPrepSpec.from_dict(spec)  # validate early, at submit time
    return {
        "apiVersion": API_VERSION,
        "kind": DATAPREP_KIND,
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


def _worker_name(job: str, index: int, attempt: int) -> str:
    return f"{job}-map-{index}-r{attempt}"


def _assignment(spec: DataPrepSpec) -> str:
    return f"{spec.workers}x{spec.num_shards}"




class DataPrepOperator:
    """Reconciles DataPrepJob CRs into mapper pods + an optional reduce pod."""

    def __init__(self, client: KubeClient, namespace: Optional[str] = None) -> None:
        self.client = client
        self.namespace = namespace

    # -- reconcile ---------------------------------------------------------

    def reconcile(self, ns: str, name: str) -> Optional[float]:
        job = self.client.get_or_none(API_VERSION, DATAPREP_KIND, ns, name)
        if job is None:
            return None
        phase = job.get("status", {}).get("phase", PHASE_PENDING)
        if phase in (PHASE_SUCCEEDED, PHASE_FAILED):
            return None

        pods = [p for p in self.client.list(
            "v1", "Pod", ns, label_selector={JOB_LABEL: name})
            if not p.get("metadata", {}).get("deletionTimestamp")]
        mappers = [p for p in pods
                   if p["metadata"]["labels"].get(ROLE_LABEL) == "map"]
        reducers = [p for p in pods
                    if p["metadata"]["labels"].get(ROLE_LABEL) == "reduce"]

        try:
            spec = DataPrepSpec.from_dict(job["spec"])
        except ValueError as e:
            # a spec edited into invalidity mid-run must also tear down
            # the live pods — Failed is terminal, nobody reconciles after
            self._teardown(ns, pods)
            self._set_status(job, PHASE_FAILED,
                             conditions=[_condition("Failed", "InvalidSpec", str(e))])
            return None

        retries: Dict[str, int] = dict(
            job.get("status", {}).get("workerRetries", {}))

        # mid-run resize: any live mapper built for a different
        # workers×shards shape has a stale shard assignment — drop the
        # whole map stage (and any reducer consuming pre-resize output)
        # and re-fan-out
        stale = [p for p in mappers
                 if p["metadata"]["labels"].get(ASSIGNMENT_LABEL)
                 != _assignment(spec)]
        if stale:
            # delete terminal pods too: a Succeeded mapper's stale
            # ASSIGNMENT_LABEL would re-trigger this branch forever
            self._teardown(ns, pods, include_terminal=True)
            self._set_status(
                job, PHASE_PENDING, workerRetries={},
                conditions=[_condition("Resizing", "WorkerCountChanged",
                                       f"re-map with {spec.workers} workers")])
            return 1.0

        # index mappers by worker id, newest attempt wins
        by_worker: Dict[int, o.Obj] = {}
        for p in mappers:
            wid = int(p["metadata"]["labels"][WORKER_LABEL])
            cur = by_worker.get(wid)
            if cur is None or (int(p["metadata"]["labels"][ATTEMPT_LABEL])
                               > int(cur["metadata"]["labels"][ATTEMPT_LABEL])):
                by_worker[wid] = p

        # two passes: decide first, act second — creating retry pods in
        # the same sweep that discovers an exhausted sibling would orphan
        # them when the job then goes terminal
        counts = {"Pending": 0, "Running": 0, "Succeeded": 0, "Failed": 0}
        to_create: List[int] = []      # worker ids needing a (re)created pod
        to_replace: List[o.Obj] = []   # failed attempts superseded by retry
        for wid in range(spec.workers):
            pod = by_worker.get(wid)
            if pod is None:
                to_create.append(wid)
                counts["Pending"] += 1
                continue
            pphase = pod.get("status", {}).get("phase", "Pending")
            if pphase == "Failed":
                if retries.get(str(wid), 0) >= spec.max_retries:
                    counts["Failed"] += 1
                    continue
                # retry this mapper alone — shard assignment is a pure
                # function of (worker id, workers, shards), so the new
                # attempt reprocesses exactly its own range
                retries[str(wid)] = retries.get(str(wid), 0) + 1
                to_replace.append(pod)
                to_create.append(wid)
                counts["Pending"] += 1
                continue
            counts[pphase] = counts.get(pphase, 0) + 1

        status: Dict[str, Any] = {"workers": counts, "workerRetries": retries}

        if counts["Failed"] > 0:
            # kill still-running siblings: the job is dead, don't leave
            # mappers burning cluster resources (the Spark driver likewise
            # tears down executors on failure)
            self._teardown(ns, pods)
            self._set_status(job, PHASE_FAILED, **status, conditions=[
                _condition("Failed", "MapperRetriesExhausted",
                           f"{counts['Failed']} mapper(s) exceeded "
                           f"maxRetries={spec.max_retries}")])
            return None

        for pod in to_replace:
            _retries.inc()
            helpers.delete_ignore_missing(self.client, "v1", "Pod", ns,
                                          pod["metadata"]["name"])
        for wid in to_create:
            self.client.create(self._mapper(job, spec, wid,
                                            retries.get(str(wid), 0)))

        if counts["Succeeded"] < spec.workers:
            self._set_status(
                job, PHASE_MAPPING, **status,
                conditions=[_condition("Mapping", "MappersRunning")])
            return 2.0

        # all mappers done
        if spec.reduce is None:
            self._set_status(job, PHASE_SUCCEEDED, **status,
                             conditions=[_condition("Succeeded", "AllMappersDone")])
            return None

        if not reducers:
            self.client.create(self._reducer(job, spec))
            self._set_status(job, PHASE_REDUCING, **status,
                             conditions=[_condition("Reducing", "ReduceStarted")])
            return 2.0
        rphase = reducers[0].get("status", {}).get("phase", "Pending")
        if rphase == "Succeeded":
            self._set_status(job, PHASE_SUCCEEDED, **status,
                             conditions=[_condition("Succeeded", "ReduceDone")])
            return None
        if rphase == "Failed":
            self._set_status(job, PHASE_FAILED, **status,
                             conditions=[_condition("Failed", "ReduceFailed")])
            return None
        self._set_status(job, PHASE_REDUCING, **status)
        return 2.0

    def _teardown(self, ns: str, pods: List[o.Obj], *,
                  include_terminal: bool = False) -> None:
        """Delete this job's pods (non-terminal only, unless asked)."""
        for p in pods:
            if (include_terminal
                    or p.get("status", {}).get("phase") not in ("Succeeded",
                                                                "Failed")):
                helpers.delete_ignore_missing(
                    self.client, "v1", "Pod", ns, p["metadata"]["name"])

    # -- pod builders ------------------------------------------------------

    def _common_env(self, spec: DataPrepSpec) -> Dict[str, str]:
        env = dict(spec.env)
        env[ENV_NUM_WORKERS] = str(spec.workers)
        env[ENV_NUM_SHARDS] = str(spec.num_shards)
        if spec.input:
            env[ENV_INPUT] = spec.input
        if spec.output:
            env[ENV_OUTPUT] = spec.output
        return env

    def _mapper(self, job: o.Obj, spec: DataPrepSpec, wid: int,
                attempt: int) -> o.Obj:
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        env = self._common_env(spec)
        env[ENV_WORKER_ID] = str(wid)
        ctr = o.container(
            "mapper", spec.image,
            command=spec.command or None, args=spec.args or None, env=env,
            volume_mounts=spec.volume_mounts or None,
        )
        pspec = o.pod_spec([ctr], restart_policy="Never",
                           volumes=spec.volumes or None)
        pod = o.pod(_worker_name(name, wid, attempt), ns, pspec,
                    labels={JOB_LABEL: name, ROLE_LABEL: "map",
                            WORKER_LABEL: str(wid),
                            ATTEMPT_LABEL: str(attempt),
                            ASSIGNMENT_LABEL: _assignment(spec)})
        return o.set_owner(pod, job)

    def _reducer(self, job: o.Obj, spec: DataPrepSpec) -> o.Obj:
        name = job["metadata"]["name"]
        ns = job["metadata"]["namespace"]
        red = spec.reduce or {}
        ctr = o.container(
            "reducer", red.get("image", spec.image),
            command=red.get("command") or None,
            args=red.get("args") or None,
            env=self._common_env(spec),
            volume_mounts=spec.volume_mounts or None,
        )
        pspec = o.pod_spec([ctr], restart_policy="Never",
                           volumes=spec.volumes or None)
        pod = o.pod(f"{name}-reduce", ns, pspec,
                    labels={JOB_LABEL: name, ROLE_LABEL: "reduce"})
        return o.set_owner(pod, job)

    # -- status ------------------------------------------------------------

    def _set_status(self, job: o.Obj, phase: str, *,
                    conditions: Optional[List[Dict[str, Any]]] = None,
                    **fields: Any) -> None:
        set_phase_status(self.client, job, phase, conditions=conditions,
                         **fields)

    # -- controller wiring -------------------------------------------------

    def controller(self) -> Controller:
        ctrl = Controller(self.client, API_VERSION, DATAPREP_KIND,
                          self.reconcile, namespace=self.namespace,
                          name="dataprep-operator")
        ctrl.watch_owned("v1", "Pod", _pod_key)
        return ctrl


def _pod_key(pod: o.Obj):
    name = (pod.get("metadata", {}).get("labels", {}) or {}).get(JOB_LABEL)
    if not name:
        return None
    return (pod["metadata"].get("namespace", ""), name)


def main() -> None:  # pragma: no cover - container entrypoint
    import os

    from kubeflow_tpu.k8s.client import HttpKubeClient

    client = HttpKubeClient.in_cluster()
    ns = os.environ.get("KFTPU_DATAPREP_NAMESPACE") or None
    DataPrepOperator(client, namespace=ns).controller().run_forever()


if __name__ == "__main__":  # pragma: no cover
    main()
