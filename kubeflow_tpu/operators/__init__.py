"""In-cluster controllers: the TpuJob operator and companions."""

from kubeflow_tpu.operators.controller import Controller, WorkQueue  # noqa: F401
from kubeflow_tpu.operators.application import (  # noqa: F401
    ApplicationController,
    application,
)
from kubeflow_tpu.operators.dataprep import (  # noqa: F401
    DataPrepOperator,
    DataPrepSpec,
    dataprep_job,
)
from kubeflow_tpu.operators.tpujob import (  # noqa: F401
    TpuJobOperator,
    TpuJobSpec,
    tpujob,
)
