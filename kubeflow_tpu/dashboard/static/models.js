// Models page: list registered models, expand into versions with stage
// promotion. Data: the model-registry service behind the edge route
// /registry/ (kubeflow_tpu/serving/registry.py).

"use strict";
// helpers ($, showError, api, esc) come from common.js

async function apiPost(path, body) {
  const resp = await fetch(path, {
    method: "POST",
    credentials: "same-origin",
    headers: { "Content-Type": "application/json" },
    body: JSON.stringify(body),
  });
  if (resp.status === 401) {
    window.location.href = "/login.html?next=" +
      encodeURIComponent(window.location.pathname);
    throw new Error("unauthenticated");
  }
  if (!resp.ok) throw new Error(path + " → HTTP " + resp.status);
  return resp.json();
}

function stageChip(stage) {
  const s = esc(stage || "none");
  return '<span class="stage stage-' + s + '">' + s + "</span>";
}

function fmtMetrics(metrics) {
  const keys = Object.keys(metrics || {});
  if (!keys.length) return "—";
  return keys.sort().map((k) =>
    esc(k) + "=" + esc(Number(metrics[k]).toPrecision(4))).join(", ");
}

function fmtLineage(lineage) {
  const keys = Object.keys(lineage || {});
  if (!keys.length) return "—";
  return keys.sort().map((k) => esc(k) + ": " + esc(lineage[k])).join("; ");
}

// Deep links for lineage keys the platform owns pages for: the chain a
// reviewer walks "which run / study / job produced this artifact".
const LINEAGE_LINKS = {
  run: (v) => "/runs.html#" + encodeURIComponent(v),
  workflow: (v) => "/runs.html#" + encodeURIComponent(v),
  study: (v) => "/studies.html#" + encodeURIComponent(v),
  trial: (v) => "/studies.html#" + encodeURIComponent(v),
  tpujob: (v) => "/tpujobs.html#" + encodeURIComponent(v),
  job: (v) => "/tpujobs.html#" + encodeURIComponent(v),
};
// provenance reads source → process → artifact
const LINEAGE_ORDER = ["dataset", "commit", "tpujob", "job", "study",
                      "trial", "workflow", "run"];

function drawLineage(name, lineage) {
  const keys = Object.keys(lineage || {});
  const panel = $("lineage-panel");
  if (!keys.length) { panel.style.display = "none"; return; }
  panel.style.display = "";
  keys.sort((a, b) => {
    const ia = LINEAGE_ORDER.indexOf(a), ib = LINEAGE_ORDER.indexOf(b);
    return (ia < 0 ? 99 : ia) - (ib < 0 ? 99 : ib) || (a < b ? -1 : 1);
  });
  const chips = keys.map((k) => {
    const v = String(lineage[k]);
    const body = '<span class="lineage-key">' + esc(k) + "</span>" +
                 '<span class="lineage-val">' + esc(v) + "</span>";
    return LINEAGE_LINKS[k]
      ? '<a class="lineage-node" href="' + LINEAGE_LINKS[k](v) + '">' +
        body + "</a>"
      : '<span class="lineage-node">' + body + "</span>";
  });
  chips.push('<span class="lineage-node lineage-self">' +
             '<span class="lineage-key">model</span>' +
             '<span class="lineage-val">' + esc(name) + "</span></span>");
  $("lineage-chain").innerHTML =
    chips.join('<span class="lineage-arrow">→</span>');
}

async function showModel(name) {
  const data = await api("/registry/api/registry/models/" +
                         encodeURIComponent(name) + "/versions");
  $("detail-panel").style.display = "";
  $("detail-title").textContent = name;
  const latest = data.versions[data.versions.length - 1];
  drawLineage(name, latest ? latest.lineage : null);
  const rows = data.versions.map((v) => {
    const canPromote = v.stage !== "production";
    return "<tr><td>" + esc(v.version) + "</td>" +
      "<td>" + esc(v.kind || "—") + "</td>" +
      "<td>" + stageChip(v.stage) + "</td>" +
      "<td>" + fmtMetrics(v.metrics) + "</td>" +
      "<td>" + fmtLineage(v.lineage) + "</td>" +
      "<td>" + esc(v.registered_at || "") + "</td>" +
      "<td>" + (canPromote
        ? '<button class="promote" data-model="' + escAttr(name) +
          '" data-version="' + escAttr(v.version) + '">promote</button>'
        : "") + "</td></tr>";
  });
  $("versions").innerHTML = rows.join("") ||
    '<tr><td colspan="7">no versions</td></tr>';
  for (const btn of document.querySelectorAll("button.promote")) {
    btn.onclick = async () => {
      try {
        await apiPost("/registry/api/registry/models/" +
          encodeURIComponent(btn.dataset.model) + "/versions/" +
          encodeURIComponent(btn.dataset.version) + ":transition",
          { stage: "production" });
        await refresh();
        await showModel(btn.dataset.model);
      } catch (e) {
        showError("promote failed: " + e.message);
      }
    };
  }
}

async function refresh() {
  const data = await api("/registry/api/registry/models");
  const rows = data.models.map((m) =>
    '<tr><td><a href="#" class="model-link" data-name="' + escAttr(m.name) +
    '">' + esc(m.name) + "</a></td>" +
    "<td>" + esc(m.versions) + "</td>" +
    "<td>" + esc(m.latest == null ? "—" : m.latest) + "</td>" +
    "<td>" + (m.production == null ? "—" : stageChip("production") +
              " v" + esc(m.production)) + "</td></tr>");
  $("models").innerHTML = rows.join("") ||
    '<tr><td colspan="4">no models registered yet</td></tr>';
  for (const link of document.querySelectorAll("a.model-link")) {
    link.onclick = (ev) => {
      ev.preventDefault();
      showModel(link.dataset.name).catch((e) => showError(e.message));
    };
  }
}

(async () => {
  try {
    const env = await api("/api/env-info");
    $("user-chip").textContent = env.user;
    await refresh();
  } catch (e) {
    if (e.message !== "unauthenticated") showError(e.message);
  }
})();
