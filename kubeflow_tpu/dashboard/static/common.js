// Shared helpers for the dashboard pages (index, studies, runs): element
// lookup, error banner, authenticated fetch with the 401 → login
// redirect, and HTML escaping. Loaded before each page's script.

"use strict";

const $ = (id) => document.getElementById(id);

function showError(msg) {
  const el = $("error");
  el.textContent = msg;
  el.style.display = "block";
}

async function api(path) {
  const resp = await fetch(path, { credentials: "same-origin" });
  if (resp.status === 401) {
    // gatekeeper cookie missing/expired → login page
    window.location.href = "/login.html?next=" +
      encodeURIComponent(window.location.pathname);
    throw new Error("unauthenticated");
  }
  if (!resp.ok) throw new Error(path + " → HTTP " + resp.status);
  return resp.json();
}

function esc(s) {
  const d = document.createElement("div");
  d.textContent = String(s == null ? "" : s);
  return d.innerHTML;
}

// esc() covers text nodes only (innerHTML leaves quotes alone); anything
// interpolated into an HTML *attribute* value must go through this or a
// quoted name like x" onmouseover="... becomes a live handler
function escAttr(s) {
  return esc(s).replace(/"/g, "&quot;").replace(/'/g, "&#39;");
}

// Deep-link plumbing shared by the list pages: "#<name>" opens a detail
// in the current namespace, "#<ns>/<name>" switches namespace first
// (model-lineage chips link cross-namespace). One implementation so the
// three pages can't drift.
function wireHashOpen(sel, loadFn, openFn) {
  const openFromHash = async () => {
    const h = decodeURIComponent(location.hash.slice(1));
    if (!h) return;
    let ns = sel.value;
    let name = h;
    const i = h.indexOf("/");
    if (i > 0) {
      const wantNs = h.slice(0, i);
      if (![...sel.options].some((o) => o.value === wantNs)) {
        // never fall through to a SAME-NAMED object in another
        // namespace — that would show wrong data without a hint
        showError("namespace " + wantNs + " is not accessible");
        return;
      }
      name = h.slice(i + 1);
      if (sel.value !== wantNs) {
        sel.value = wantNs;
        await loadFn(wantNs);
      }
      ns = wantNs;
    }
    await openFn(ns, name);
  };
  openFromHash().catch((err) => showError(err.message));
  window.addEventListener("hashchange", () =>
    openFromHash().catch((err) => showError(err.message)));
}
