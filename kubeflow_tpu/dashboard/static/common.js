// Shared helpers for the dashboard pages (index, studies, runs): element
// lookup, error banner, authenticated fetch with the 401 → login
// redirect, and HTML escaping. Loaded before each page's script.

"use strict";

const $ = (id) => document.getElementById(id);

function showError(msg) {
  const el = $("error");
  el.textContent = msg;
  el.style.display = "block";
}

async function api(path) {
  const resp = await fetch(path, { credentials: "same-origin" });
  if (resp.status === 401) {
    // gatekeeper cookie missing/expired → login page
    window.location.href = "/login.html?next=" +
      encodeURIComponent(window.location.pathname);
    throw new Error("unauthenticated");
  }
  if (!resp.ok) throw new Error(path + " → HTTP " + resp.status);
  return resp.json();
}

function esc(s) {
  const d = document.createElement("div");
  d.textContent = String(s == null ? "" : s);
  return d.innerHTML;
}

// esc() covers text nodes only (innerHTML leaves quotes alone); anything
// interpolated into an HTML *attribute* value must go through this or a
// quoted name like x" onmouseover="... becomes a live handler
function escAttr(s) {
  return esc(s).replace(/"/g, "&quot;").replace(/'/g, "&#39;");
}
