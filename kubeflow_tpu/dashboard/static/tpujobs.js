// TPU Jobs page over /api/tpujobs/<ns> (list + worker-gang detail).

"use strict";
// helpers ($, showError, api, esc) come from common.js

async function openJob(ns, name) {
  const d = await api(`/api/tpujobs/${encodeURIComponent(ns)}/` +
                      encodeURIComponent(name));
  $("detail-panel").style.display = "";
  $("detail-title").textContent =
    `${name} — ${d.status.phase || "Pending"}` +
    (d.status.restarts ? ` (${d.status.restarts} restarts)` : "");
  $("workers").innerHTML = d.workers.length
    ? d.workers.map((w) => `
      <tr>
        <td>${esc(w.name)}</td>
        <td>${esc(w.slice)}</td>
        <td>${esc(w.host)}</td>
        <td><span class="pill ${esc(w.phase)}">${esc(w.phase)}</span></td>
      </tr>`).join("")
    : "<tr><td colspan=4>no worker pods</td></tr>";
  $("detail-panel").scrollIntoView({ behavior: "smooth" });
}

async function loadJobs(ns) {
  const jobs = await api("/api/tpujobs/" + encodeURIComponent(ns));
  $("jobs").innerHTML = jobs.length
    ? jobs.map((j) => `
      <tr>
        <td><a href="#" data-job="${esc(j.name)}">${esc(j.name)}</a></td>
        <td><span class="pill ${esc(j.phase)}">${esc(j.phase)}</span></td>
        <td>${esc(j.slices)}×${esc(j.hostsPerSlice)}</td>
        <td>${esc(j.accelerator)}</td>
        <td>${esc(j.workersRunning)}/${esc(j.workersTotal)}</td>
        <td>${esc(j.restarts)}</td>
        <td>${esc(j.startTime || "—")}</td>
      </tr>`).join("")
    : "<tr><td colspan=7>no TPU jobs in this namespace</td></tr>";
  for (const a of document.querySelectorAll("a[data-job]")) {
    a.addEventListener("click", (e) => {
      e.preventDefault();
      openJob(ns, a.dataset.job).catch((err) => showError(err.message));
    });
  }
}

async function main() {
  try {
    const env = await api("/api/env-info");
    $("user-chip").textContent = env.user;
    const sel = $("ns-select");
    sel.innerHTML = env.namespaces
      .map((n) => `<option value="${esc(n)}">${esc(n)}</option>`).join("");
    const saved = localStorage.getItem("kftpu-ns");
    if (saved && env.namespaces.includes(saved)) sel.value = saved;
    await loadJobs(sel.value);
    // deep links: /tpujobs.html#<job> or #<ns>/<job>
    wireHashOpen(sel, loadJobs, openJob);
    sel.addEventListener("change", () => {
      localStorage.setItem("kftpu-ns", sel.value);
      $("detail-panel").style.display = "none";
      loadJobs(sel.value).catch((err) => showError(err.message));
    });
    setInterval(() => loadJobs(sel.value).catch(() => {}), 10000);
  } catch (err) {
    if (err.message !== "unauthenticated") showError(err.message);
  }
}

main();
