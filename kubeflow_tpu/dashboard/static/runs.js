// Workflow runs page over /api/runs/<ns> (live CRs + RunArchive merge).

"use strict";
// helpers ($, showError, api, esc) come from common.js

async function openRun(ns, name) {
  const d = await api(`/api/runs/${encodeURIComponent(ns)}/` +
                      encodeURIComponent(name));
  $("detail-panel").style.display = "";
  $("detail-title").textContent =
    `${name} — ${d.status.phase || "Pending"}` +
    (d.live ? "" : " (archived)");
  const nodes = Object.entries(d.status.nodes || {});
  $("nodes").innerHTML = nodes.length
    ? nodes.map(([step, n]) => `
      <tr>
        <td>${esc(step)}</td>
        <td><span class="pill ${esc(n.phase)}">${esc(n.phase)}</span></td>
        <td>${esc(n.startedAt || "—")}</td>
        <td>${esc(n.finishedAt || "—")}</td>
        <td>${esc(n.message || "")}</td>
      </tr>`).join("")
    : "<tr><td colspan=5>no steps recorded</td></tr>";
  $("detail-panel").scrollIntoView({ behavior: "smooth" });
}

async function loadRuns(ns) {
  const runs = await api("/api/runs/" + encodeURIComponent(ns));
  $("runs").innerHTML = runs.length
    ? runs.map((r) => `
      <tr>
        <td><a href="#" data-run="${esc(r.name)}">${esc(r.name)}</a></td>
        <td><span class="pill ${esc(r.phase)}">${esc(r.phase)}</span></td>
        <td>${esc(r.succeededSteps)}/${esc(r.steps)}</td>
        <td>${esc(r.startedAt || "—")}</td>
        <td>${esc(r.finishedAt || "—")}</td>
        <td>${r.live ? "live" : "archive"}</td>
      </tr>`).join("")
    : "<tr><td colspan=6>no runs in this namespace</td></tr>";
  for (const a of document.querySelectorAll("a[data-run]")) {
    a.addEventListener("click", (e) => {
      e.preventDefault();
      openRun(ns, a.dataset.run).catch((err) => showError(err.message));
    });
  }
}

async function main() {
  try {
    const env = await api("/api/env-info");
    $("user-chip").textContent = env.user;
    const sel = $("ns-select");
    sel.innerHTML = env.namespaces
      .map((n) => `<option value="${esc(n)}">${esc(n)}</option>`).join("");
    const saved = localStorage.getItem("kftpu-ns");
    if (saved && env.namespaces.includes(saved)) sel.value = saved;
    await loadRuns(sel.value);
    sel.addEventListener("change", () => {
      localStorage.setItem("kftpu-ns", sel.value);
      $("detail-panel").style.display = "none";
      loadRuns(sel.value).catch((err) => showError(err.message));
    });
    setInterval(() => loadRuns(sel.value).catch(() => {}), 15000);
  } catch (err) {
    if (err.message !== "unauthenticated") showError(err.message);
  }
}

main();
