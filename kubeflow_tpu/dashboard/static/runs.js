// Workflow runs page over /api/runs/<ns> (live CRs + RunArchive merge).

"use strict";
// helpers ($, showError, api, esc) come from common.js

// Phase colors for DAG nodes (pill palette twins).
const PHASE_FILL = {
  Succeeded: "#188038", Running: "#1a73e8", Failed: "#d93025",
  Error: "#d93025", Pending: "#9aa0a6",
};

// Layered left-to-right DAG of spec.steps (dependencies), each node
// colored by its status.nodes phase — the KFP graph view's role.
function drawDag(steps, nodes) {
  const svg = $("dag");
  svg.innerHTML = "";
  if (!steps.length) { svg.setAttribute("height", 0); return; }
  const depth = {};
  const byName = {};
  for (const s of steps) byName[s.name] = s;
  const depthOf = (name, seen) => {
    if (depth[name] != null) return depth[name];
    if (!byName[name] || (seen && seen.has(name))) return 0;
    const mark = seen || new Set();
    mark.add(name);
    const deps = byName[name].dependencies || [];
    const d = deps.length
      ? 1 + Math.max(...deps.map((p) => depthOf(p, mark))) : 0;
    depth[name] = d;
    return d;
  };
  steps.forEach((s) => depthOf(s.name));
  const cols = [];
  for (const s of steps) {
    const d = depth[s.name] || 0;
    (cols[d] = cols[d] || []).push(s.name);
  }
  const W = 960, CW = Math.max(140, Math.min(220, W / cols.length));
  const RH = 44, NH = 28, NW = Math.min(CW - 36, 150);
  // cols may be sparse (a step depending on a name not in the spec
  // leaves depth-0 empty) — Array.from visits holes, .map skips them
  const H = Math.max(...Array.from(cols, (c) => (c || []).length))
    * RH + 24;
  svg.setAttribute("height", H);
  const pos = {};
  cols.forEach((col, ci) => col.forEach((name, ri) => {
    pos[name] = { x: 12 + ci * CW, y: 12 + ri * RH };
  }));
  const NS = "http://www.w3.org/2000/svg";
  const el = (tag, attrs, text) => {
    const e = document.createElementNS(NS, tag);
    for (const [k, v] of Object.entries(attrs)) e.setAttribute(k, v);
    if (text != null) e.textContent = text;
    return e;
  };
  for (const s of steps) {
    for (const dep of s.dependencies || []) {
      if (!pos[dep]) continue;
      const a = pos[dep], b = pos[s.name];
      const x1 = a.x + NW, y1 = a.y + NH / 2,
            x2 = b.x, y2 = b.y + NH / 2, mx = (x1 + x2) / 2;
      svg.appendChild(el("path", {
        d: `M${x1},${y1} C${mx},${y1} ${mx},${y2} ${x2},${y2}`,
        fill: "none", stroke: "#9aa0a6", "stroke-width": 1.5,
      }));
    }
  }
  for (const s of steps) {
    const p = pos[s.name];
    const phase = (nodes[s.name] || {}).phase || "Pending";
    svg.appendChild(el("rect", {
      x: p.x, y: p.y, width: NW, height: NH, rx: 6,
      fill: PHASE_FILL[phase] || PHASE_FILL.Pending, opacity: 0.9,
    }));
    const label = el("text", {
      x: p.x + NW / 2, y: p.y + NH / 2 + 4, "text-anchor": "middle",
      fill: "#fff", "font-size": "12",
    }, s.name.length > 20 ? s.name.slice(0, 19) + "…" : s.name);
    const tip = el("title", {}, `${s.name}: ${phase}`);
    label.appendChild(tip);
    svg.appendChild(label);
  }
}

function fmtBytes(n) {
  if (n >= 1 << 20) return (n / (1 << 20)).toFixed(1) + " MiB";
  if (n >= 1 << 10) return (n / (1 << 10)).toFixed(1) + " KiB";
  return n + " B";
}

async function openRun(ns, name) {
  const d = await api(`/api/runs/${encodeURIComponent(ns)}/` +
                      encodeURIComponent(name));
  $("detail-panel").style.display = "";
  $("detail-title").textContent =
    `${name} — ${d.status.phase || "Pending"}` +
    (d.live ? "" : " (archived)");
  const nodes = d.status.nodes || {};
  drawDag(d.spec.steps || [], nodes);
  const rows = Object.entries(nodes);
  $("nodes").innerHTML = rows.length
    ? rows.map(([step, n]) => `
      <tr>
        <td>${esc(step)}</td>
        <td><span class="pill ${esc(n.phase)}">${esc(n.phase)}</span></td>
        <td>${esc(n.startedAt || "—")}</td>
        <td>${esc(n.finishedAt || "—")}</td>
        <td>${esc(n.message || "")}</td>
      </tr>`).join("")
    : "<tr><td colspan=5>no steps recorded</td></tr>";
  const arts = d.artifacts || [];
  $("artifacts").innerHTML = arts.length
    ? arts.map((a) => `
      <tr>
        <td>${esc(a.step)}</td>
        <td><a href="/api/artifacts/${encodeURIComponent(ns)}/${
          encodeURIComponent(name)}/${encodeURIComponent(a.step)}/${
          encodeURIComponent(a.name)}">${esc(a.name)}</a></td>
        <td>${fmtBytes(a.bytes)}</td>
      </tr>`).join("")
    : "<tr><td colspan=3>no artifacts reported</td></tr>";
  $("detail-panel").scrollIntoView({ behavior: "smooth" });
}

async function loadRuns(ns) {
  const runs = await api("/api/runs/" + encodeURIComponent(ns));
  $("runs").innerHTML = runs.length
    ? runs.map((r) => `
      <tr>
        <td><a href="#" data-run="${esc(r.name)}">${esc(r.name)}</a></td>
        <td><span class="pill ${esc(r.phase)}">${esc(r.phase)}</span></td>
        <td>${esc(r.succeededSteps)}/${esc(r.steps)}</td>
        <td>${esc(r.startedAt || "—")}</td>
        <td>${esc(r.finishedAt || "—")}</td>
        <td>${r.live ? "live" : "archive"}</td>
      </tr>`).join("")
    : "<tr><td colspan=6>no runs in this namespace</td></tr>";
  for (const a of document.querySelectorAll("a[data-run]")) {
    a.addEventListener("click", (e) => {
      e.preventDefault();
      openRun(ns, a.dataset.run).catch((err) => showError(err.message));
    });
  }
}

async function main() {
  try {
    const env = await api("/api/env-info");
    $("user-chip").textContent = env.user;
    const sel = $("ns-select");
    sel.innerHTML = env.namespaces
      .map((n) => `<option value="${esc(n)}">${esc(n)}</option>`).join("");
    const saved = localStorage.getItem("kftpu-ns");
    if (saved && env.namespaces.includes(saved)) sel.value = saved;
    await loadRuns(sel.value);
    // deep links: /runs.html#<run> or #<ns>/<run> (lineage chips)
    wireHashOpen(sel, loadRuns, openRun);
    sel.addEventListener("change", () => {
      localStorage.setItem("kftpu-ns", sel.value);
      $("detail-panel").style.display = "none";
      loadRuns(sel.value).catch((err) => showError(err.message));
    });
    setInterval(() => loadRuns(sel.value).catch(() => {}), 15000);
  } catch (err) {
    if (err.message !== "unauthenticated") showError(err.message);
  }
}

main();
