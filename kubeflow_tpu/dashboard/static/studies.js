// Studies page: list studies, expand into trials + objective chart.
// Data: /api/studies/<ns> and /api/studies/<ns>/<name>
// (kubeflow_tpu/dashboard/server.py).

"use strict";
// helpers ($, showError, api, esc) come from common.js

function fmt(v) {
  if (v == null) return "—";
  const n = Number(v);
  return Number.isFinite(n)
    ? (Math.abs(n) >= 1000 || (n !== 0 && Math.abs(n) < 0.01)
        ? n.toExponential(3) : n.toPrecision(4))
    : esc(v);
}

// Objective-vs-trial chart: single series (no legend needed — the panel
// title names it), 2px line, 8px hover targets, recessive grid, text in
// ink tokens, per-point tooltip.
function drawChart(trials, direction) {
  const svg = $("objective-chart");
  const tip = $("chart-tip");
  const pts = trials
    .map((t, i) => ({ i, t }))
    .filter((p) => p.t.objective != null)
    .map((p, k) => ({ k, i: p.i, name: p.t.name,
                      v: Number(p.t.objective) }));
  svg.innerHTML = "";
  if (!pts.length) {
    svg.innerHTML =
      '<text x="20" y="30">no completed trials reported the objective yet' +
      "</text>";
    return;
  }
  const W = 920, H = 240, L = 64, R = 16, T = 16, B = 34;
  const xs = (k) => pts.length === 1
    ? (L + (W - L - R) / 2)
    : L + (k * (W - L - R)) / (pts.length - 1);
  let lo = Math.min(...pts.map((p) => p.v));
  let hi = Math.max(...pts.map((p) => p.v));
  if (lo === hi) { lo -= Math.abs(lo) * 0.1 || 1; hi += Math.abs(hi) * 0.1 || 1; }
  const ys = (v) => T + (1 - (v - lo) / (hi - lo)) * (H - T - B);
  const NS = "http://www.w3.org/2000/svg";
  const el = (tag, attrs, text) => {
    const e = document.createElementNS(NS, tag);
    for (const [k, v] of Object.entries(attrs)) e.setAttribute(k, v);
    if (text != null) e.textContent = text;
    return e;
  };
  // recessive horizontal grid at 4 ticks + y labels
  for (let g = 0; g <= 3; g++) {
    const v = lo + (g * (hi - lo)) / 3;
    const y = ys(v);
    svg.appendChild(el("line", { x1: L, x2: W - R, y1: y, y2: y,
                                 class: "gridline" }));
    svg.appendChild(el("text", { x: L - 8, y: y + 4,
                                 "text-anchor": "end" }, fmt(v)));
  }
  svg.appendChild(el("line", { x1: L, x2: W - R, y1: H - B, y2: H - B,
                               class: "axisline" }));
  svg.appendChild(el("text", { x: (L + W - R) / 2, y: H - 8,
                               "text-anchor": "middle" },
                    "trial (completion order)"));
  // running best line (the curve a tuner reads) + per-trial dots
  const sign = direction === "maximize" ? 1 : -1;
  let best = null;
  const bestPts = pts.map((p) => {
    if (best == null || sign * p.v > sign * best) best = p.v;
    return { x: xs(p.k), y: ys(best) };
  });
  svg.appendChild(el("polyline", {
    points: bestPts.map((p) => `${p.x},${p.y}`).join(" "),
    fill: "none", stroke: "#1a73e8", "stroke-width": 2,
    "stroke-linejoin": "round",
  }));
  for (const p of pts) {
    const dot = el("circle", {
      cx: xs(p.k), cy: ys(p.v), r: 4,
      fill: "#1a73e8", stroke: "var(--surface)", "stroke-width": 2,
    });
    // hover target larger than the mark
    const hit = el("circle", { cx: xs(p.k), cy: ys(p.v), r: 10,
                               fill: "transparent" });
    hit.addEventListener("mouseenter", () => {
      dot.setAttribute("r", 6);
      tip.innerHTML = `<b>${esc(p.name)}</b>objective: ${fmt(p.v)}`;
      tip.style.display = "block";
      tip.style.left = Math.min(xs(p.k) + 12, W - 180) + "px";
      tip.style.top = (ys(p.v) - 10) + "px";
    });
    hit.addEventListener("mouseleave", () => {
      dot.setAttribute("r", 4);
      tip.style.display = "none";
    });
    svg.appendChild(dot);
    svg.appendChild(hit);
  }
}

async function openStudy(ns, name) {
  const d = await api(`/api/studies/${encodeURIComponent(ns)}/` +
                      encodeURIComponent(name));
  $("detail-panel").style.display = "";
  $("detail-title").textContent =
    `${name} — ${d.objective || "objective"} (${d.direction})`;
  drawChart(d.trials, d.direction);
  $("trials").innerHTML = d.trials.length
    ? d.trials.map((t) => `
      <tr>
        <td>${esc(t.name)}</td>
        <td><code>${esc(JSON.stringify(t.parameters))}</code></td>
        <td><span class="pill ${esc(t.phase)}">${esc(t.phase)}</span></td>
        <td>${fmt(t.objective)}</td>
      </tr>`).join("")
    : "<tr><td colspan=4>no trials yet</td></tr>";
  $("detail-panel").scrollIntoView({ behavior: "smooth" });
}

async function loadStudies(ns) {
  const studies = await api("/api/studies/" + encodeURIComponent(ns));
  $("studies").innerHTML = studies.length
    ? studies.map((s) => `
      <tr>
        <td><a href="#" data-study="${esc(s.name)}">${esc(s.name)}</a></td>
        <td>${esc(s.algorithm)}</td>
        <td>${esc(s.objective)} (${esc(s.direction)})</td>
        <td><span class="pill ${esc(s.phase)}">${esc(s.phase)}</span></td>
        <td>${esc(s.trials)}${s.trialsRunning
            ? ` (${esc(s.trialsRunning)} running)` : ""}</td>
        <td>${s.bestTrial
            ? `${fmt(s.bestTrial.objective)} · ${esc(s.bestTrial.name)}`
            : "—"}</td>
      </tr>`).join("")
    : "<tr><td colspan=6>no studies in this namespace</td></tr>";
  for (const a of document.querySelectorAll("a[data-study]")) {
    a.addEventListener("click", (e) => {
      e.preventDefault();
      openStudy(ns, a.dataset.study).catch((err) => showError(err.message));
    });
  }
}

async function main() {
  try {
    const env = await api("/api/env-info");
    $("user-chip").textContent = env.user;
    const sel = $("ns-select");
    sel.innerHTML = env.namespaces
      .map((n) => `<option value="${esc(n)}">${esc(n)}</option>`).join("");
    const saved = localStorage.getItem("kftpu-ns");
    if (saved && env.namespaces.includes(saved)) sel.value = saved;
    await loadStudies(sel.value);
    // deep links: /studies.html#<study> or #<ns>/<study>
    wireHashOpen(sel, loadStudies, openStudy);
    sel.addEventListener("change", () => {
      localStorage.setItem("kftpu-ns", sel.value);
      $("detail-panel").style.display = "none";
      loadStudies(sel.value).catch((err) => showError(err.message));
    });
    setInterval(() => loadStudies(sel.value).catch(() => {}), 15000);
  } catch (err) {
    if (err.message !== "unauthenticated") showError(err.message);
  }
}

main();
