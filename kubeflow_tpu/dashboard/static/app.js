// Central dashboard frontend. Plain fetch() against the DashboardApi
// routes (kubeflow_tpu/dashboard/server.py); no framework.

"use strict";
// helpers ($, showError, api, esc) come from common.js

// icon names come from /api/dashboard-links (material names in the
// reference); map to simple glyphs
const ICONS = {
  book: "\u{1F4D3}", "donut-large": "\u{25D4}", tune: "\u{1F39B}",
  "device-hub": "\u{2B21}", "cloud-upload": "\u{2601}", people: "\u{1F465}",
};

async function loadCards() {
  const links = await api("/api/dashboard-links");
  $("cards").innerHTML = links.map((l) => `
    <a class="card" href="${esc(l.link)}">
      <div class="icon">${ICONS[l.icon] || "\u{25A4}"}</div>
      <h3>${esc(l.text)}</h3>
      <p>${esc(l.link)}</p>
    </a>`).join("");
}

async function loadEnv() {
  const env = await api("/api/env-info");
  $("user-chip").textContent =
    `${env.user} · ${env.platform.kind} ${env.platform.version}` +
    (env.isClusterAdmin ? " · admin" : "");
  const sel = $("ns-select");
  sel.innerHTML = env.namespaces
    .map((n) => `<option value="${esc(n)}">${esc(n)}</option>`).join("");
  const saved = localStorage.getItem("kftpu-ns");
  if (saved && env.namespaces.includes(saved)) sel.value = saved;
  return sel.value;
}

async function loadActivities(ns) {
  $("activity-ns").textContent = ns || "—";
  if (!ns) { $("activities").innerHTML = ""; return; }
  const acts = await api("/api/activities/" + encodeURIComponent(ns));
  $("activities").innerHTML = acts.length
    ? acts.slice(0, 30).map((a) => `
      <tr>
        <td>${esc(a.time)}</td>
        <td><span class="pill ${esc(a.type)}">${esc(a.type)}</span></td>
        <td>${esc(a.reason)}</td>
        <td>${esc(a.object)}</td>
        <td>${esc(a.message)}</td>
      </tr>`).join("")
    : `<tr><td colspan="5">no recent events in ${esc(ns)}</td></tr>`;
}

async function loadApplications(ns) {
  $("health-ns").textContent = ns || "—";
  if (!ns) { $("applications").innerHTML = ""; return; }
  const apps = await api("/api/applications/" + encodeURIComponent(ns));
  $("applications").innerHTML = apps.length
    ? apps.map((a) => `
      <tr>
        <td>${esc(a.name)}</td>
        <td><span class="pill ${a.phase === "Ready" ? "Normal" : "Warning"}">
            ${esc(a.phase)}</span></td>
        <td>${esc(a.ready)}</td>
        <td>${a.failing.length ? esc(a.failing.join(", ")) : "—"}</td>
      </tr>`).join("")
    : `<tr><td colspan="4">no Application CRs in ${esc(ns)}</td></tr>`;
}

async function loadMetrics() {
  const metrics = await api("/api/metrics/cluster");
  $("metrics").innerHTML = metrics.length
    ? metrics.slice(0, 40).map((m) => `
      <tr><td>${esc(m.metric)}</td><td>${esc(m.value)}</td></tr>`).join("")
    : "<tr><td colspan=2>no metrics reported yet</td></tr>";
}

async function loadWorkgroup() {
  const wg = await api("/api/workgroup/exists");
  if (wg.hasWorkgroup) {
    $("workgroup-panel").style.display = "";
    $("workgroup-info").textContent =
      "Your workgroups: " + wg.workgroups.join(", ");
  }
}

async function main() {
  try {
    await loadCards();
    const ns = await loadEnv();
    await Promise.all([loadActivities(ns), loadApplications(ns),
                       loadMetrics(), loadWorkgroup()]);
    $("ns-select").addEventListener("change", (e) => {
      localStorage.setItem("kftpu-ns", e.target.value);
      loadActivities(e.target.value).catch((err) => showError(err.message));
      loadApplications(e.target.value).catch((err) => showError(err.message));
    });
    setInterval(() => {
      loadApplications($("ns-select").value).catch(() => {});
      loadActivities($("ns-select").value).catch(() => {});
      loadMetrics().catch(() => {});
    }, 15000);
  } catch (err) {
    if (err.message !== "unauthenticated") showError(err.message);
  }
}

main();
