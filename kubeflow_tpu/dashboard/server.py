"""Dashboard REST API: env-info, namespaces, activities, metrics, workgroup.

Route parity with the reference's Express server
(``/root/reference/components/centraldashboard/app/api.ts:78-150``):

- ``GET /api/env-info``            — platform + namespaces + user
- ``GET /api/namespaces``          — namespace list
- ``GET /api/activities/<ns>``     — k8s Events, newest first (api.ts:131-136)
- ``GET /api/metrics/<type>``      — behind a swappable MetricsService
  (``metrics_service_factory.ts``; Stackdriver impl swapped for one
  reading the framework's own Prometheus registry)
- ``GET /api/metrics/autoscale``   — the serving autoscaler's loop state
  (per-model ready/warming/draining replicas, panic flag, events); fed
  by an in-process :class:`~kubeflow_tpu.autoscale.reconciler.Autoscaler`
  or proxied from the autoscaler service (``KFTPU_AUTOSCALE_URL``)
- ``GET /api/metrics/engine``      — the decode-engine series for the
  serving panel: slot occupancy, queue depth, prefix-cache bytes, the
  paged-cache gauges ``kftpu_engine_kv_pages_in_use`` /
  ``kftpu_engine_prefill_chunks_total``, and the prefix-trie /
  copy-on-write effectiveness counters
  ``kftpu_engine_prefix_pages_shared_total`` /
  ``kftpu_engine_cow_splits_total`` (docs/SERVING.md; the paged
  ``engine.snapshot()`` mirrors them as ``prefix_hits`` /
  ``prefix_misses`` / ``prefix_pages_shared`` / ``cow_splits``)
- ``GET /api/metrics/scheduler``   — the cluster gang queue's state
  (``kubeflow_tpu/scheduler/queue.py``; docs/SCHEDULER.md): per-gang
  queue states, priorities, waits, preemption counts, plus the
  ``kftpu_queue_depth`` / ``kftpu_queue_wait_seconds`` /
  ``kftpu_preemptions_total`` series when no queue is in-process
- ``GET /api/metrics/goodput``     — the fleet goodput/badput rollup
  (``kubeflow_tpu/obs/goodput.py``; docs/OBSERVABILITY.md "Goodput"):
  every TpuJob's ``status.goodput`` ledger weighted by chips × seconds,
  per-state fractions + per-job rows
- ``GET /api/metrics/requests``    — the fleet request-lifecycle rollup
  (``kubeflow_tpu/obs/requests.py``; docs/OBSERVABILITY.md "Request
  lifecycle"): per-model and fleet phase-seconds breakdowns, phase
  fractions, TTFT percentiles, shed/breach counts
- ``GET /api/models/<model>/requests`` — one model's request-phase
  percentiles (TTFT/ITL/per-phase seconds) plus the single worst-TTFT
  request's trace exemplar (resolves via ``GET /api/traces/<id>``,
  mirroring the goodput worst-interval exemplar)
- ``GET /api/metrics/query``       — the monitoring tier's query API
  over the in-process time-series store (``kubeflow_tpu/obs/tsdb.py``):
  instant and range evaluation of ``instant``/``rate``/``delta``/
  ``avg``/``quantile`` over any stored series, exemplar trace ids
  included (docs/OBSERVABILITY.md Monitoring section). Query params:
  ``metric`` (required), ``func``, ``window`` (seconds), ``q``
  (quantile), ``start``/``end``/``step`` (range mode), and repeated
  ``label=k:v`` matchers (``v`` may end in ``*`` for prefix match)
- ``GET /api/alerts``              — the alert engine's rule states
  (``kubeflow_tpu/obs/alerts.py``): pending/firing alerts, values,
  exemplar trace ids; with no in-process
  :class:`~kubeflow_tpu.obs.alerts.AlertManager` attached, the
  registry's ``kftpu_alerts_*`` series still answer "is anything
  firing"
- ``GET /api/workgroup/exists``    — profile/workgroup flow via kfam
  (``api_workgroup.ts``)
- ``GET /api/dashboard-links``     — component cards for the UI shell
- ``GET /api/traces``              — recent root spans from the platform's
  span collector (``kubeflow_tpu/obs``); ``GET /api/traces/<trace_id>``
  returns one full span tree (docs/OBSERVABILITY.md)
- ``GET /api/jobs/<ns>/<name>/telemetry`` — training-plane telemetry for
  one TpuJob: step rate, MFU, recompiles, per-worker lag + stragglers,
  aggregated live from the workers' beacon ConfigMaps
  (``kubeflow_tpu/obs/steps.py``; docs/OBSERVABILITY.md), plus the
  ``goodput.fraction`` efficiency summary
- ``GET /api/jobs/<ns>/<name>/goodput`` — one job's goodput ledger:
  interval timeline, per-state fractions, and the worst badput
  interval's trace exemplar (resolves via ``GET /api/traces/<id>``)
- ``GET /api/jobs/<ns>/<name>/profile`` — one job's compile & memory
  profile (docs/OBSERVABILITY.md "Compile & memory"): event-sourced
  compile count/seconds with per-module breakdown, static
  ``memory_analysis`` budgets per HLO fingerprint, and the gang's live
  HBM watermark from the beacon ``hbm`` blocks
"""

from __future__ import annotations

import abc
import logging
import os
from typing import Any, Dict, List, Optional, Tuple

import kubeflow_tpu
from kubeflow_tpu.k8s.client import ApiError, KubeClient
from kubeflow_tpu.obs import DEFAULT_COLLECTOR, SpanCollector
from kubeflow_tpu.tenancy.kfam import AccessManagementApi
from kubeflow_tpu.tenancy.profiles import PROFILE_API_VERSION, PROFILE_KIND
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import serve_json

log = logging.getLogger(__name__)


class MetricsService(abc.ABC):
    """Swappable metrics backend (reference MetricsService interface)."""

    @abc.abstractmethod
    def query(self, metric_type: str) -> List[Dict[str, Any]]: ...


class RegistryMetricsService(MetricsService):
    """Serves the framework's own registry instead of Stackdriver."""

    PREFIXES = {
        "podcpu": "kftpu_",          # closest equivalents by prefix
        "podmem": "kftpu_",
        "cluster": "kftpu_",
        # the serving panel's decode-engine series: occupancy, queue
        # depth, and the paged-cache gauges (kv_pages_in_use,
        # prefill_chunks_total — docs/SERVING.md)
        "engine": "kftpu_engine_",
    }

    def __init__(self, registry=DEFAULT_REGISTRY) -> None:
        self.registry = registry

    def query(self, metric_type: str) -> List[Dict[str, Any]]:
        prefix = self.PREFIXES.get(metric_type, metric_type)
        return _parse_prom(self.registry.expose(), prefix)


def _parse_prom(text: str, prefix: str) -> List[Dict[str, Any]]:
    """Prefix-filtered series list over the shared escape-aware parser
    (``obs/scrape.parse_exposition``) — the old line-splitting here
    mis-read exactly what this PR made representable: escaped label
    values and OpenMetrics exemplar suffixes."""
    from kubeflow_tpu.obs.scrape import parse_exposition
    from kubeflow_tpu.utils.metrics import format_labels

    out = []
    for s in parse_exposition(text):
        if not s.name.startswith(prefix):
            continue
        metric = s.name
        if s.labels:
            metric += "{" + format_labels(tuple(sorted(
                s.labels.items()))) + "}"
        out.append({"metric": metric, "value": s.value})
    return out


class ClusterMetricsService(MetricsService):
    """Scrapes the framework components' ``serve_metrics`` endpoints.

    The reference's MetricsService is an explicitly swappable
    cluster-metrics backend (``/root/reference/components/centraldashboard/
    app/metrics_service_factory.ts``); this implementation aggregates the
    operator/serving/controller Prometheus endpoints (targets from
    ``KFTPU_METRICS_TARGETS``, comma-separated ``name=url`` pairs) so the
    dashboard's metrics panel shows cluster state, not the dashboard's own
    request counters. Falls back to the in-process registry when no
    targets are configured (dev mode)."""

    def __init__(self, targets: Optional[Dict[str, str]] = None,
                 timeout_s: float = 5.0) -> None:
        import os

        if targets is None:
            targets = {}
            for pair in os.environ.get("KFTPU_METRICS_TARGETS",
                                       "").split(","):
                name, _, url = pair.strip().partition("=")
                if name and url:
                    targets[name] = url
        self.targets = targets
        self.timeout_s = timeout_s
        self._fallback = RegistryMetricsService()

    def _scrape(self, url: str) -> Optional[str]:
        import http.client
        import urllib.request

        try:
            with urllib.request.urlopen(url,
                                        timeout=self.timeout_s) as resp:
                return resp.read().decode("utf-8", "replace")
        except (OSError, http.client.HTTPException, ValueError):
            # any unreachable/garbled target degrades to up=0, never a 500
            return None

    @staticmethod
    def _stamp_target(metric: str, name: str) -> str:
        """Add target="name" to a metric, merging into existing labels so
        same-named series from different components stay distinguishable."""
        if "{" in metric:
            head, rest = metric.split("{", 1)
            return f'{head}{{target="{name}",{rest}'
        return f'{metric}{{target="{name}"}}'

    def query(self, metric_type: str) -> List[Dict[str, Any]]:
        from concurrent.futures import ThreadPoolExecutor

        if not self.targets:
            return self._fallback.query(metric_type)
        prefix = RegistryMetricsService.PREFIXES.get(metric_type,
                                                     metric_type)
        items = sorted(self.targets.items())
        # concurrent scrapes: panel latency is max(target), not the sum of
        # timeouts when a pod is down
        with ThreadPoolExecutor(max_workers=min(8, len(items))) as pool:
            texts = list(pool.map(lambda kv: self._scrape(kv[1]), items))
        out: List[Dict[str, Any]] = []
        for (name, _url), text in zip(items, texts):
            out.append({"metric": f'up{{target="{name}"}}',
                        "value": 0.0 if text is None else 1.0})
            for m in _parse_prom(text or "", prefix):
                m["metric"] = self._stamp_target(m["metric"], name)
                out.append(m)
        return out


class DashboardApi:
    """Pure handle() route table served via the shared JSON scaffold."""

    def __init__(self, client: KubeClient, *,
                 metrics: Optional[MetricsService] = None,
                 kfam: Optional[AccessManagementApi] = None,
                 platform: str = "gcp-tpu",
                 run_archive=None,
                 artifact_store=None,
                 authorize=None,
                 autoscaler=None,
                 collector: Optional[SpanCollector] = None,
                 scheduler_queue=None,
                 tsdb=None,
                 alerts=None,
                 edge=None,
                 request_ledger=None) -> None:
        from kubeflow_tpu.tenancy.authz import default_authorizer

        self.client = client
        self.metrics = metrics or ClusterMetricsService()
        self.kfam = kfam or AccessManagementApi(client)
        self.platform = platform
        self.run_archive = run_archive
        self.artifact_store = artifact_store
        # namespace-scoped tenant data (studies, runs) goes through the
        # same Profile-RBAC default as the notebook webapp; allow_all only
        # behind the explicit dev flag
        self.authorize = (authorize if authorize is not None
                          else default_authorizer(client))
        # anything with .status() (an Autoscaler, or a URL-backed shim);
        # None = proxy to KFTPU_AUTOSCALE_URL, else registry gauges only
        self.autoscaler = autoscaler
        # span source for /api/traces — the process-local collector by
        # default (dev/in-process), a remote-backed shim when the fleet
        # ships spans to the trace-collector service instead
        self.collector = (collector if collector is not None
                          else DEFAULT_COLLECTOR)
        # anything with .status() (a scheduler GangQueue); None = the
        # registry's kftpu_queue_* gauges only
        self.scheduler_queue = scheduler_queue
        # the monitoring tier (docs/OBSERVABILITY.md): a TimeSeriesStore
        # for /api/metrics/query and an AlertManager for /api/alerts;
        # None degrades each route (410 for queries — there is no store
        # to ask — and the registry's kftpu_alerts_* series for alerts)
        self.tsdb = tsdb
        self.alerts = alerts
        # anything with .status() (a fleet FleetEdge); None = the
        # registry's kftpu_edge_* / kftpu_multiplex_* series only
        self.edge = edge
        # the serving request-lifecycle ledger for /api/metrics/requests
        # and /api/models/<model>/requests — the process-default ledger
        # unless a test or a multi-engine host wires its own
        from kubeflow_tpu.obs import requests as reqobs

        self.rledger = (request_ledger if request_ledger is not None
                        else reqobs.DEFAULT_LEDGER)

    def _authz(self, user: str, ns: str, resource: str) -> None:
        if not self.authorize(user, "get", ns, resource):
            raise ApiError(403,
                           f"{user!r} may not view {resource} in {ns!r}")

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "") -> Tuple[int, Any]:
        # route on the bare path; the query string (the /api/metrics/query
        # parameters) is parsed by the handler that wants it
        path, _, query = path.partition("?")
        try:
            if method != "GET":
                return 405, {"error": "dashboard API is read-only"}
            if path == "/api/env-info":
                return 200, self.env_info(user)
            if path == "/api/namespaces":
                return 200, self.namespaces()
            if path.startswith("/api/activities/"):
                ns = path.rsplit("/", 1)[1]
                if not ns:
                    # empty ns = cluster-wide list at the client layer —
                    # a cross-tenant leak; reject before authz
                    return 404, {"error": f"no route {path}"}
                # k8s Events carry workload names/failure messages —
                # namespace-scoped tenant data, same guard as studies/runs
                self._authz(user, ns, "events")
                return 200, self.activities(ns)
            if path == "/api/metrics/autoscale":
                return 200, self.autoscale_view()
            if path == "/api/metrics/scheduler":
                return 200, self.scheduler_view()
            if path == "/api/metrics/edge":
                return 200, self.edge_view()
            if path == "/api/metrics/goodput":
                return 200, self.goodput_view()
            if path == "/api/metrics/requests":
                return 200, self.requests_view()
            if path.startswith("/api/models/"):
                parts = path[len("/api/models/"):].split("/")
                if len(parts) == 2 and parts[0] \
                        and parts[1] == "requests":
                    return self.model_requests(parts[0])
                return 404, {"error": f"no route {path}"}
            if path == "/api/metrics/query":
                return self.metrics_query(query)
            if path == "/api/alerts":
                return 200, self.alerts_view()
            if path == "/api/traces":
                return 200, self.traces()
            if path.startswith("/api/traces/"):
                tid = path[len("/api/traces/"):]
                if not tid or "/" in tid:
                    return 404, {"error": f"no route {path}"}
                return self.trace_detail(tid)
            if path.startswith("/api/metrics/"):
                return 200, self.metrics.query(path.rsplit("/", 1)[1])
            if path == "/api/workgroup/exists":
                return 200, self.workgroup_exists(user)
            if path == "/api/dashboard-links":
                return 200, self.dashboard_links()
            if path.startswith("/api/jobs/"):
                # the training-plane telemetry surface
                # (docs/OBSERVABILITY.md); the literal "/api/jobs/" is
                # this route's entry in the tpulint TPU004 route table
                parts = path[len("/api/jobs/"):].split("/")
                if len(parts) == 3 and parts[0] and parts[1] \
                        and parts[2] == "telemetry":
                    self._authz(user, parts[0], "tpujobs")
                    return self.job_telemetry(parts[0], parts[1])
                if len(parts) == 3 and parts[0] and parts[1] \
                        and parts[2] == "goodput":
                    self._authz(user, parts[0], "tpujobs")
                    return self.job_goodput(parts[0], parts[1])
                if len(parts) == 3 and parts[0] and parts[1] \
                        and parts[2] == "profile":
                    self._authz(user, parts[0], "tpujobs")
                    return self.job_profile(parts[0], parts[1])
                return 404, {"error": f"no route {path}"}
            if path.startswith("/api/tpujobs/"):
                parts = path[len("/api/tpujobs/"):].split("/")
                if not parts[0]:
                    return 404, {"error": f"no route {path}"}
                self._authz(user, parts[0], "tpujobs")
                if len(parts) == 1:
                    return 200, self.tpujobs(parts[0])
                if len(parts) == 2:
                    return self.tpujob_detail(parts[0], parts[1])
            if path.startswith("/api/studies/"):
                parts = path[len("/api/studies/"):].split("/")
                if not parts[0]:
                    return 404, {"error": f"no route {path}"}
                self._authz(user, parts[0], "studies")
                if len(parts) == 1:
                    return 200, self.studies(parts[0])
                if len(parts) == 2:
                    return self.study_detail(parts[0], parts[1])
            if path.startswith("/api/runs/"):
                parts = path[len("/api/runs/"):].split("/")
                if not parts[0]:
                    return 404, {"error": f"no route {path}"}
                self._authz(user, parts[0], "workflows")
                if len(parts) == 1:
                    return 200, self.runs(parts[0])
                if len(parts) == 2:
                    return self.run_detail(parts[0], parts[1])
            if path.startswith("/api/artifacts/"):
                from urllib.parse import unquote

                # segments are percent-decoded (artifact steps can be
                # nested paths, sent as one %2F-encoded segment)
                parts = [unquote(p) for p in
                         path[len("/api/artifacts/"):].split("/")]
                if len(parts) < 2 or not parts[0] or not parts[1]:
                    return 404, {"error": f"no route {path}"}
                # artifacts belong to workflow runs — same guard
                self._authz(user, parts[0], "workflows")
                if len(parts) == 2:
                    return self.artifacts(parts[0], parts[1])
                if len(parts) >= 4:
                    return self.artifact_download(
                        parts[0], parts[1], "/".join(parts[2:-1]),
                        parts[-1])
                return 404, {"error": f"no route {path}"}
            if path.startswith("/api/applications/"):
                parts = path[len("/api/applications/"):].split("/")
                # empty ns would become a CLUSTER-WIDE list at the client
                # layer — a cross-tenant leak; reject before authz
                if len(parts) != 1 or not parts[0]:
                    return 404, {"error": f"no route {path}"}
                self._authz(user, parts[0], "applications")
                return 200, self.applications(parts[0])
            return 404, {"error": f"no route {path}"}
        except ApiError as e:
            return e.code, {"error": e.message}

    # -- handlers ----------------------------------------------------------

    def env_info(self, user: str) -> Dict[str, Any]:
        return {
            "user": user or "anonymous",
            "platform": {"kind": self.platform,
                         "version": kubeflow_tpu.__version__},
            "namespaces": [n["name"] for n in self.namespaces()],
            "isClusterAdmin": self.kfam.is_cluster_admin(user),
        }

    def namespaces(self) -> List[Dict[str, str]]:
        out = []
        for ns in self.client.list("v1", "Namespace"):
            md = ns.get("metadata", {})
            out.append({"name": md.get("name", ""),
                        "owner": (md.get("annotations", {}) or {})
                        .get("owner", "")})
        return out

    def activities(self, ns: str) -> List[Dict[str, Any]]:
        events = self.client.list("v1", "Event", ns)
        events.sort(key=lambda e: e.get("lastTimestamp", ""), reverse=True)
        return [{
            "time": e.get("lastTimestamp", ""),
            "type": e.get("type", "Normal"),
            "reason": e.get("reason", ""),
            "message": e.get("message", ""),
            "object": (e.get("involvedObject", {}) or {}).get("name", ""),
        } for e in events]

    def autoscale_view(self) -> Dict[str, Any]:
        """The autoscaler's loop state for the serving panel.

        Resolution order: an in-process autoscaler handed to the
        constructor, else the autoscaler service named by
        ``KFTPU_AUTOSCALE_URL``, else the local registry's
        ``kftpu_autoscale_*`` gauges (enough for "is it scaling" even
        when the dashboard can't reach the loop)."""
        if self.autoscaler is not None:
            return self.autoscaler.status()
        url = os.environ.get("KFTPU_AUTOSCALE_URL", "")
        if url:
            import json as _json
            import urllib.request

            try:
                with urllib.request.urlopen(
                        f"{url.rstrip('/')}/api/autoscale/status",
                        timeout=5.0) as resp:
                    return _json.loads(resp.read())
            except (OSError, ValueError):
                return {"error": f"autoscaler at {url} unreachable",
                        "metrics": _parse_prom(DEFAULT_REGISTRY.expose(),
                                               "kftpu_autoscale_")}
        return {"metrics": _parse_prom(DEFAULT_REGISTRY.expose(),
                                       "kftpu_autoscale_")}

    def scheduler_view(self) -> Dict[str, Any]:
        """The cluster gang queue's state for the scheduler panel
        (docs/SCHEDULER.md): per-gang queue states, waits, priorities,
        preemption counts from an in-process
        :class:`~kubeflow_tpu.scheduler.queue.GangQueue`; with no queue
        attached, the registry's ``kftpu_queue_*`` /
        ``kftpu_preemptions_total`` series still answer "is the queue
        moving"."""
        if self.scheduler_queue is not None:
            return self.scheduler_queue.status()
        exposition = DEFAULT_REGISTRY.expose()
        return {"metrics": _parse_prom(exposition, "kftpu_queue_")
                + _parse_prom(exposition, "kftpu_preemptions_total")}

    def edge_view(self) -> Dict[str, Any]:
        """The fleet serving edge's state for the serving panel
        (docs/EDGE.md): replica ring membership, per-replica in-flight
        and pressure, SLO-class table and shed counts, multiplex
        residency from an in-process
        :class:`~kubeflow_tpu.edge.fleet.FleetEdge`; with none
        attached, the registry's ``kftpu_edge_*`` /
        ``kftpu_multiplex_*`` series still answer "is the edge
        shedding"."""
        if self.edge is not None:
            return self.edge.status()
        exposition = DEFAULT_REGISTRY.expose()
        return {"metrics": _parse_prom(exposition, "kftpu_edge_")
                + _parse_prom(exposition, "kftpu_multiplex_")}

    def goodput_view(self) -> Dict[str, Any]:
        """The fleet goodput rollup (docs/OBSERVABILITY.md "Goodput"):
        every TpuJob's ``status.goodput`` ledger weighted by
        chips × seconds, so one idle 256-chip gang outweighs fifty
        busy singles. Per-job rows carry the fraction the tuning/
        scheduling planes rank by."""
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )
        from kubeflow_tpu.obs import goodput as gp
        from kubeflow_tpu.operators.tpujob import TpuJobSpec

        rows = []
        jobs = []
        for j in self.client.list(API_VERSION, TPUJOB_KIND):
            md = j.get("metadata", {}) or {}
            spec = j.get("spec", {}) or {}
            status = j.get("status", {}) or {}
            g = status.get("goodput")
            if not g:
                continue
            try:
                # the SAME chips definition the operator weights the
                # fleet counters with — the rollup and the
                # job-badput-burn alert must not diverge
                chips = TpuJobSpec.from_dict(spec).chips
            except (TypeError, ValueError):
                # from_dict raises TypeError on null numerics, not
                # just ValueError — one bad spec must not 500 the
                # whole fleet rollup
                # a spec that went invalid after running still has a
                # ledger; fall back to the schema defaults
                chips = (int(spec.get("slices", 1) or 1)
                         * int(spec.get("hostsPerSlice", 1) or 1)
                         * int(spec.get("chipsPerHost", 4) or 4))
            rows.append((chips, g))
            jobs.append({
                "namespace": md.get("namespace", ""),
                "name": md.get("name", ""),
                "phase": status.get("phase", "Pending"),
                "chips": chips,
                "wallSeconds": round(
                    float(g.get("asOf", 0.0) or 0.0)
                    - float(g.get("start", 0.0) or 0.0), 6),
                "goodputFraction": round(gp.goodput_fraction(g), 6),
            })
        jobs.sort(key=lambda r: (r["namespace"], r["name"]))
        return {**gp.fleet_rollup(rows), "perJob": jobs}

    def job_goodput(self, ns: str, name: str) -> Tuple[int, Any]:
        """One job's goodput ledger: the interval timeline, per-state
        fractions, and a trace-linked exemplar for the single WORST
        badput interval — the span tree that explains where the wall
        clock went (``GET /api/traces/<traceId>`` opens it)."""
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )
        from kubeflow_tpu.obs import goodput as gp
        from kubeflow_tpu.obs.steps import tpujob_trace_ids

        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND, ns, name)
        if job is None:
            return 404, {"error": f"tpujob {name!r} not found"}
        status = job.get("status", {}) or {}
        g = status.get("goodput") or {}
        trace_id, _ = tpujob_trace_ids(
            ns, name, job.get("metadata", {}).get("uid", ""))
        worst = gp.worst_badput_interval(g)
        exemplar = None
        if worst is not None:
            exemplar = {**worst,
                        "seconds": round(worst["end"] - worst["start"],
                                         6),
                        "traceId": trace_id}
            # the span that caused it: the job-trace span overlapping
            # the interval the most (instantaneous decision spans —
            # queue place/preempt/requeue — touch it at a boundary)
            best, best_key = None, None
            for s in self.collector.spans():
                if s.trace_id != trace_id:
                    continue
                if s.start > worst["end"] or s.end < worst["start"]:
                    continue
                overlap = (min(s.end, worst["end"])
                           - max(s.start, worst["start"]))
                key = (overlap, s.end - s.start)
                if best_key is None or key > best_key:
                    best, best_key = s, key
            if best is not None:
                exemplar["spanId"] = best.span_id
                exemplar["span"] = best.name
        return 200, {
            "name": name,
            "namespace": ns,
            "phase": status.get("phase", "Pending"),
            "traceId": trace_id,
            **gp.view(g),
            "worstBadput": exemplar,
        }

    def requests_view(self) -> Dict[str, Any]:
        """``GET /api/metrics/requests``: the fleet request-lifecycle
        rollup off the ledger (docs/OBSERVABILITY.md "Request
        lifecycle")."""
        return self.rledger.rollup()

    def model_requests(self, model: str) -> Tuple[int, Any]:
        """One model's request-phase percentiles plus the single
        worst-TTFT request's trace exemplar — the request record's id
        IS its trace id, so ``GET /api/traces/<traceId>`` opens the
        span tree that explains the tail (the goodput worst-interval
        exemplar pattern at request granularity)."""
        view = self.rledger.view(model)
        if not view["count"]:
            return 404, {"error": f"no finished requests for model "
                                  f"{model!r}"}
        worst = self.rledger.worst_ttft(model)
        exemplar = None
        if worst is not None:
            exemplar = {
                "traceId": worst.rid,
                "ttftMs": (None if worst.ttft_ms is None
                           else round(worst.ttft_ms, 3)),
                "sloClass": worst.slo_class or "none",
                "shed": worst.shed,
                "breach": worst.breach,
            }
            # the span that explains the tail: the request-trace span
            # overlapping [submit, first token] the most (full-wall
            # fallback for requests that never produced one)
            t_hi = (worst.t_first_token
                    if worst.t_first_token is not None else worst.t_end)
            best, best_key = None, None
            for s in self.collector.spans():
                if s.trace_id != worst.rid:
                    continue
                if s.start > t_hi or s.end < worst.t_start:
                    continue
                overlap = min(s.end, t_hi) - max(s.start, worst.t_start)
                key = (overlap, s.end - s.start)
                if best_key is None or key > best_key:
                    best, best_key = s, key
            if best is not None:
                exemplar["spanId"] = best.span_id
                exemplar["span"] = best.name
        return 200, {**view, "worstTtft": exemplar}

    def metrics_query(self, query: str) -> Tuple[int, Any]:
        """The monitoring query API over the in-process tsdb
        (docs/OBSERVABILITY.md): instant evaluation by default, range
        evaluation when ``start``/``end`` are given (``func`` applied
        at each ``step``). One evaluation path with the alert engine
        (:func:`kubeflow_tpu.obs.tsdb.evaluate`), so a panel and the
        rule watching the same expression cannot disagree."""
        from urllib.parse import parse_qs

        if self.tsdb is None:
            return 410, {"error": "no time-series store attached "
                                  "(run the monitoring tier)"}
        params = parse_qs(query or "")

        def one(key: str, default: Optional[str] = None) -> Optional[str]:
            vals = params.get(key)
            return vals[-1] if vals else default

        metric = one("metric")
        if not metric:
            return 400, {"error": "missing required param 'metric'"}
        func = one("func", "instant")
        match: Dict[str, str] = {}
        for pair in params.get("label", []):
            k, sep, v = pair.partition(":")
            if not sep or not k:
                return 400, {"error": f"bad label matcher {pair!r}; "
                                      "use label=key:value"}
            match[k] = v
        try:
            window_s = float(one("window", "300"))
            q = float(one("q", "0.99"))
            start = one("start")
            end = one("end")
            step = float(one("step", "0") or 0)
        except ValueError as e:
            return 400, {"error": f"bad numeric param: {e}"}
        import math as _math

        if not 0.0 <= q <= 1.0:
            # histogram_quantile raises on this; a bad param must be a
            # 400 like every other one, not a 500 (NaN fails the
            # comparison chain and lands here too)
            return 400, {"error": f"q must be in [0, 1], got {q}"}
        if not _math.isfinite(window_s) or window_s <= 0:
            return 400, {"error": f"window must be a positive finite "
                                  f"number of seconds, got {window_s}"}
        from kubeflow_tpu.obs.tsdb import QUERY_FUNCS, evaluate

        if func not in QUERY_FUNCS:
            return 400, {"error": f"unknown func {func!r}; known: "
                                  f"{', '.join(QUERY_FUNCS)}"}
        base = {"metric": metric, "func": func, "labels": match}
        if func in ("rate", "delta", "avg", "quantile"):
            base["window"] = window_s
        if func == "quantile":
            base["q"] = q

        def exemplars_for(at: float) -> List[Dict[str, Any]]:
            if func != "quantile":
                return []
            return [e.to_dict() for e in self.tsdb.exemplars(
                f"{metric}_bucket", match, since=at - window_s)]

        if (start is None) != (end is None):
            # a half-specified range is a user error, not instant mode
            return 400, {"error": "range mode needs both start and end"}
        if start is not None and end is not None:
            try:
                t0, t1 = float(start), float(end)
            except ValueError as e:
                return 400, {"error": f"bad range param: {e}"}
            if not (_math.isfinite(t0) and _math.isfinite(t1)):
                # NaN compares false everywhere and inf overflows the
                # step arithmetic — both must be a 400, not a 500
                return 400, {"error": "start/end must be finite"}
            if t1 < t0:
                return 400, {"error": "end must be >= start"}
            if step <= 0:
                step = max((t1 - t0) / 60.0, 1e-9)
            # fixed evaluation count: start==end is one point, the
            # boundary is never double-counted, and a user-supplied
            # tiny step over a wide range cannot spin this handler
            # (the Prometheus point-cap stance). The ratio is checked
            # finite BEFORE int() — 1e300/1e-300 overflows to inf and a
            # NaN step slips every comparison
            ratio = (t1 - t0) / step
            if not _math.isfinite(ratio) or ratio > 10000:
                return 400, {"error": "range too dense: more than "
                                      "10000 evaluation steps"}
            n_steps = int(ratio + 1e-9)
            by_series: Dict[Tuple[Tuple[str, str], ...], Dict[str, Any]] \
                = {}
            for i in range(n_steps + 1):
                t = t0 + i * step
                for labels, value in evaluate(
                        self.tsdb, func, metric, match=match,
                        window_s=window_s, q=q, at=t):
                    key = tuple(sorted(labels.items()))
                    row = by_series.setdefault(
                        key, {"labels": labels, "points": []})
                    row["points"].append([round(t, 6), value])
            return 200, {**base, "start": t0, "end": t1, "step": step,
                         "result": list(by_series.values())}
        at = self.tsdb.clock()
        result = [{"labels": labels, "value": value}
                  for labels, value in evaluate(
                      self.tsdb, func, metric, match=match,
                      window_s=window_s, q=q, at=at)]
        return 200, {**base, "at": at, "result": result,
                     "exemplars": exemplars_for(at)}

    def alerts_view(self) -> Dict[str, Any]:
        """The alert engine's rule states for the monitoring panel;
        with no in-process :class:`~kubeflow_tpu.obs.alerts.
        AlertManager`, the registry's ``kftpu_alerts_*`` series still
        answer "is anything firing" (the scheduler_view fallback
        stance)."""
        if self.alerts is not None:
            return self.alerts.status()
        return {"metrics": _parse_prom(DEFAULT_REGISTRY.expose(),
                                       "kftpu_alerts_")}

    def traces(self) -> List[Dict[str, Any]]:
        """Recent root spans (+ per-trace span counts), newest first —
        the incident entry point: find the slow request, open its tree."""
        return self.collector.summary()

    def trace_detail(self, trace_id: str) -> Tuple[int, Any]:
        # the trace-collector service's handler, over this collector —
        # one API shape everywhere (docs/OBSERVABILITY.md)
        from kubeflow_tpu.obs.service import trace_detail

        return trace_detail(self.collector, trace_id)

    def workgroup_exists(self, user: str) -> Dict[str, Any]:
        profiles = self.client.list(PROFILE_API_VERSION, PROFILE_KIND)
        owned = []
        for p in profiles:
            owner = p.get("spec", {}).get("owner", {})
            name = owner.get("name") if isinstance(owner, dict) else owner
            if name == user:
                owned.append(p["metadata"]["name"])
        return {"hasWorkgroup": bool(owned), "workgroups": owned}

    # -- TPU jobs (the tf-job dashboard role) ------------------------------

    def tpujobs(self, ns: str) -> List[Dict[str, Any]]:
        """Job list with phase/shape/restarts — the reference's tf-job
        dashboard table (``/root/reference/components/tf-job-dashboard``)
        for the unified TpuJob."""
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )

        out = []
        for j in self.client.list(API_VERSION, TPUJOB_KIND, ns):
            spec, status = j.get("spec", {}), j.get("status", {})
            workers = status.get("workers", {}) or {}
            out.append({
                "name": j["metadata"]["name"],
                "phase": status.get("phase", "Pending"),
                "slices": spec.get("slices", 1),
                "hostsPerSlice": spec.get("hostsPerSlice", 1),
                "accelerator": spec.get("accelerator", ""),
                "restarts": status.get("restarts", 0),
                "workersRunning": workers.get("Running", 0),
                "workersTotal": int(spec.get("slices", 1))
                * int(spec.get("hostsPerSlice", 1)),
                "startTime": status.get("startTime", ""),
            })
        out.sort(key=lambda j: j["name"])
        return out

    def tpujob_detail(self, ns: str, name: str) -> Tuple[int, Any]:
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )

        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND, ns, name)
        if job is None:
            return 404, {"error": f"tpujob {name!r} not found"}
        pods = self.client.list("v1", "Pod", ns, label_selector={
            "kubeflow-tpu.org/job-name": name})
        workers = [{
            "name": p["metadata"]["name"],
            "phase": p.get("status", {}).get("phase", "Pending"),
            "slice": (p["metadata"].get("labels", {}) or {}).get(
                "kubeflow-tpu.org/slice", ""),
            "host": (p["metadata"].get("labels", {}) or {}).get(
                "kubeflow-tpu.org/host", ""),
        } for p in pods]
        # numeric placement order (string sort puts slice "10" before "2");
        # foreign pods with non-numeric labels sort last, never 500
        def order(w):
            try:
                return (0, int(w["slice"] or -1), int(w["host"] or -1), "")
            except ValueError:
                return (1, 0, 0, f"{w['slice']}/{w['host']}")

        workers.sort(key=order)
        return 200, {
            "name": name,
            "spec": job.get("spec", {}),
            "status": job.get("status", {}),
            "workers": workers,
        }

    def job_telemetry(self, ns: str, name: str) -> Tuple[int, Any]:
        """Training-plane telemetry for one TpuJob: step rate, MFU,
        recompile count, per-worker lag, straggler list, and the
        identity-derived trace id (docs/OBSERVABILITY.md).

        Live-first: the workers' beacon ConfigMaps are re-aggregated on
        every GET (fresher than the operator's last reconcile pass);
        the CR's ``status.telemetry`` is the fallback when the beacons
        are unreadable — same builder (`obs.steps.telemetry_view`) both
        places, so the shapes cannot drift."""
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )
        from kubeflow_tpu.obs.steps import (
            read_beacons,
            telemetry_view,
            tpujob_trace_ids,
        )
        from kubeflow_tpu.operators.tpujob import TpuJobSpec

        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND, ns, name)
        if job is None:
            return 404, {"error": f"tpujob {name!r} not found"}
        status = job.get("status", {}) or {}
        try:
            spec = TpuJobSpec.from_dict(job.get("spec", {}))
            straggler_k = spec.straggler_steps
            max_workers: Optional[int] = spec.num_workers
        except ValueError:
            from kubeflow_tpu.obs.steps import DEFAULT_STRAGGLER_STEPS

            straggler_k = DEFAULT_STRAGGLER_STEPS
            max_workers = None
        try:
            # world-size filter: an elastic downsize leaves departed
            # workers' last beacons behind until the operator GCs them
            beacons = read_beacons(self.client, ns, name,
                                   max_workers=max_workers)
        except ApiError:
            beacons = {}
        if beacons:
            view = telemetry_view(beacons, straggler_k)
        else:
            # no beacons visible: the operator's last aggregation, else
            # the empty view (keys always present for UI/consumers)
            view = (dict(status.get("telemetry") or {})
                    or telemetry_view({}, straggler_k))
        trace_id, _ = tpujob_trace_ids(
            ns, name, job.get("metadata", {}).get("uid", ""))
        # the hbm block rides the shared view builder; a CR status
        # aggregated by a pre-watermark operator lacks the key, so the
        # fallback path backfills the empty shape (keys always present)
        if "hbm" not in view:
            from kubeflow_tpu.obs.steps import _hbm_view

            view["hbm"] = _hbm_view({})
        resize = dict(status.get("resize") or {})
        from kubeflow_tpu.obs import goodput as gp

        return 200, {
            "name": name,
            "namespace": ns,
            "phase": status.get("phase", "Pending"),
            "restarts": status.get("restarts", 0),
            # compile summary (docs/OBSERVABILITY.md "Compile &
            # memory"): event-sourced count/seconds so the tuning
            # harvester and autoscaler read the startup tax without a
            # second endpoint (the full breakdown lives at
            # /api/jobs/<ns>/<name>/profile)
            "compile": self._compile_summary(ns, name),
            # efficiency summary (docs/OBSERVABILITY.md "Goodput"): the
            # productive fraction of the job's wall clock, inline so
            # the tuning objective harvester can prefer efficient
            # trials without a second endpoint (the full timeline lives
            # at /api/jobs/<ns>/<name>/goodput)
            "goodput": {"fraction": round(gp.goodput_fraction(
                status.get("goodput")), 6)},
            # elastic-resize visibility (docs/ELASTIC.md): how many
            # resizes this run survived, whether one is in flight, and
            # the step it resumed from (kftpu_job_resizes_total is the
            # fleet-level twin in the metrics registry/tsdb)
            "resizes": {
                "count": int(resize.get("count", 0) or 0),
                "inProgress": bool(resize.get("requested")),
                "direction": resize.get("direction"),
                "lastCheckpointStep": resize.get("lastCheckpointStep"),
            },
            "traceId": trace_id,
            **view,
        }

    def _compile_summary(self, ns: str, name: str) -> Dict[str, Any]:
        """``compile.{count,seconds}`` for one job: the scraped
        ``kftpu_compile_seconds`` histogram through the tsdb (sum
        across its per-module series), else the in-process xprof
        totals — the all-in-one-process tier."""
        count = 0.0
        seconds = 0.0
        found = False
        if self.tsdb is not None:
            try:
                for _labels, p in self.tsdb.latest(
                        "kftpu_compile_seconds_count",
                        {"namespace": ns, "job": name}):
                    count += p.value
                    found = True
                for _labels, p in self.tsdb.latest(
                        "kftpu_compile_seconds_sum",
                        {"namespace": ns, "job": name}):
                    seconds += p.value
            except Exception:  # noqa: BLE001 — telemetry view never 500s
                log.debug("tsdb compile read failed", exc_info=True)
        if not found:
            from kubeflow_tpu.obs import xprof

            totals = xprof.job_compile_totals(ns, name)
            count = float(totals.get("count", 0) or 0)
            seconds = float(totals.get("seconds", 0.0) or 0.0)
        return {"count": int(count), "seconds": round(seconds, 6)}

    def job_profile(self, ns: str, name: str) -> Tuple[int, Any]:
        """The compile & memory profile of one TpuJob
        (docs/OBSERVABILITY.md "Compile & memory"): the event-sourced
        compile summary with its per-module/shape-class breakdown,
        the static ``memory_analysis`` budgets recorded beside each
        HLO fingerprint, the gang's live HBM watermark, and the
        goodput ledger's measured compile states — the price tag the
        ROADMAP's compile-cache item is adjudicated against."""
        from kubeflow_tpu.manifests.components.tpujob_operator import (
            API_VERSION,
            TPUJOB_KIND,
        )
        from kubeflow_tpu.obs import xprof
        from kubeflow_tpu.obs.steps import (
            _hbm_view,
            read_beacons,
            tpujob_trace_ids,
        )

        job = self.client.get_or_none(API_VERSION, TPUJOB_KIND, ns, name)
        if job is None:
            return 404, {"error": f"tpujob {name!r} not found"}
        status = job.get("status", {}) or {}
        trace_id, _ = tpujob_trace_ids(
            ns, name, job.get("metadata", {}).get("uid", ""))

        compile_block = self._compile_summary(ns, name)
        series: List[Dict[str, Any]] = []
        hbm_series: List[Dict[str, Any]] = []
        if self.tsdb is not None:
            try:
                for labels, p in self.tsdb.latest(
                        "kftpu_compile_seconds_sum",
                        {"namespace": ns, "job": name}):
                    series.append({"labels": dict(labels),
                                   "seconds": round(p.value, 6)})
                for labels, p in self.tsdb.latest(
                        "kftpu_hbm_bytes",
                        {"namespace": ns, "job": name}):
                    hbm_series.append({"labels": dict(labels),
                                       "bytes": p.value})
            except Exception:  # noqa: BLE001
                log.debug("tsdb profile read failed", exc_info=True)
        series.sort(key=lambda r: sorted(r["labels"].items()))
        hbm_series.sort(key=lambda r: sorted(r["labels"].items()))
        if series:
            compile_block["series"] = series

        # the gang's live watermark, beacon-first (fresher than any
        # scrape), the scraped gauge series as the fallback shape
        try:
            beacons = read_beacons(self.client, ns, name)
        except ApiError:
            beacons = {}
        hbm = _hbm_view(beacons)
        g = status.get("goodput") or {}
        secs = g.get("seconds") or {}
        return 200, {
            "name": name,
            "namespace": ns,
            "phase": status.get("phase", "Pending"),
            "traceId": trace_id,
            "compile": compile_block,
            "hbm": {**hbm, "series": hbm_series},
            # every fingerprint's predicted footprint (in-process; a
            # deployed fleet reads kftpu_hbm_budget_bytes instead)
            "budgets": xprof.budgets(),
            "goodput": {
                "startupCompileSeconds": round(
                    float(secs.get("startup_compile", 0.0) or 0.0), 6),
                "recompileSeconds": round(
                    float(secs.get("recompile", 0.0) or 0.0), 6),
            },
        }

    # -- studies (katib-ui parity) ----------------------------------------

    def studies(self, ns: str) -> List[Dict[str, Any]]:
        """Study list with trial counts + best objective — the katib-ui
        studies table (``/root/reference/kubeflow/katib/
        vizier.libsonnet:429-455`` deploys the UI this replaces)."""
        from kubeflow_tpu.tuning.study import STUDY_API_VERSION, STUDY_KIND

        out = []
        for s in self.client.list(STUDY_API_VERSION, STUDY_KIND, ns):
            spec, status = s.get("spec", {}), s.get("status", {})
            objective = spec.get("objective", {}) or {}
            algorithm = spec.get("algorithm", {}) or {}
            out.append({
                "name": s["metadata"]["name"],
                "algorithm": algorithm.get("name", "random"),
                "objective": objective.get("metric", ""),
                "direction": objective.get("type", "maximize"),
                "phase": status.get("phase", "Pending"),
                "trials": status.get("trials", 0),
                "trialsRunning": status.get("trialsRunning", 0),
                "bestTrial": status.get("bestTrial"),
            })
        out.sort(key=lambda s: s["name"])
        return out

    def study_detail(self, ns: str, name: str) -> Tuple[int, Any]:
        """Study + its trials (params, phase, objective) — the data behind
        an objective-vs-trial curve."""
        from kubeflow_tpu.tuning.study import (
            STUDY_API_VERSION,
            STUDY_KIND,
            STUDY_LABEL,
            TRIAL_KIND,
        )

        study = self.client.get_or_none(STUDY_API_VERSION, STUDY_KIND, ns,
                                        name)
        if study is None:
            return 404, {"error": f"study {name!r} not found"}
        spec = study.get("spec", {})
        objective = spec.get("objective", {}) or {}
        trials = []
        for t in self.client.list(STUDY_API_VERSION, TRIAL_KIND, ns):
            labels = t.get("metadata", {}).get("labels", {}) or {}
            if labels.get(STUDY_LABEL) != name:
                continue
            status = t.get("status", {})
            obs = status.get("observation", {}) or {}
            trials.append({
                "name": t["metadata"]["name"],
                "index": t.get("spec", {}).get("index", 0),
                "parameters": t.get("spec", {}).get("parameters", {}),
                "phase": status.get("phase", "Pending"),
                "objective": obs.get(objective.get("metric", "")),
            })
        trials.sort(key=lambda t: (t["index"], t["name"]))
        return 200, {
            "name": name,
            "objective": objective.get("metric", ""),
            "direction": objective.get("type", "maximize"),
            "spec": spec,
            "status": study.get("status", {}),
            "trials": trials,
        }

    # -- workflow runs (KFP runs-page parity) -----------------------------

    def runs(self, ns: str) -> List[Dict[str, Any]]:
        """Live Workflow CRs merged with the persisted run archive, so
        history survives CR deletion (KFP api-server runs list,
        ``/root/reference/kubeflow/pipeline/pipeline-apiserver.libsonnet``)."""
        from kubeflow_tpu.workflows.workflow import (
            WORKFLOW_API_VERSION,
            WORKFLOW_KIND,
        )

        by_key: Dict[Tuple[str, str], Dict[str, Any]] = {}
        if self.run_archive is not None:
            for rec in self.run_archive.list(ns):
                rec["live"] = False
                by_key[(rec["name"], rec.get("uid", ""))] = rec
        for wf in self.client.list(WORKFLOW_API_VERSION, WORKFLOW_KIND, ns):
            md, status = wf.get("metadata", {}), wf.get("status", {})
            nodes = status.get("nodes", {}) or {}
            by_key[(md.get("name", ""), md.get("uid", ""))] = {
                "name": md.get("name", ""),
                "uid": md.get("uid", ""),
                "phase": status.get("phase", "Pending"),
                "startedAt": status.get("startedAt", ""),
                "finishedAt": status.get("finishedAt", ""),
                "steps": len(nodes),
                "succeededSteps": sum(1 for n in nodes.values()
                                      if n.get("phase") == "Succeeded"),
                "live": True,
            }
        out = list(by_key.values())
        out.sort(key=lambda r: r.get("startedAt", ""), reverse=True)
        return out

    def run_detail(self, ns: str, name: str) -> Tuple[int, Any]:
        from kubeflow_tpu.workflows.workflow import (
            WORKFLOW_API_VERSION,
            WORKFLOW_KIND,
        )

        wf = self.client.get_or_none(WORKFLOW_API_VERSION, WORKFLOW_KIND,
                                     ns, name)
        if wf is None and self.run_archive is not None:
            rec = self.run_archive.get(ns, name)
            if rec is not None:
                return 200, {"name": name, "live": False,
                             "spec": rec.get("spec", {}),
                             "status": rec.get("status", {}),
                             "artifacts": self._artifact_list(ns, name)}
        if wf is None:
            return 404, {"error": f"run {name!r} not found"}
        return 200, {"name": name, "live": True,
                     "spec": wf.get("spec", {}),
                     "status": wf.get("status", {}),
                     "artifacts": self._artifact_list(ns, name)}

    def _artifact_list(self, ns: str, run: str) -> List[Dict[str, Any]]:
        if self.artifact_store is None:
            return []
        return self.artifact_store.list(ns, run)

    def artifacts(self, ns: str, run: str) -> Tuple[int, Any]:
        return 200, self._artifact_list(ns, run)

    def artifact_download(self, ns: str, run: str, step: str,
                          name: str) -> Tuple[int, Any]:
        """Raw artifact bytes (the MinIO-console role, one GET)."""
        from kubeflow_tpu.utils.jsonhttp import RawResponse

        if self.artifact_store is None:
            return 404, {"error": "no artifact store configured"}
        path = self.artifact_store.path(ns, run, step, name)
        if not os.path.isfile(path):
            return 404, {"error": f"artifact {step}/{name} not found"}
        import mimetypes

        ctype = mimetypes.guess_type(name)[0] or "application/octet-stream"
        # streamed from disk: checkpoints are GB-scale
        return 200, RawResponse(ctype, path=path, download_name=name)

    def applications(self, ns: str) -> List[Dict[str, Any]]:
        """Aggregated platform health: the Application CRs' status (the
        one-look 'is the stack healthy' panel; reference concept:
        ``/root/reference/kubeflow/application/application.libsonnet``)."""
        from kubeflow_tpu.operators.application import (
            API_VERSION as APP_API,
            APPLICATION_KIND,
        )

        out = []
        for app in self.client.list(APP_API, APPLICATION_KIND, ns):
            status = app.get("status", {}) or {}
            failing = [c for c in status.get("components", [])
                       if not c.get("ready")]
            out.append({
                "name": app["metadata"]["name"],
                "phase": status.get("phase", "Unknown"),
                "ready": status.get("ready", "—"),
                "failing": [f"{c['kind']}/{c['name']}" for c in failing[:8]],
            })
        return out

    def dashboard_links(self) -> List[Dict[str, str]]:
        """The iframe cards the UI shell embeds (iframe-link.js parity)."""
        return [
            # /jupyter/ is the gateway's prefix-stripped route to the
            # notebook web app (reference mounts jupyter-web-app the same
            # way); studies/runs are dashboard-served pages over the
            # /api/studies + /api/runs routes
            {"text": "Notebooks", "link": "/jupyter/", "icon": "book"},
            {"text": "TPU Jobs", "link": "/tpujobs.html",
             "icon": "donut-large"},
            {"text": "Studies (HP tuning)", "link": "/studies.html",
             "icon": "tune"},
            {"text": "Workflow Runs", "link": "/runs.html",
             "icon": "device-hub"},
            {"text": "Model Serving", "link": "/serving/",
             "icon": "cloud-upload"},
            {"text": "Model Registry", "link": "/models.html",
             "icon": "collections-bookmark"},
            {"text": "TensorBoard", "link": "/tensorboard/",
             "icon": "timeline"},
            {"text": "Manage Contributors", "link": "/workgroup/",
             "icon": "people"},
        ]


def main() -> None:
    import os

    from kubeflow_tpu.k8s.client import HttpKubeClient

    from kubeflow_tpu.auth.gatekeeper import authenticator_from_env
    from kubeflow_tpu.workflows.archive import ArtifactStore, RunArchive

    api = DashboardApi(HttpKubeClient(), run_archive=RunArchive.from_env(),
                       artifact_store=ArtifactStore.from_env())
    serve_json(api.handle,
               int(os.environ.get("KFTPU_DASHBOARD_PORT", "8082")),
               authenticator=authenticator_from_env(),
               static_dir=os.path.join(os.path.dirname(__file__), "static"))


if __name__ == "__main__":
    main()
