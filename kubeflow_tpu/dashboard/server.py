"""Dashboard REST API: env-info, namespaces, activities, metrics, workgroup.

Route parity with the reference's Express server
(``/root/reference/components/centraldashboard/app/api.ts:78-150``):

- ``GET /api/env-info``            — platform + namespaces + user
- ``GET /api/namespaces``          — namespace list
- ``GET /api/activities/<ns>``     — k8s Events, newest first (api.ts:131-136)
- ``GET /api/metrics/<type>``      — behind a swappable MetricsService
  (``metrics_service_factory.ts``; Stackdriver impl swapped for one
  reading the framework's own Prometheus registry)
- ``GET /api/workgroup/exists``    — profile/workgroup flow via kfam
  (``api_workgroup.ts``)
- ``GET /api/dashboard-links``     — component cards for the UI shell
"""

from __future__ import annotations

import abc
from typing import Any, Dict, List, Optional, Tuple

import kubeflow_tpu
from kubeflow_tpu.k8s.client import ApiError, KubeClient
from kubeflow_tpu.tenancy.kfam import AccessManagementApi
from kubeflow_tpu.tenancy.profiles import PROFILE_API_VERSION, PROFILE_KIND
from kubeflow_tpu.utils import DEFAULT_REGISTRY
from kubeflow_tpu.utils.jsonhttp import serve_json


class MetricsService(abc.ABC):
    """Swappable metrics backend (reference MetricsService interface)."""

    @abc.abstractmethod
    def query(self, metric_type: str) -> List[Dict[str, Any]]: ...


class RegistryMetricsService(MetricsService):
    """Serves the framework's own registry instead of Stackdriver."""

    PREFIXES = {
        "podcpu": "kftpu_",          # closest equivalents by prefix
        "podmem": "kftpu_",
        "cluster": "kftpu_",
    }

    def __init__(self, registry=DEFAULT_REGISTRY) -> None:
        self.registry = registry

    def query(self, metric_type: str) -> List[Dict[str, Any]]:
        prefix = self.PREFIXES.get(metric_type, metric_type)
        out = []
        for line in self.registry.expose().splitlines():
            if line.startswith("#") or not line.strip():
                continue
            name, _, value = line.rpartition(" ")
            if name.startswith(prefix):
                out.append({"metric": name, "value": float(value)})
        return out


class DashboardApi:
    """Pure handle() route table served via the shared JSON scaffold."""

    def __init__(self, client: KubeClient, *,
                 metrics: Optional[MetricsService] = None,
                 kfam: Optional[AccessManagementApi] = None,
                 platform: str = "gcp-tpu") -> None:
        self.client = client
        self.metrics = metrics or RegistryMetricsService()
        self.kfam = kfam or AccessManagementApi(client)
        self.platform = platform

    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str = "") -> Tuple[int, Any]:
        try:
            if method != "GET":
                return 405, {"error": "dashboard API is read-only"}
            if path == "/api/env-info":
                return 200, self.env_info(user)
            if path == "/api/namespaces":
                return 200, self.namespaces()
            if path.startswith("/api/activities/"):
                return 200, self.activities(path.rsplit("/", 1)[1])
            if path.startswith("/api/metrics/"):
                return 200, self.metrics.query(path.rsplit("/", 1)[1])
            if path == "/api/workgroup/exists":
                return 200, self.workgroup_exists(user)
            if path == "/api/dashboard-links":
                return 200, self.dashboard_links()
            return 404, {"error": f"no route {path}"}
        except ApiError as e:
            return e.code, {"error": e.message}

    # -- handlers ----------------------------------------------------------

    def env_info(self, user: str) -> Dict[str, Any]:
        return {
            "user": user or "anonymous",
            "platform": {"kind": self.platform,
                         "version": kubeflow_tpu.__version__},
            "namespaces": [n["name"] for n in self.namespaces()],
            "isClusterAdmin": self.kfam.is_cluster_admin(user),
        }

    def namespaces(self) -> List[Dict[str, str]]:
        out = []
        for ns in self.client.list("v1", "Namespace"):
            md = ns.get("metadata", {})
            out.append({"name": md.get("name", ""),
                        "owner": (md.get("annotations", {}) or {})
                        .get("owner", "")})
        return out

    def activities(self, ns: str) -> List[Dict[str, Any]]:
        events = self.client.list("v1", "Event", ns)
        events.sort(key=lambda e: e.get("lastTimestamp", ""), reverse=True)
        return [{
            "time": e.get("lastTimestamp", ""),
            "type": e.get("type", "Normal"),
            "reason": e.get("reason", ""),
            "message": e.get("message", ""),
            "object": (e.get("involvedObject", {}) or {}).get("name", ""),
        } for e in events]

    def workgroup_exists(self, user: str) -> Dict[str, Any]:
        profiles = self.client.list(PROFILE_API_VERSION, PROFILE_KIND)
        owned = []
        for p in profiles:
            owner = p.get("spec", {}).get("owner", {})
            name = owner.get("name") if isinstance(owner, dict) else owner
            if name == user:
                owned.append(p["metadata"]["name"])
        return {"hasWorkgroup": bool(owned), "workgroups": owned}

    def dashboard_links(self) -> List[Dict[str, str]]:
        """The iframe cards the UI shell embeds (iframe-link.js parity)."""
        return [
            # /jupyter/ is the gateway's prefix-stripped route to the
            # notebook web app (reference mounts jupyter-web-app the same
            # way); the other links are iframe placeholders until their
            # routes land
            {"text": "Notebooks", "link": "/jupyter/", "icon": "book"},
            {"text": "TPU Jobs", "link": "/tpujobs/", "icon": "donut-large"},
            {"text": "Studies (HP tuning)", "link": "/tuning/",
             "icon": "tune"},
            {"text": "Workflows", "link": "/workflows/",
             "icon": "device-hub"},
            {"text": "Model Serving", "link": "/serving/",
             "icon": "cloud-upload"},
            {"text": "Manage Contributors", "link": "/workgroup/",
             "icon": "people"},
        ]


def main() -> None:
    import os

    from kubeflow_tpu.k8s.client import HttpKubeClient

    from kubeflow_tpu.auth.gatekeeper import authenticator_from_env

    api = DashboardApi(HttpKubeClient())
    serve_json(api.handle,
               int(os.environ.get("KFTPU_DASHBOARD_PORT", "8082")),
               authenticator=authenticator_from_env(),
               static_dir=os.path.join(os.path.dirname(__file__), "static"))


if __name__ == "__main__":
    main()
