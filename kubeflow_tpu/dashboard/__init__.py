"""Central dashboard backend.

Reference: ``/root/reference/components/centraldashboard/`` — an Express
(TS) server with REST routes (``app/api.ts:78-150``), a swappable metrics
service (``app/metrics_service.ts`` + ``stackdriver_metrics_service.ts``
behind ``metrics_service_factory.ts``), and workgroup flows through kfam
(``app/api_workgroup.ts``).
"""

from kubeflow_tpu.dashboard.server import (  # noqa: F401
    DashboardApi,
    MetricsService,
    RegistryMetricsService,
)
