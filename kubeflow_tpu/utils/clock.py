"""The platform-wide injectable-clock contract.

Every control loop takes ``clock: Optional[Clock] = None`` and defaults
it to the real clock BY REFERENCE (``self.clock = clock if clock is not
None else time.monotonic``) — never call time.time()/time.sleep()
inline. The convention was set by :mod:`kubeflow_tpu.autoscale` and is
enforced repo-wide by tpulint rule TPU003 (docs/ANALYSIS.md).

Lives in utils so bench/workflows/operators can type against it without
importing the autoscale subsystem; :mod:`kubeflow_tpu.autoscale.policy`
re-exports both names.
"""

from __future__ import annotations

from typing import Callable

Clock = Callable[[], float]
# its companion for poll loops: an injectable sleep(seconds)
Sleep = Callable[[float], None]
