"""Shared JSON-over-HTTP server scaffold for the platform's web services.

One implementation of the dispatch/serve shape used by the notebook web
app, kfam, and the suggestion service (the reference runs three separate
Flask/go-kit/gRPC stacks for these; here they share one stdlib server).
"""

from __future__ import annotations

import inspect
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

USER_HEADER = "X-Kubeflow-Userid"  # identity header the platform trusts

MAX_BODY_BYTES = 4 << 20  # reject absurd request bodies before parsing

# handle(method, path, body, user) -> (status_code, json_payload);
# a handler declaring a 5th parameter also receives the request headers
# (needed by e.g. the gatekeeper's cookie-based /verify)
Handle = Callable[[str, str, Optional[Dict[str, Any]], str], Tuple[int, Any]]

# authenticator(headers) -> verified username or None (reject). When one is
# configured, the verified identity REPLACES the client-supplied user header
# — otherwise any in-cluster pod can spoof an admin by setting the header
# (kfam applies RoleBindings, bootstrap drives cluster-wide applies).
Authenticator = Callable[[Dict[str, str]], Optional[str]]


def _wants_headers(handle: Handle) -> bool:
    try:
        return len(inspect.signature(handle).parameters) >= 5
    except (TypeError, ValueError):
        return False


def serve_json(handle: Handle, port: int, *,
               background: bool = False,
               host: str = "0.0.0.0",
               authenticator: Optional[Authenticator] = None,
               ) -> Optional[ThreadingHTTPServer]:
    pass_headers = _wants_headers(handle)

    class Handler(BaseHTTPRequestHandler):
        def _dispatch(self, method: str) -> None:
            try:
                length = int(self.headers.get("Content-Length", "0"))
            except ValueError:
                length = 0
            if length > MAX_BODY_BYTES:
                code, payload = 413, {"log": "request body too large"}
            else:
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    body = {}
                user = self.headers.get(USER_HEADER, "")
                if authenticator is not None:
                    verified = authenticator(dict(self.headers))
                    if verified is None:
                        self._reply(401, {"log": "authentication required"})
                        return
                    user = verified
                try:
                    if pass_headers:
                        code, payload = handle(method, self.path, body, user,
                                               dict(self.headers))
                    else:
                        code, payload = handle(method, self.path, body, user)
                except Exception as e:  # noqa: BLE001 — a server never dies
                    code, payload = 500, {"log": f"internal error: {e}"}
            self._reply(code, payload)

        def _reply(self, code: int, payload: Any) -> None:
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

        def do_PUT(self):  # noqa: N802
            self._dispatch("PUT")

        def do_DELETE(self):  # noqa: N802
            self._dispatch("DELETE")

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    if background:
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv
    srv.serve_forever()
    return None
