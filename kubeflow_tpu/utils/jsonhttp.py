"""Shared JSON-over-HTTP server scaffold for the platform's web services.

One implementation of the dispatch/serve shape used by the notebook web
app, kfam, and the suggestion service (the reference runs three separate
Flask/go-kit/gRPC stacks for these; here they share one stdlib server).
"""

from __future__ import annotations

import inspect
import json
import mimetypes
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

USER_HEADER = "X-Kubeflow-Userid"  # identity header the platform trusts

MAX_BODY_BYTES = 4 << 20  # reject absurd request bodies before parsing

# static assets the login flow itself needs — served without a session
# cookie even when an authenticator is configured
PUBLIC_STATIC = frozenset({"login.html", "style.css"})

# handle(method, path, body, user) -> (status_code, json_payload);
# a handler declaring a 5th parameter also receives the request headers
# (needed by e.g. the gatekeeper's cookie-based /verify)
Handle = Callable[[str, str, Optional[Dict[str, Any]], str], Tuple[int, Any]]

# authenticator(headers) -> verified username or None (reject). When one is
# configured, the verified identity REPLACES the client-supplied user header
# — otherwise any in-cluster pod can spoof an admin by setting the header
# (kfam applies RoleBindings, bootstrap drives cluster-wide applies).
Authenticator = Callable[[Dict[str, str]], Optional[str]]


class RawResponse:
    """A handler may return this instead of a JSON payload to serve raw
    bytes (artifact downloads): ``(code, RawResponse(ctype, data))`` or,
    for large files, ``RawResponse(ctype, path=...)`` — the server then
    streams from disk instead of buffering the file (multi-GB training
    checkpoints must not be held in the dashboard's memory)."""

    def __init__(self, content_type: str, data: Optional[bytes] = None,
                 download_name: Optional[str] = None,
                 path: Optional[str] = None) -> None:
        if (data is None) == (path is None):
            raise ValueError("exactly one of data/path is required")
        self.content_type = content_type
        self.data = data
        self.path = path
        self.download_name = download_name


def _wants_headers(handle: Handle) -> bool:
    try:
        return len(inspect.signature(handle).parameters) >= 5
    except (TypeError, ValueError):
        return False


def serve_json(handle: Handle, port: int, *,
               background: bool = False,
               host: str = "0.0.0.0",
               authenticator: Optional[Authenticator] = None,
               static_dir: Optional[str] = None,
               ) -> Optional[ThreadingHTTPServer]:
    """``static_dir`` also serves a browser frontend: GET paths outside
    ``/api`` resolve to files under it (``/`` → ``index.html``), giving the
    UI and its API one origin — the reference splits these across an
    Express static server + API routes (centraldashboard ``app/api.ts``).

    With an ``authenticator`` configured, static files are auth-gated like
    everything else except the login flow's own assets (PUBLIC_STATIC) —
    otherwise the login page would be unreachable and the flow dead-ends.
    """
    pass_headers = _wants_headers(handle)

    class Handler(BaseHTTPRequestHandler):
        def _try_static(self, path: str, authenticated: bool) -> bool:
            if static_dir is None or path.startswith("/api"):
                return False
            rel = path.lstrip("/") or "index.html"
            if not authenticated and rel not in PUBLIC_STATIC:
                return False
            full = os.path.realpath(os.path.join(static_dir, rel))
            # stay inside static_dir (no ../ escapes)
            if not full.startswith(os.path.realpath(static_dir) + os.sep):
                return False
            if os.path.isdir(full):
                full = os.path.join(full, "index.html")
            if not os.path.isfile(full):
                return False
            ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
            with open(full, "rb") as f:
                data = f.read()
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
            return True

        def _dispatch(self, method: str) -> None:
            verified: Optional[str] = None
            if authenticator is not None:
                verified = authenticator(dict(self.headers))
            clean_path = self.path.split("?")[0]
            if method == "GET" and self._try_static(
                    clean_path,
                    authenticated=authenticator is None or verified is not None):
                return
            if (authenticator is not None and verified is None
                    and method == "GET" and static_dir is not None
                    and not clean_path.startswith("/api")
                    and clean_path.lstrip("/") not in PUBLIC_STATIC):
                # browser page load without a session: send the human to the
                # login page instead of a bare JSON 401
                self.send_response(302)
                self.send_header("Location", "/login.html?next=" + clean_path)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            try:
                length = int(self.headers.get("Content-Length", "0"))
            except ValueError:
                length = 0
            if length > MAX_BODY_BYTES:
                code, payload = 413, {"log": "request body too large"}
            else:
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except ValueError:
                    body = {}
                user = self.headers.get(USER_HEADER, "")
                if authenticator is not None:
                    if verified is None:
                        self._reply(401, {"log": "authentication required"})
                        return
                    user = verified
                try:
                    if pass_headers:
                        code, payload = handle(method, self.path, body, user,
                                               dict(self.headers))
                    else:
                        code, payload = handle(method, self.path, body, user)
                except Exception as e:  # noqa: BLE001 — a server never dies
                    code, payload = 500, {"log": f"internal error: {e}"}
            self._reply(code, payload)

        def _reply(self, code: int, payload: Any) -> None:
            if isinstance(payload, RawResponse):
                size = (len(payload.data) if payload.data is not None
                        else os.path.getsize(payload.path))
                self.send_response(code)
                self.send_header("Content-Type", payload.content_type)
                self.send_header("Content-Length", str(size))
                if payload.download_name:
                    self.send_header(
                        "Content-Disposition",
                        f'attachment; filename="{payload.download_name}"')
                self.end_headers()
                if payload.data is not None:
                    self.wfile.write(payload.data)
                else:
                    import shutil

                    with open(payload.path, "rb") as f:
                        shutil.copyfileobj(f, self.wfile, 1 << 20)
                return
            data = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def do_GET(self):  # noqa: N802
            self._dispatch("GET")

        def do_POST(self):  # noqa: N802
            self._dispatch("POST")

        def do_PUT(self):  # noqa: N802
            self._dispatch("PUT")

        def do_DELETE(self):  # noqa: N802
            self._dispatch("DELETE")

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer((host, port), Handler)
    if background:
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv
    srv.serve_forever()
    return None
