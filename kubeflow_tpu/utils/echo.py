"""Request-echo service: reflects method/path/headers/body as JSON.

The in-container side of the ``echo-server`` component (reference:
``/root/reference/kubeflow/common/echo-server.libsonnet`` runs an
external echo image; here the framework serves its own). Point an edge
route or Istio VirtualService at it to see exactly what a backend
receives — prefix stripping, auth headers, websocket upgrade attempts.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

from kubeflow_tpu.utils.jsonhttp import serve_json


class EchoService:
    def handle(self, method: str, path: str, body: Optional[Dict[str, Any]],
               user: str, headers: Dict[str, str]) -> Tuple[int, Any]:
        if path == "/healthz":
            return 200, {"ok": True}
        return 200, {
            "method": method,
            "path": path,
            "user": user or None,
            "headers": dict(headers),
            "body": body,
        }


def main() -> None:  # pragma: no cover - container entrypoint
    serve_json(EchoService().handle,
               int(os.environ.get("KFTPU_ECHO_PORT", "8080")))


if __name__ == "__main__":  # pragma: no cover
    main()
