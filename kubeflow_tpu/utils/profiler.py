"""XLA/JAX profiler trace capture — the observability tier SURVEY §5 names.

The reference's observability stops at Prometheus scrape annotations on
the operator pods (``/root/reference/kubeflow/tf-training/
tf-job-operator.libsonnet:180-184``); it has no kernel-level tracing at
all. On TPU the profiler is the difference between guessing and knowing
where a step's time goes (MXU idle vs HBM-bound vs host-bound), so trace
capture is first-class here:

- :func:`trace` — context manager around any block; writes a TensorBoard-
  loadable trace directory (``plugins/profile/...``).
- :class:`StepProfiler` — capture a step window ``[start, stop)`` inside a
  training loop, driven by env (``KFTPU_PROFILE_DIR``,
  ``KFTPU_PROFILE_START``, ``KFTPU_PROFILE_STEPS``) so the operator can
  switch it on for any job without code changes.
- annotations re-exported (``annotate``/``TraceAnnotation``) so runtime
  phases (data load, step, checkpoint) show up as named spans on the
  trace's host timeline.

The captured directory is what the TensorBoard component
(``kubeflow_tpu/manifests/components/tensorboard.py``) points at.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
from typing import Iterator, Optional

from kubeflow_tpu.utils.clock import Clock

log = logging.getLogger(__name__)

ENV_PROFILE_DIR = "KFTPU_PROFILE_DIR"
ENV_PROFILE_START = "KFTPU_PROFILE_START"
ENV_PROFILE_STEPS = "KFTPU_PROFILE_STEPS"


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a device+host trace of the enclosed block into ``logdir``."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        log.info("profiler trace written to %s", logdir)


def annotate(name: str):
    """Named span on the profiler's host timeline (no-op cost when idle)."""
    import jax

    return jax.profiler.TraceAnnotation(name)


class StepProfiler:
    """Captures steps ``[start, start+n)`` of a training loop.

    Call :meth:`step` once per loop iteration with the global step number;
    the profiler starts/stops the trace on the right boundaries. Inactive
    (no logdir) it costs one integer compare per step.

    >>> prof = StepProfiler.from_env()          # or StepProfiler(dir, 10, 3)
    >>> for step in range(steps):
    ...     prof.step(step)
    ...     state, m = train_step(state, batch)
    >>> prof.close()                            # safety stop at loop exit

    ``clock`` follows the platform's injectable-Clock contract
    (:mod:`kubeflow_tpu.utils.clock`): the capture-window wall time it
    measures (``last_capture_s``) is what the step-telemetry layer
    subtracts so profiler overhead never reads as a straggling step.
    """

    def __init__(self, logdir: Optional[str], start: int = 10,
                 n_steps: int = 3, clock: Optional[Clock] = None) -> None:
        self.logdir = logdir
        self.start = start
        self.stop = start + n_steps
        self.clock: Clock = clock if clock is not None else time.monotonic
        self.last_capture_s: Optional[float] = None
        self._tracing = False
        self._t_start = 0.0

    @classmethod
    def from_env(cls, environ=None,
                 clock: Optional[Clock] = None) -> "StepProfiler":
        """Build from the operator's env contract.

        A malformed window int must never kill the worker at boot — a
        typo'd annotation would crash every pod in the gang before the
        first step. Warn and come up with profiling disabled instead.
        """
        env = os.environ if environ is None else environ
        logdir = env.get(ENV_PROFILE_DIR) or None
        window = {ENV_PROFILE_START: 10, ENV_PROFILE_STEPS: 3}
        for key, default in list(window.items()):
            raw = env.get(key)
            if raw is None or raw == "":
                continue
            try:
                window[key] = int(raw)
            except (TypeError, ValueError):
                log.warning(
                    "%s=%r is not an integer; profiling disabled for "
                    "this run", key, raw)
                logdir = None
        return cls(
            logdir,
            start=window[ENV_PROFILE_START],
            n_steps=window[ENV_PROFILE_STEPS],
            clock=clock,
        )

    @property
    def enabled(self) -> bool:
        return bool(self.logdir)

    def step(self, step: int) -> None:
        if not self.logdir:
            return
        import jax

        if not self._tracing and self.start <= step < self.stop:
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._tracing = True
            self._t_start = self.clock()
        elif self._tracing and step >= self.stop:
            jax.profiler.stop_trace()
            self._tracing = False
            self.last_capture_s = self.clock() - self._t_start
            log.info("profiler trace (steps %d..%d, %.3fs) written to %s",
                     self.start, self.stop - 1, self.last_capture_s,
                     self.logdir)

    def close(self) -> None:
        if self._tracing:
            import jax

            jax.profiler.stop_trace()
            self._tracing = False
            self.last_capture_s = self.clock() - self._t_start
            log.info("profiler trace (%.3fs) written to %s",
                     self.last_capture_s, self.logdir)
