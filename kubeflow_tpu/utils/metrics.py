"""Prometheus-style metrics: counters/gauges + text exposition + HTTP endpoint.

The reference exposes operator metrics via annotated Services scraped by
prometheus (``tf-job-operator.libsonnet:180-184``) and serves ``/metrics``
from the bootstrap server (``ksServer.go:906``). Here a minimal in-process
registry serves the same exposition format from stdlib HTTP.
"""

from __future__ import annotations

import http.server
import threading
from typing import Dict, Mapping, Optional, Tuple

_Label = Tuple[Tuple[str, str], ...]


class Metric:
    def __init__(self, name: str, help_: str, kind: str) -> None:
        self.name = name
        self.help = help_
        self.kind = kind
        self._values: Dict[_Label, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[Mapping[str, str]]) -> _Label:
        return tuple(sorted((labels or {}).items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + amount

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def expose(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                if key:
                    lbl = ",".join(f'{k}="{v}"' for k, v in key)
                    lines.append(f"{self.name}{{{lbl}}} {val}")
                else:
                    lines.append(f"{self.name} {val}")
        return "\n".join(lines)


class Registry:
    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Metric:
        return self._register(name, help_, "counter")

    def gauge(self, name: str, help_: str = "") -> Metric:
        return self._register(name, help_, "gauge")

    def _register(self, name: str, help_: str, kind: str) -> Metric:
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = Metric(name, help_, kind)
            return self._metrics[name]

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.expose() for m in metrics) + "\n"


DEFAULT_REGISTRY = Registry()


def serve_metrics(port: int, registry: Registry = DEFAULT_REGISTRY) -> threading.Thread:
    """Serve GET /metrics on a daemon thread; returns the thread."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            if self.path.rstrip("/") in ("", "/metrics", "/healthz"):
                body = (registry.expose() if "metrics" in self.path else "ok\n"
                        ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self.send_error(404)

        def log_message(self, *a):  # quiet
            pass

    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.server = server  # type: ignore[attr-defined]
    t.start()
    return t
