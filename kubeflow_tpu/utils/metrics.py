"""Prometheus-style metrics: counters/gauges + text exposition + HTTP endpoint.

The reference exposes operator metrics via annotated Services scraped by
prometheus (``tf-job-operator.libsonnet:180-184``) and serves ``/metrics``
from the bootstrap server (``ksServer.go:906``). Here a minimal in-process
registry serves the same exposition format from stdlib HTTP.
"""

from __future__ import annotations

import bisect
import http.server
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from kubeflow_tpu.utils.clock import Clock

_Label = Tuple[Tuple[str, str], ...]


def escape_label_value(value: str) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and line feed (in that order — escaping the escapes first).
    An unescaped ``"`` truncates the value mid-line and a raw newline
    splits one sample into two garbage lines, so a label value like a
    model path or an error message used to produce an exposition no
    parser (including our own scraper) could read back."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def format_labels(key: _Label) -> str:
    """``k1="v1",k2="v2"`` with values escaped per the text format."""
    return ",".join(f'{k}="{escape_label_value(v)}"' for k, v in key)


class Metric:
    def __init__(self, name: str, help_: str, kind: str) -> None:
        self.name = name
        self.help = help_
        self.kind = kind
        self._values: Dict[_Label, float] = {}
        self._lock = threading.Lock()

    def _key(self, labels: Optional[Mapping[str, str]]) -> _Label:
        return tuple(sorted((labels or {}).items()))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            key = self._key(labels)
            self._values[key] = self._values.get(key, 0.0) + amount

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def get(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def remove(self, **labels: str) -> None:
        """Drop one label row (no-op when absent). For per-object gauge
        series (per-job, per-model): the object is gone, so exporting
        its last value forever is a lie AND unbounded cardinality."""
        with self._lock:
            self._values.pop(self._key(labels), None)

    def expose(self, exemplars: bool = True) -> str:
        # ``exemplars`` is meaningful only for Histogram (exemplar
        # suffixes); accepted here so Registry can pass it uniformly
        del exemplars
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            for key, val in sorted(self._values.items()):
                if key:
                    lines.append(f"{self.name}{{{format_labels(key)}}} {val}")
                else:
                    lines.append(f"{self.name} {val}")
        return "\n".join(lines)


# Prometheus client-library default bounds: right for request latencies
# in seconds, overridable per histogram
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Train-step wall times: sub-10ms micro-steps through minutes-long
# recompile stalls — the request-latency bounds above top out at 10s and
# would fold every recompile into +Inf, exactly the tail a step-time
# histogram exists to resolve
STEP_TIME_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
    2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class Histogram(Metric):
    """Cumulative histogram: ``_bucket{le=...}``/``_sum``/``_count``
    exposition with configurable bounds. Buckets are stored per label
    set; exposition emits cumulative counts (each ``le`` bucket includes
    everything below it, ``+Inf`` equals ``_count``), the shape every
    Prometheus quantile function expects.

    ``observe(..., exemplar_trace_id=)`` keeps the *latest* observed
    (trace_id, value) per bucket — OpenMetrics exemplars — and
    exposition suffixes the bucket line with ``# {trace_id="..."} v``,
    so a latency bucket links straight to a trace of a request that
    landed in it (docs/OBSERVABILITY.md; the tsdb scraper round-trips
    the suffix)."""

    def __init__(self, name: str, help_: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, help_, "histogram")
        bounds = sorted(set(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        if bounds[-1] == float("inf"):
            bounds.pop()  # +Inf is implicit
        if not bounds:
            raise ValueError("histogram needs a finite bucket bound")
        self.bounds: Tuple[float, ...] = tuple(bounds)
        # per label set: per-bucket (non-cumulative) counts + [+Inf]
        self._counts: Dict[_Label, List[int]] = {}
        self._sums: Dict[_Label, float] = {}
        # per label set: bucket index -> latest (trace_id, value)
        self._exemplars: Dict[_Label, Dict[int, Tuple[str, float]]] = {}

    def observe(self, value: float,
                exemplar_trace_id: Optional[str] = None,
                **labels: str) -> None:
        key = self._key(labels)
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = self._counts[key] = [0] * (len(self.bounds) + 1)
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            if exemplar_trace_id:
                self._exemplars.setdefault(key, {})[idx] = (
                    str(exemplar_trace_id), float(value))

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        raise TypeError(f"histogram {self.name!r}: use observe(), not inc()")

    def set(self, value: float, **labels: str) -> None:
        raise TypeError(f"histogram {self.name!r}: use observe(), not set()")

    def get(self, **labels: str) -> float:
        """Observation count for the label set (the ``_count`` series)."""
        with self._lock:
            return float(sum(self._counts.get(self._key(labels), ())))

    def remove(self, **labels: str) -> None:
        """Drop one label row's buckets and sum (histogram storage)."""
        with self._lock:
            key = self._key(labels)
            self._counts.pop(key, None)
            self._sums.pop(key, None)
            self._exemplars.pop(key, None)

    def exemplars(self, **labels: str) -> Dict[str, Tuple[str, float]]:
        """Latest exemplar per bucket, keyed by ``le`` string."""
        with self._lock:
            ex = dict(self._exemplars.get(self._key(labels), {}))
        bounds = list(self.bounds) + [float("inf")]
        return {("+Inf" if i == len(self.bounds) else _fmt_bound(bounds[i])):
                v for i, v in ex.items()}

    def bucket_counts(self, **labels: str) -> Dict[str, int]:
        """Cumulative counts keyed by ``le`` string (tests/debugging)."""
        with self._lock:
            counts = list(self._counts.get(self._key(labels),
                                           [0] * (len(self.bounds) + 1)))
        out: Dict[str, int] = {}
        acc = 0
        for bound, n in zip(self.bounds, counts):
            acc += n
            out[_fmt_bound(bound)] = acc
        out["+Inf"] = acc + counts[-1]
        return out

    def sum(self, **labels: str) -> float:
        with self._lock:
            return self._sums.get(self._key(labels), 0.0)

    def time(self, clock: Optional[Clock] = None,
             **labels: str) -> "_HistogramTimer":
        """Context manager observing the enclosed block's wall time:
        ``with h.time(route="/x"): ...``. The clock is injectable (the
        TPU003 contract) and defaults to the real clock by reference;
        the elapsed value is observed on exit even when the block
        raises — failures are exactly the latencies worth keeping."""
        return _HistogramTimer(
            self, clock if clock is not None else time.monotonic, labels)

    def expose(self, exemplars: bool = True) -> str:
        """``exemplars=False`` omits the exemplar suffixes: they are a
        private extension of the 0.0.4 text format (OpenMetrics-style
        syntax, but this exposition is NOT spec-valid OpenMetrics — no
        ``# EOF``, counter families keep their ``_total`` name), and
        the classic Prometheus text parser errors on tokens after the
        value — one exemplar would make the whole target unscrapeable.
        HTTP endpoints emit them only to a scraper that explicitly
        requests the extension (:data:`EXEMPLARS_HEADER`)."""
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            items = sorted((k, list(v), self._sums.get(k, 0.0),
                            dict(self._exemplars.get(k, {})))
                           for k, v in self._counts.items())
        for key, counts, total, bucket_exemplars in items:
            base = format_labels(key)

            def bucket_line(idx: int, le: str, acc: int) -> str:
                lbl = (base + "," if base else "") + f'le="{le}"'
                line = f"{self.name}_bucket{{{lbl}}} {acc}"
                ex = bucket_exemplars.get(idx) if exemplars else None
                if ex is not None:
                    # OpenMetrics-style exemplar: `# {labels} v` suffix
                    line += (f' # {{trace_id="'
                             f'{escape_label_value(ex[0])}"}} {ex[1]}')
                return line

            acc = 0
            for i, (bound, n) in enumerate(zip(self.bounds, counts)):
                acc += n
                lines.append(bucket_line(i, _fmt_bound(bound), acc))
            acc += counts[-1]
            lines.append(bucket_line(len(self.bounds), "+Inf", acc))
            suffix = f"{{{base}}}" if base else ""
            lines.append(f"{self.name}_sum{suffix} {total}")
            lines.append(f"{self.name}_count{suffix} {acc}")
        return "\n".join(lines)


def _fmt_bound(b: float) -> str:
    """``0.005``/``1``/``2.5`` — no float noise in the ``le`` label."""
    return format(b, "g")


class _HistogramTimer:
    """The :meth:`Histogram.time` helper: one observation per ``with``
    block. ``elapsed`` stays readable after exit (tests/debugging)."""

    def __init__(self, hist: Histogram, clock: Clock,
                 labels: Mapping[str, str]) -> None:
        self._hist = hist
        self._clock = clock
        self._labels = dict(labels)
        self.elapsed: Optional[float] = None

    def __enter__(self) -> "_HistogramTimer":
        self._t0 = self._clock()
        return self

    def __exit__(self, *exc: object) -> bool:
        self.elapsed = self._clock() - self._t0
        self._hist.observe(self.elapsed, **self._labels)
        return False


class Registry:
    def __init__(self) -> None:
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Metric:
        return self._register(name, help_, "counter")

    def gauge(self, name: str, help_: str = "") -> Metric:
        return self._register(name, help_, "gauge")

    def histogram(self, name: str, help_: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._register(  # type: ignore[return-value]
            name, help_, "histogram",
            factory=lambda: Histogram(name, help_,
                                      buckets if buckets is not None
                                      else DEFAULT_BUCKETS))

    def _register(self, name: str, help_: str, kind: str,
                  factory=None) -> Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if existing.kind != kind:
                    # returning the existing metric under the wrong type
                    # would silently cross counter/gauge semantics (and
                    # histogram observe() would be missing entirely)
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {kind}")
                return existing
            self._metrics[name] = (factory() if factory is not None
                                   else Metric(name, help_, kind))
            return self._metrics[name]

    def expose(self, exemplars: bool = True) -> str:
        """In-process consumers (the tsdb sampler, tests) default to the
        exemplar-carrying shape; pass ``exemplars=False`` for a
        classic-0.0.4-safe exposition (what HTTP endpoints serve unless
        the scraper requests the extension)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return "\n".join(m.expose(exemplars=exemplars)
                         for m in metrics) + "\n"


DEFAULT_REGISTRY = Registry()

# the exemplar-extension request header: exemplar suffixes are NOT valid
# in either the classic 0.0.4 text format or (as emitted here) strict
# OpenMetrics, so HTTP endpoints send them only to a scraper explicitly
# asking for the extension — the in-process obs/scrape.Scraper does; a
# real Prometheus never does and always gets a clean 0.0.4 body. Accept
# negotiation is deliberately NOT used: Prometheus v2.x advertises
# application/openmetrics-text on every scrape, and answering with a
# not-quite-OpenMetrics body (no ``# EOF``) would fail its strict parser.
EXEMPLARS_HEADER = "X-Kftpu-Exemplars"

EXPOSITION_CONTENT_TYPE = "text/plain; version=0.0.4"


def wants_exemplars(headers: Mapping[str, str]) -> bool:
    """True when the request opts into the exemplar extension."""
    for k, v in headers.items():
        if str(k).lower() == EXEMPLARS_HEADER.lower():
            return str(v).strip().lower() in ("1", "true", "yes")
    return False


def exposition(registry: Registry,
               headers: Optional[Mapping[str, str]] = None
               ) -> Tuple[bytes, str]:
    """(body, content type) for an HTTP ``/metrics`` response — the ONE
    policy for every exposition endpoint (serve_metrics, the serving
    server, the trace collector): classic 0.0.4 unless the scraper
    requested the exemplar extension."""
    body = registry.expose(
        exemplars=wants_exemplars(headers or {})).encode()
    return body, EXPOSITION_CONTENT_TYPE


def serve_metrics(port: int, registry: Registry = DEFAULT_REGISTRY) -> threading.Thread:
    """Serve GET /metrics on a daemon thread; returns the thread."""

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            # exact-path routing: the old '"metrics" in path' substring
            # test served the exposition for /healthz?x=metrics and any
            # path merely containing "metrics"
            path = self.path.split("?")[0].rstrip("/") or "/"
            if path == "/metrics":
                body, ctype = exposition(registry, dict(self.headers))
            elif path in ("/", "/healthz"):
                body = b"ok\n"
                # a health probe is not a Prometheus exposition — no
                # exposition version suffix
                ctype = "text/plain"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.server = server  # type: ignore[attr-defined]
    t.start()
    return t
