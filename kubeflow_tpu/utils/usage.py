"""Anonymous usage reporting — spartakus-volunteer parity, opt-out.

Reference: ``/root/reference/kubeflow/common/spartakus.libsonnet`` deploys
a spartakus-volunteer with a random cluster uuid and node-reading RBAC,
gated by a ``reportUsage`` param. Here the reporter is a small in-repo
loop: it builds a report of {anonymous cluster id, framework version,
node count, TPU accelerator types} — never names, namespaces, images, or
workloads — and POSTs it to the configured collector. Disabled unless a
collector URL is configured, and removable by dropping the component
(`usage-reporting`) from the deployment config.
"""

from __future__ import annotations

import json
import logging
import os
import time
import urllib.request
import uuid
from typing import Any, Dict, Optional

import kubeflow_tpu
from kubeflow_tpu.k8s.client import ApiError, KubeClient

log = logging.getLogger(__name__)

ENV_COLLECTOR_URL = "KFTPU_USAGE_COLLECTOR_URL"
ENV_CLUSTER_ID = "KFTPU_USAGE_CLUSTER_ID"


def build_report(client: KubeClient, cluster_id: str,
                 now: Optional[float] = None) -> Dict[str, Any]:
    """The spartakus report shape: anonymous id + coarse cluster facts.

    ``now`` is the injectable epoch-seconds source (TPU003 contract;
    this was the baseline's last utils-layer raw clock)."""
    try:
        nodes = client.list("v1", "Node")
    except ApiError:
        nodes = []
    accelerators: Dict[str, int] = {}
    for n in nodes:
        labels = n.get("metadata", {}).get("labels", {}) or {}
        acc = labels.get("cloud.google.com/gke-tpu-accelerator")
        if acc:
            accelerators[acc] = accelerators.get(acc, 0) + 1
    return {
        "clusterID": cluster_id,
        "version": kubeflow_tpu.__version__,
        "nodes": len(nodes),
        "tpuAccelerators": accelerators,
        "timestamp": int(now if now is not None else time.time()),
    }


class UsageReporter:
    """Periodic anonymous report POSTs (the volunteer loop)."""

    def __init__(self, client: KubeClient, collector_url: str,
                 cluster_id: Optional[str] = None,
                 interval_s: float = 24 * 3600.0) -> None:
        self.client = client
        self.collector_url = collector_url
        self.cluster_id = cluster_id or str(uuid.uuid4())
        self.interval_s = interval_s

    def report_once(self, timeout_s: float = 10.0) -> bool:
        payload = json.dumps(
            build_report(self.client, self.cluster_id)).encode()
        req = urllib.request.Request(
            self.collector_url, data=payload,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return 200 <= resp.status < 300
        except OSError as e:
            log.info("usage report skipped (collector unreachable: %s)", e)
            return False

    def run_forever(self) -> None:  # pragma: no cover — thin loop
        while True:  # report forever; the pod's lifecycle ends it
            self.report_once()
            time.sleep(self.interval_s)  # tpulint: disable=TPU003,TPU005


def main() -> None:  # pragma: no cover — container entrypoint
    from kubeflow_tpu.k8s.client import HttpKubeClient

    logging.basicConfig(level=logging.INFO)
    url = os.environ.get(ENV_COLLECTOR_URL, "")
    if not url:
        # idle, don't exit: returning would make the default-rendered
        # Deployment (no collector configured) crash-loop forever
        log.info("no %s configured; usage reporting idle",
                 ENV_COLLECTOR_URL)
        while True:  # idle forever by design (see comment above)
            time.sleep(24 * 3600)  # tpulint: disable=TPU003,TPU005
    UsageReporter(HttpKubeClient(), url,
                  cluster_id=os.environ.get(ENV_CLUSTER_ID)).run_forever()


if __name__ == "__main__":
    main()
