"""Shared utilities: metrics, logging, retry."""

from kubeflow_tpu.utils.metrics import (  # noqa: F401
    DEFAULT_REGISTRY,
    Histogram,
    Metric,
    Registry,
    serve_metrics,
)
from kubeflow_tpu.utils.profiler import (  # noqa: F401
    StepProfiler,
    annotate,
    trace,
)
