"""Availability prober: the metric-collector equivalent.

Reference: ``/root/reference/metric-collector/service-readiness/
metric_collect.py:21-38`` — a loop probing the deployment's public
endpoint and exporting a binary ``kubeflow_availability`` prometheus
gauge. Same contract here, on the framework's own registry.
"""

from __future__ import annotations

import threading
import urllib.error
import urllib.request
from typing import Optional

from kubeflow_tpu.utils import DEFAULT_REGISTRY

_availability = DEFAULT_REGISTRY.gauge(
    "kubeflow_availability", "1 when the probed endpoint answers 200")
_probes = DEFAULT_REGISTRY.counter(
    "kubeflow_availability_probes_total", "availability probes issued")


def probe(url: str, timeout_s: float = 10.0) -> bool:
    """One probe; records the gauge and returns reachability."""
    _probes.inc(target=url)
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            up = 200 <= resp.status < 400
    except (urllib.error.URLError, OSError, ValueError):
        up = False
    _availability.set(1.0 if up else 0.0, target=url)
    return up


class AvailabilityProber:
    """Background loop probing on a period (the CronJob-ish collector)."""

    def __init__(self, url: str, *, period_s: float = 30.0,
                 timeout_s: float = 10.0) -> None:
        self.url = url
        self.period_s = period_s
        self.timeout_s = timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        def loop() -> None:
            while not self._stop.wait(self.period_s):
                probe(self.url, self.timeout_s)

        probe(self.url, self.timeout_s)  # prime the gauge immediately
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()


def main() -> None:
    import os

    from kubeflow_tpu.utils import serve_metrics

    url = os.environ.get("KFTPU_PROBE_URL", "http://centraldashboard")
    period = float(os.environ.get("KFTPU_PROBE_PERIOD_S", "30"))
    serve_metrics(int(os.environ.get("KFTPU_MONITORING_PORT", "8090")))
    prober = AvailabilityProber(url, period_s=period)
    prober.start()
    threading.Event().wait()


if __name__ == "__main__":
    main()
