"""In-framework model zoo: transformer LM (flagship), ResNet, BERT, ViT,
MNIST CNN."""

from kubeflow_tpu.models.transformer import (  # noqa: F401
    Transformer,
    TransformerConfig,
    param_logical_axes,
    param_partition_specs,
    tiny_config,
)
from kubeflow_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNetConfig,
    resnet18_thin,
    resnet50,
)
from kubeflow_tpu.models.bert import (  # noqa: F401
    Bert,
    BertConfig,
    bert_base,
    bert_large,
    bert_tiny,
)
from kubeflow_tpu.models.vit import (  # noqa: F401
    ViT,
    ViTConfig,
    vit_base,
    vit_large,
    vit_tiny,
)
from kubeflow_tpu.models.mnist import MnistCnn  # noqa: F401
from kubeflow_tpu.models.decode import (  # noqa: F401
    decode_step,
    generate,
    make_generate,
    prefill,
)
