"""MNIST CNN — the correctness-smoke workload.

Mirrors the reference's 1-worker tf-cnn MNIST smoke config (BASELINE.md
config 1; reference harness ``/root/reference/tf-controller-examples/tf-cnn/``).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MnistCnn(nn.Module):
    num_classes: int = 10

    @nn.compact
    def __call__(self, images: jnp.ndarray) -> jnp.ndarray:
        """images: (B, 28, 28, 1) -> logits (B, 10)."""
        x = nn.Conv(32, (3, 3), name="conv1")(images)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), name="conv2")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, name="fc1")(x))
        return nn.Dense(self.num_classes, name="fc2")(x)
