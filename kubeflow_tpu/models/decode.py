"""Autoregressive generation with a KV cache — the LLM serving hot loop.

The reference platform serves models as opaque TF-Serving containers
(``/root/reference/kubeflow/tf-serving/``) and has no generation story;
a TPU-native framework must own it, XLA-style: everything below is
traced once and compiled — static shapes, ``lax.scan`` over decode
steps, no Python in the loop.

Shapes are the whole design:

- prompts are right-padded to a bucket (one compiled prefill per
  bucket, like the model server's padded batch buckets); the cache
  write index is then reset to each row's true length, so the padded
  tail is dead weight that the next real tokens overwrite before any
  attention can see it (masking is by absolute position);
- the per-step state is the flax ``cache`` collection the decode-mode
  :class:`~kubeflow_tpu.models.transformer.Transformer` maintains
  (K/V ``(L, B, max_seq_len, KH, Dh)`` + write index, stacked over
  layers by ``nn.scan``) — donated through the scan so XLA updates it
  in place;
- sampling is greedy (``temperature=0``) or temperature-scaled
  categorical with a threaded PRNG key.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.transformer import Transformer, TransformerConfig
from kubeflow_tpu.ops.attention import NEG_INF


def _decode_model(config: TransformerConfig) -> Transformer:
    return Transformer(config, decode=True)


def prefill(config: TransformerConfig, params, tokens: jnp.ndarray,
            true_len: Optional[jnp.ndarray] = None):
    """Run the prompt through the decode-mode model, fill the cache.

    ``tokens``: (B, S) right-padded prompts; ``true_len``: the actual
    prompt length(s) — a scalar shared by the batch or a (B,) vector for
    RAGGED batches (defaults to S). Each row's write position resets to
    its own length, so its generated tokens land contiguously after its
    prompt; a shorter row's pad tail stays causally masked until
    overwritten. Returns (next_token_logits, cache) where logits are
    each row's LAST REAL token's.
    """
    model = _decode_model(config)
    B, S = tokens.shape
    if true_len is None:
        true_len = S
    true_len = jnp.asarray(true_len, jnp.int32)
    if true_len.ndim > 1:
        raise ValueError("true_len must be a scalar or a (B,) vector")
    lens = jnp.broadcast_to(true_len, (B,))

    logits, variables = model.apply({"params": params}, tokens,
                                    mutable=["cache"])
    cache = variables["cache"]
    # the write positions advanced to S (the padded bucket); pull each
    # row back to its true length so its next tokens overwrite the pad
    # tail — pad positions are masked (kv_pos <= q_pos) until overwritten
    cache = jax.tree_util.tree_map_with_path(
        lambda path, leaf: (jnp.broadcast_to(lens, leaf.shape)
                            .astype(leaf.dtype)
                            if path[-1].key == "positions" else leaf),
        cache)
    last = jnp.take_along_axis(
        logits, (lens - 1)[:, None, None], axis=1)[:, 0]
    return last, cache


def prefill_continue(config: TransformerConfig, params, cache,
                     tokens: jnp.ndarray, suffix_len, total_len):
    """Extend an existing prefilled cache by a (right-padded) suffix.

    The prefix-caching primitive: ``cache`` holds a prompt prefix (its
    write positions sit at the prefix length; rows sharing a start take
    the contiguous fast path — per-row ragged starts need
    ``config.ragged_decode``); ``tokens`` (B, S) is the right-padded
    continuation, ``suffix_len`` its true per-row length (scalar or
    (B,)) and ``total_len`` the full prompt length (prefix + suffix).
    Returns (last real token's logits, cache positioned at total_len) —
    exactly :func:`prefill`'s contract, at the suffix's cost.
    """
    model = _decode_model(config)
    B, S = tokens.shape
    suffix = jnp.broadcast_to(jnp.asarray(suffix_len, jnp.int32), (B,))
    total = jnp.broadcast_to(jnp.asarray(total_len, jnp.int32), (B,))
    logits, variables = model.apply({"params": params, "cache": cache},
                                    tokens, mutable=["cache"])
    new_cache = jax.tree_util.tree_map_with_path(
        lambda path, leaf: (jnp.broadcast_to(total, leaf.shape)
                            .astype(leaf.dtype)
                            if path[-1].key == "positions" else leaf),
        variables["cache"])
    last = jnp.take_along_axis(
        logits, (suffix - 1)[:, None, None], axis=1)[:, 0]
    return last, new_cache


def _is_key(path, name: str) -> bool:
    return getattr(path[-1], "key", None) == name


def _slot_view(cache, slot, start):
    """A batch-1 view of one engine slot against the SHARED paged pool.

    ``positions``/``pages`` leaves narrow to the slot's row; pool
    ``k``/``v`` leaves pass through whole (every slot writes the same
    pool, disjoint pages). The view's position is OVERRIDDEN with the
    host-authoritative ``start``: between two chunks of the same slot
    the engine's decode step advances the device-side position of every
    row (idle rows decode garbage by design), so the device value for a
    mid-prefill slot is drift, not truth.
    """
    def narrow(path, leaf):
        if _is_key(path, "positions"):
            return jnp.full(leaf.shape[:-1] + (1,),
                            start).astype(leaf.dtype)
        if _is_key(path, "pages"):
            return jax.lax.dynamic_slice_in_dim(
                leaf, slot, 1, axis=leaf.ndim - 2)
        return leaf

    return jax.tree_util.tree_map_with_path(narrow, cache)


def _slot_merge(cache, view, slot, new_pos):
    """Write a :func:`_slot_view` back: the slot's position becomes
    ``new_pos`` (true tokens, not the padded width the apply advanced
    by), its page row round-trips, and the pool leaves are taken from
    the view (the apply mutated them in place)."""
    def widen(path, big, small):
        if _is_key(path, "positions"):
            row = jnp.full(big.shape[:-1] + (1,),
                           new_pos).astype(big.dtype)
            return jax.lax.dynamic_update_slice_in_dim(
                big, row, slot, axis=big.ndim - 1)
        if _is_key(path, "pages"):
            return jax.lax.dynamic_update_slice_in_dim(
                big, small, slot, axis=big.ndim - 2)
        return small

    return jax.tree_util.tree_map_with_path(widen, cache, view)


def arm_slot(cache, slot, start, page_row):
    """Point one slot's device-side position/page-table rows at host
    truth — the paged engine's admission, page growth, and retirement
    are this one tiny program (page-map surgery), never a KV copy.

    Lives beside :func:`_slot_view`/:func:`_slot_merge` because the
    three share the paged-cache leaf contract ("positions" rows on the
    last axis, "pages" rows on the second-to-last); pool leaves pass
    through untouched. Jit with ``donate_argnums=(0,)``.
    """
    slot = jnp.asarray(slot, jnp.int32)

    def upd(path, leaf):
        if _is_key(path, "positions"):
            row = jnp.full(leaf.shape[:-1] + (1,),
                           start).astype(leaf.dtype)
            return jax.lax.dynamic_update_slice_in_dim(
                leaf, row, slot, axis=leaf.ndim - 1)
        if _is_key(path, "pages"):
            row = jnp.broadcast_to(
                page_row,
                leaf.shape[:-2] + (1,) + page_row.shape).astype(
                    leaf.dtype)
            return jax.lax.dynamic_update_slice_in_dim(
                leaf, row, slot, axis=leaf.ndim - 2)
        return leaf

    return jax.tree_util.tree_map_with_path(upd, cache)


def copy_page(cache, src, dst):
    """Copy ONE physical pool page (k and v, every layer) ``src`` →
    ``dst`` — the copy-on-write split primitive: sharing a partial
    boundary page costs one page-sized device copy instead of
    re-prefilling up to ``page_size − 1`` tokens through the model.

    Lives beside :func:`arm_slot` because it shares the paged-cache
    leaf contract: pool ``k``/``v`` leaves are ``(…, P, ps, KH, Dh)``
    (page axis at ``ndim − 4``); ``positions``/``pages`` rows pass
    through untouched. Jit with ``donate_argnums=(0,)``.
    """
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)

    def upd(path, leaf):
        if _is_key(path, "positions") or _is_key(path, "pages"):
            return leaf
        ax = leaf.ndim - 4
        page = jax.lax.dynamic_slice_in_dim(leaf, src, 1, axis=ax)
        return jax.lax.dynamic_update_slice_in_dim(leaf, page, dst,
                                                   axis=ax)

    return jax.tree_util.tree_map_with_path(upd, cache)


def prefill_chunk(config: TransformerConfig, params, cache,
                  tokens: jnp.ndarray, slot, start, true_n):
    """One prompt chunk for ONE slot of a PAGED decode cache.

    The chunked-prefill primitive (``config.kv_page_size > 0``): the
    engine splits prompts into fixed-width chunks and runs one chunk
    per scheduler cycle, so a long admission never stalls co-tenant
    decode for more than one chunk's compute — and the whole prompt
    path needs ONE compiled program (one chunk shape), not one per
    prompt bucket.

    ``tokens``: (1, C) right-padded chunk; ``slot``: engine row the
    chunk belongs to; ``start``: the slot's true position before this
    chunk (0 for a fresh prompt, the shared-page boundary on a prefix
    hit, mid-prompt for every later chunk); ``true_n``: real tokens in
    this chunk (< C only on the final, padded chunk — the pad tail's
    garbage KV lands inside the slot's own pages, stays causally masked
    while the position sits at ``start + true_n``, and is overwritten
    by decode before it can be unmasked, exactly like prefill()'s pad
    tail). Returns ``(logits of the last real token (1, V), cache)``.
    """
    model = _decode_model(config)
    slot = jnp.asarray(slot, jnp.int32)
    start = jnp.asarray(start, jnp.int32)
    true_n = jnp.asarray(true_n, jnp.int32)
    view = _slot_view(cache, slot, start)
    logits, variables = model.apply({"params": params, "cache": view},
                                    tokens, mutable=["cache"])
    new_cache = _slot_merge(cache, variables["cache"], slot,
                            start + true_n)
    last = jnp.take_along_axis(
        logits, (true_n - 1).reshape(1, 1, 1), axis=1)[:, 0]
    return last, new_cache


def decode_step(config: TransformerConfig, params, cache,
                token: jnp.ndarray):
    """One token in, one token's logits out; cache advances by one."""
    model = _decode_model(config)
    logits, variables = model.apply(
        {"params": params, "cache": cache}, token[:, None],
        mutable=["cache"])
    return logits[:, 0], variables["cache"]


def sample_logits(logits: jnp.ndarray, rng: jax.Array, *,
                  temperature=1.0, top_k=0, top_p=1.0,
                  bound: Optional[int] = None) -> jnp.ndarray:
    """Sample token ids from ``(B, V)`` logits — the serving sampler.

    Every parameter may be a Python scalar or a ``(B,)`` array, so ONE
    compiled program serves requests with different sampling settings
    sharing a decode batch (the continuous-batching engine's contract):

    - ``temperature``: 0 → greedy (argmax) for that row; >0 scales.
    - ``top_k``: keep only the k highest logits (0 or ≥V → no filter).
    - ``top_p``: nucleus — keep the smallest prefix of the sorted
      distribution with cumulative probability ≥ p (1.0 → no filter).

    Filters compose HF-style: temperature, then top-k, then top-p.
    Fully jittable: one descending sort of the vocab axis drives both
    filters (threshold-based, static shapes, no boolean gather).

    ``bound`` (a STATIC int) selects the bounded TPU-fast path: only the
    top-``bound`` logits per row are extracted with ``lax.top_k`` — no
    full-vocab sort, no (B, V) sorted materialization (at engine batch
    32 the sort is 32 vocab sorts per token). Semantics under the bound:

    - top-k is exact for ``k <= bound``; larger k clamps to ``bound``
      (the serving cap — public APIs cap top_k the same way);
    - top-p nucleus masses are EXACT (the softmax denominator is a
      full-vocab logsumexp — no sort needed), but a flat distribution
      whose nucleus overflows ``bound`` candidates truncates to the
      bound's top tokens;
    - ``k <= 0`` with ``p >= 1`` rows are unfiltered — exact full-vocab
      categorical; ``temperature <= 0`` rows are exact argmax.

    Bounded and unbounded paths draw different (identically
    distributed) samples for the same key — switching the engine's
    sampler changes sampled streams, like any sampler upgrade.
    """
    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))
    p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))

    greedy_row = temp <= 0.0
    scaled = logits / jnp.where(greedy_row, 1.0, temp)[:, None]

    if bound is not None and int(bound) > 0 and int(bound) < V:
        M = int(bound)
        topv, topi = jax.lax.top_k(scaled, M)  # (B, M) descending
        k_eff = jnp.where(k <= 0, M, jnp.minimum(k, M))
        pos = jnp.arange(M)[None, :]
        kmask = pos < k_eff[:, None]
        # compose parity with the sort path: top-p renormalizes over
        # the k-filtered distribution — over the FULL vocab when no k
        # filter is set (exact via logsumexp), over the kept top-k
        # candidates otherwise
        full_lse = jax.scipy.special.logsumexp(scaled, axis=-1)
        k_lse = jax.scipy.special.logsumexp(
            jnp.where(kmask, topv, NEG_INF), axis=-1)
        denom = jnp.where(k <= 0, full_lse, k_lse)
        probs = jnp.exp(topv - denom[:, None]) * kmask
        before = jnp.cumsum(probs, axis=-1) - probs
        keep = kmask & ((before < p[:, None]) | (p[:, None] >= 1.0))
        rng_m, rng_v = jax.random.split(rng)
        choice = jax.random.categorical(
            rng_m, jnp.where(keep, topv, NEG_INF), axis=-1)
        bounded_tok = jnp.take_along_axis(
            topi, choice[:, None], axis=-1)[:, 0]
        unfiltered = (k <= 0) & (p >= 1.0)
        full_tok = jax.random.categorical(rng_v, scaled, axis=-1)
        out = jnp.where(greedy_row, jnp.argmax(logits, axis=-1),
                        jnp.where(unfiltered, full_tok, bounded_tok))
        return out.astype(jnp.int32)

    srt = jnp.sort(scaled, axis=-1)[:, ::-1]  # (B, V) descending
    # top-k: per-row threshold at the k-th largest (k<=0 → keep all)
    k_eff = jnp.where(k <= 0, V, jnp.minimum(k, V))
    kth = jnp.take_along_axis(srt, (k_eff - 1)[:, None], axis=-1)
    keep = scaled >= kth
    # top-p on the k-filtered distribution: renormalised cumulative
    # mass strictly BEFORE each sorted position; a position is kept
    # while that prefix mass is < p (the first is always kept). In
    # sorted order the k-filter is positional: the first k_eff entries.
    srt_masked = jnp.where(jnp.arange(V)[None, :] < k_eff[:, None],
                           srt, NEG_INF)
    probs = jax.nn.softmax(srt_masked, axis=-1)
    before = jnp.cumsum(probs, axis=-1) - probs
    # p >= 1.0 must be a strict no-op: f32 cumsum rounding can push
    # `before` to exactly 1.0 for tail tokens, which `< p` would mask
    kept_sorted = (before < p[:, None]) | (p[:, None] >= 1.0)
    # smallest kept sorted logit = the acceptance threshold
    n_kept = jnp.sum(kept_sorted, axis=-1)  # >= 1
    p_thresh = jnp.take_along_axis(srt, (n_kept - 1)[:, None], axis=-1)
    keep = keep & (scaled >= p_thresh)
    masked = jnp.where(keep, scaled, NEG_INF)
    sampled = jax.random.categorical(rng, masked, axis=-1)
    out = jnp.where(greedy_row, jnp.argmax(logits, axis=-1), sampled)
    return out.astype(jnp.int32)


def _sample(logits: jnp.ndarray, temperature, rng: Optional[jax.Array],
            greedy: bool, top_k=0, top_p=1.0) -> jnp.ndarray:
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # the sort-free fast path needs the filters statically off and the
    # temperature scalar — a (B,) temperature (per-row greedy mix) must
    # go through sample_logits, whose broadcasting and temp<=0 handling
    # are per-row. A 0-d TRACED temperature stays on the fast path (the
    # serving closure traces it; its greedy split is static, so a traced
    # temperature is guaranteed > 0 here).
    scalar_temp = (isinstance(temperature, (int, float)) or
                   getattr(temperature, "ndim", None) == 0)
    static_nofilter = (
        scalar_temp and
        isinstance(top_k, int) and top_k == 0 and
        isinstance(top_p, (int, float)) and top_p >= 1.0)
    if static_nofilter:
        return jax.random.categorical(
            rng, logits / temperature, axis=-1).astype(jnp.int32)
    return sample_logits(logits, rng, temperature=temperature,
                         top_k=top_k, top_p=top_p)


def generate(config: TransformerConfig, params, prompt: jnp.ndarray,
             *, max_new_tokens: int,
             true_len: Optional[jnp.ndarray] = None,
             temperature: float = 0.0,
             top_k=0, top_p=1.0,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Prefill + scan decode; returns (B, max_new_tokens) int32.

    Fully traceable: wrap in ``jax.jit`` (static ``config`` and
    ``max_new_tokens``). ``temperature`` may be a traced array — the
    greedy/sampling split is decided statically by whether it is the
    Python float 0.0, so a serving layer can compile ONE sampling
    program for all temperatures. ``top_k``/``top_p`` likewise may be
    traced (scalars or per-row vectors, see :func:`sample_logits`);
    their no-filter defaults are recognised statically so the plain
    temperature path compiles without the vocab sort.
    """
    greedy = isinstance(temperature, (int, float)) and temperature == 0.0
    if not greedy:
        if rng is None:
            raise ValueError("sampling (temperature > 0) needs an rng key")
        if isinstance(temperature, (int, float)) and temperature < 0:
            raise ValueError("temperature must be >= 0")
    if isinstance(top_k, int) and top_k < 0:
        raise ValueError("top_k must be >= 0 (0 = no filter)")
    if isinstance(top_p, (int, float)) and not 0.0 < top_p <= 1.0:
        raise ValueError("top_p must be in (0, 1]")
    if rng is None:
        rng = jax.random.key(0)  # unused by greedy; keeps the scan carry

    # cache writes past max_seq_len silently clamp (scatter semantics) —
    # reject overruns where the start is known eagerly. A traced
    # true_len (inside an outer jit, e.g. the serving wrapper) is the
    # caller's contract: the padded prompt width would over-reject.
    if true_len is None:
        start = prompt.shape[1]
    elif isinstance(true_len, jax.core.Tracer):
        start = None
    else:
        start = int(jnp.max(jnp.asarray(true_len)))
    if start is not None and start + max_new_tokens > config.max_seq_len:
        raise ValueError(
            f"prompt length {start} + max_new_tokens "
            f"{max_new_tokens} exceeds max_seq_len {config.max_seq_len}: "
            "cache writes past the end would silently clamp")

    last_logits, cache = prefill(config, params, prompt, true_len)
    rng, sub = jax.random.split(rng)
    first = _sample(last_logits, temperature, sub, greedy, top_k, top_p)

    def step(carry, _):
        cache, token, rng = carry
        logits, cache = decode_step(config, params, cache, token)
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits, temperature, sub, greedy, top_k, top_p)
        return (cache, nxt, rng), nxt

    if max_new_tokens == 1:
        return first[:, None]
    (_, _, _), rest = jax.lax.scan(
        step, (cache, first, rng), None, length=max_new_tokens - 1)
    # scan stacks on axis 0: (T-1, B) -> (B, T-1)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def speculative_generate(config: TransformerConfig, params,
                         draft_config: TransformerConfig, draft_params,
                         prompt: jnp.ndarray, *, max_new_tokens: int,
                         draft_len: int = 4,
                         true_len: Optional[jnp.ndarray] = None):
    """Greedy speculative decoding: a small draft model proposes
    ``draft_len`` tokens per round, the target verifies them in ONE
    multi-token forward, and every accepted token costs the target
    1/draft_len of a decode step.

    Output matches ``generate(config, params, prompt, ...)`` token for
    token (greedy verification accepts a proposal iff it equals the
    target's argmax) — speculation changes the cost, never the policy.
    Caveat: the k-token verify and the 1-token step are different XLA
    programs; under reduced precision (bf16) a near-tie argmax can
    resolve differently and diverge the tail. Exactness is guaranteed
    at f32 (the test tier); at bf16 the stream remains a valid greedy
    stream of the target up to tie-breaks.

    TPU-first detail: the decode cache stores token t at physical slot
    t (``transformer.py:_decode_attend``), so rejecting draft tokens is
    a ROLLBACK-BY-RESET — set the per-row write position back to the
    accepted length and the stale tail is dead weight the next tokens
    overwrite before attention can see it. No copies, no re-prefill,
    ragged per-row acceptance for free.

    Returns ``(tokens (B, max_new_tokens) int32, stats)`` with
    ``stats = {"rounds": R, "draft_tokens": R*draft_len, "accepted":
    total draft tokens accepted}`` — acceptance/draft_tokens is the
    acceptance rate that decides whether the draft pays for itself.
    """
    B, S = prompt.shape
    k = int(draft_len)
    _spec_validate(config, draft_config, S, max_new_tokens, k, true_len)

    t_logits, t_cache = _prefill_jit(config)(params, prompt, true_len)
    _, d_cache = _prefill_jit(draft_config)(draft_params, prompt,
                                            true_len)
    first = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)

    spec_round = _spec_round_fn(config, draft_config, k)
    emitted = [[int(first[b])] for b in range(B)]
    pending = first
    rounds = accepted_total = 0
    # Ragged batches (B>1): a fast row keeps decoding past
    # max_new_tokens while slow rows catch up; its overshoot tokens are
    # sliced off below and its cache writes past max_seq_len are
    # DROPPED by jnp scatter out-of-bounds semantics (`.at[pos].set`
    # drops OOB writes — the same invariant the decode engine's idle
    # slots rely on). The kept tokens never depend on an OOB write: a
    # row's first max_new_tokens are all produced from in-bounds cache
    # state (guaranteed by the max_seq_len slack check above), so the
    # reliance is confined to the discarded tail.
    while min(len(e) for e in emitted) < max_new_tokens:
        t_cache, d_cache, out, m, pending, n = spec_round(
            params, draft_params, t_cache, d_cache, pending)
        # the per-round surfacing point BY DESIGN: acceptance counts
        # decide on the host whether another speculative round runs
        out, m, n = np.asarray(out), np.asarray(m), np.asarray(n)  # tpulint: disable=TPU017
        rounds += 1
        accepted_total += int(n.sum())
        for b in range(B):
            emitted[b].extend(int(t) for t in out[b, :m[b]])
    tokens = np.asarray([e[:max_new_tokens] for e in emitted], np.int32)
    stats = {"rounds": rounds, "draft_tokens": rounds * k,
             "accepted": accepted_total}
    return jnp.asarray(tokens), stats


@functools.lru_cache(maxsize=32)
def _prefill_jit(config: TransformerConfig):
    """Compiled prefill per (config, shape) — cached across calls so a
    serving loop never re-traces."""
    return jax.jit(functools.partial(prefill, config))


def _spec_validate(config: TransformerConfig,
                   draft_config: TransformerConfig, prompt_width: int,
                   max_new_tokens: int, k: int, true_len) -> None:
    """Shared eager validation for the speculative variants.

    Each round may advance up to ``k`` cache slots past the final
    output; the real footprint starts at the TRUE prompt length when
    known eagerly (a traced ``true_len`` is the caller's contract, like
    ``generate()``)."""
    if k < 1:
        raise ValueError("draft_len must be >= 1")
    if config.vocab_size != draft_config.vocab_size:
        raise ValueError("draft and target must share a vocabulary")
    if true_len is None:
        start: Optional[int] = prompt_width
    elif isinstance(true_len, jax.core.Tracer):
        start = None
    else:
        start = int(jnp.max(jnp.asarray(true_len)))
    for name, c in (("target", config), ("draft", draft_config)):
        if start is not None and start + max_new_tokens + k > c.max_seq_len:
            raise ValueError(
                f"prompt {start} + max_new_tokens {max_new_tokens} + "
                f"draft_len {k} exceeds {name} max_seq_len "
                f"{c.max_seq_len} (speculation needs slack for "
                "in-flight proposals)")


def _spec_round_body(ragged_config: TransformerConfig,
                     draft_config: TransformerConfig, k: int,
                     params, draft_params, t_cache, d_cache, pending):
    """One propose-verify-rollback round (traceable; shared by the
    per-round jit and the fused while_loop path)."""
    B = pending.shape[0]

    def dstep(carry, _):
        cache, tok = carry
        logits, cache = decode_step(draft_config, draft_params,
                                    cache, tok)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    (d_cache2, _), xs = jax.lax.scan(dstep, (d_cache, pending),
                                     None, length=k)
    xs = xs.T  # (B, k): proposals x1..xk
    # verify: the target processes (pending, x1..x_{k-1}) in one
    # forward; logits[i] is its prediction for position i+1
    seq = jnp.concatenate([pending[:, None], xs[:, :k - 1]], axis=1)
    model = _decode_model(ragged_config)
    logits, variables = model.apply(
        {"params": params, "cache": t_cache}, seq, mutable=["cache"])
    t_cache2 = variables["cache"]
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # (B, k)
    match = xs == preds
    # accepted = length of the all-True prefix
    n = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    idx = jnp.arange(k)[None, :]
    rows = jnp.arange(B)
    correction = preds[rows, jnp.minimum(n, k - 1)]
    out = jnp.where(idx < n[:, None], xs, 0)
    # at index n the target's own token replaces the rejected one
    out = jnp.where(idx == n[:, None], correction[:, None], out)
    m = jnp.where(n < k, n + 1, k)  # emitted this round, per row
    new_pending = jnp.where(n < k, correction, xs[:, k - 1])
    # rollback-by-reset: the verify advanced every row k slots, but
    # only (pending, x1..x_n) are valid — n+1 entries on rejection
    # rounds, all k on full acceptance (x_k was proposed, never
    # written). Pull each row back by the overshoot.
    delta = jnp.maximum(k - n - 1, 0)

    def reset(path, leaf):
        if path[-1].key != "positions":
            return leaf
        return (leaf - jnp.broadcast_to(delta, leaf.shape)
                ).astype(leaf.dtype)

    t_cache2 = jax.tree_util.tree_map_with_path(reset, t_cache2)
    d_cache2 = jax.tree_util.tree_map_with_path(reset, d_cache2)
    return t_cache2, d_cache2, out, m, new_pending, n


@functools.lru_cache(maxsize=16)
def _spec_round_fn(config: TransformerConfig,
                   draft_config: TransformerConfig, k: int):
    """Compiled propose-verify round, cached per (configs, draft_len) —
    a fresh closure per generate call would retrace both models every
    time."""
    # the verify writes k tokens from PER-ROW ragged positions
    ragged = dataclasses.replace(config, ragged_decode=True)

    @jax.jit
    def spec_round(params, draft_params, t_cache, d_cache, pending):
        return _spec_round_body(ragged, draft_config, k, params,
                                draft_params, t_cache, d_cache, pending)

    return spec_round


def speculative_generate_fused(config: TransformerConfig, params,
                               draft_config: TransformerConfig,
                               draft_params, prompt: jnp.ndarray, *,
                               max_new_tokens: int, draft_len: int = 4,
                               true_len: Optional[jnp.ndarray] = None):
    """:func:`speculative_generate` as ONE traceable program: prefills,
    every propose-verify-rollback round (``lax.while_loop``), and token
    assembly all compile into a single XLA computation.

    The host-loop variant pays one device dispatch per round; whenever
    dispatch/transfer latency is non-negligible (remote transports,
    small models) those round-trips dominate wall time — measured round
    5: ~224 ms/round over the tunneled chip vs sub-ms of device compute.
    Fused, speculation is a single dispatch exactly like the plain
    ``generate`` scan, so the comparison is pure compute: a round costs
    one k-token target verify plus k draft steps for ``1 + acceptance·k``
    emitted tokens.

    Identical round math to ``speculative_generate`` (f32-exact parity
    is test-gated; at bf16 XLA may fuse the two variants differently, so
    near-tie argmaxes can diverge — each stream remains a valid greedy
    stream of the target up to tie-breaks). Ragged rows: a finished row
    keeps stepping until the slowest row completes; its overshoot
    tokens land past ``max_new_tokens`` in the output buffer (scatter-
    drop) and its cache writes past ``max_seq_len`` are dropped by the
    same out-of-bounds semantics the host variant documents.

    Returns ``(tokens (B, max_new_tokens) int32, stats)``; stats values
    are 0-d device arrays under tracing (``int()`` them outside jit).
    Wrap in ``jax.jit`` with params/prompt as ARGUMENTS (closing over
    params embeds the weights as program constants).
    """
    B, S = prompt.shape
    k = int(draft_len)
    _spec_validate(config, draft_config, S, max_new_tokens, k, true_len)

    ragged = dataclasses.replace(config, ragged_decode=True)
    t_logits, t_cache = prefill(config, params, prompt, true_len)
    _, d_cache = prefill(draft_config, draft_params, prompt, true_len)
    first = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)

    # buffer slack: a row at counts==max_new-1 can still write m<=k
    # tokens; masked positions index `cap` and are scatter-dropped
    cap = max_new_tokens + k + 1
    out_buf = jnp.zeros((B, cap), jnp.int32).at[:, 0].set(first)
    counts = jnp.ones((B,), jnp.int32)
    rows = jnp.arange(B)
    idx = jnp.arange(k)[None, :]

    def cond(carry):
        return jnp.min(carry[4]) < max_new_tokens

    def body(carry):
        t_cache, d_cache, pending, out_buf, counts, rounds, acc = carry
        t_cache, d_cache, out, m, pending, n = _spec_round_body(
            ragged, draft_config, k, params, draft_params, t_cache,
            d_cache, pending)
        pos = jnp.where(idx < m[:, None], counts[:, None] + idx, cap)
        out_buf = out_buf.at[rows[:, None], pos].set(out, mode="drop")
        return (t_cache, d_cache, pending, out_buf, counts + m,
                rounds + 1, acc + jnp.sum(n))

    carry = (t_cache, d_cache, first, out_buf, counts,
             jnp.int32(0), jnp.int32(0))
    _, _, _, out_buf, _, rounds, accepted = jax.lax.while_loop(
        cond, body, carry)
    stats = {"rounds": rounds, "draft_tokens": rounds * k,
             "accepted": accepted}
    return out_buf[:, :max_new_tokens], stats


@functools.lru_cache(maxsize=16)
def _spec_fused_fn(config: TransformerConfig,
                   draft_config: TransformerConfig, k: int,
                   max_new_tokens: int):
    @jax.jit
    def fn(params, draft_params, prompt, true_len):
        return speculative_generate_fused(
            config, params, draft_config, draft_params, prompt,
            max_new_tokens=max_new_tokens, draft_len=k,
            true_len=true_len)

    return fn


def speculative_generate_jit(config: TransformerConfig, params,
                             draft_config: TransformerConfig,
                             draft_params, prompt: jnp.ndarray, *,
                             max_new_tokens: int, draft_len: int = 4,
                             true_len: Optional[jnp.ndarray] = None):
    """Serving entry for fused speculation: eager validation (the slack
    ValueError serving maps to 400 fires before any device work) + a
    cached compiled program per (configs, draft_len, max_new_tokens,
    shapes). Stats come back as Python ints like the host-loop
    variant's."""
    B, S = prompt.shape
    _spec_validate(config, draft_config, S, max_new_tokens,
                   int(draft_len), true_len)
    fn = _spec_fused_fn(config, draft_config, int(draft_len),
                        int(max_new_tokens))
    toks, stats = fn(params, draft_params, prompt, true_len)
    return toks, {key: int(np.asarray(v)) for key, v in stats.items()}


def make_generate(config: TransformerConfig, *, max_new_tokens: int,
                  temperature: float = 0.0, top_k: int = 0,
                  top_p: float = 1.0):
    """Jitted generate closure: (params, prompt, true_len, rng) -> tokens."""
    import functools

    @functools.partial(jax.jit, donate_argnums=())
    def fn(params, prompt, true_len, rng):
        return generate(config, params, prompt,
                        max_new_tokens=max_new_tokens,
                        true_len=true_len, temperature=temperature,
                        top_k=top_k, top_p=top_p,
                        rng=rng)

    return fn
