"""Autoregressive generation with a KV cache — the LLM serving hot loop.

The reference platform serves models as opaque TF-Serving containers
(``/root/reference/kubeflow/tf-serving/``) and has no generation story;
a TPU-native framework must own it, XLA-style: everything below is
traced once and compiled — static shapes, ``lax.scan`` over decode
steps, no Python in the loop.

Shapes are the whole design:

- prompts are right-padded to a bucket (one compiled prefill per
  bucket, like the model server's padded batch buckets); the cache
  write index is then reset to each row's true length, so the padded
  tail is dead weight that the next real tokens overwrite before any
  attention can see it (masking is by absolute position);
- the per-step state is the flax ``cache`` collection the decode-mode
  :class:`~kubeflow_tpu.models.transformer.Transformer` maintains
  (K/V ``(L, B, max_seq_len, KH, Dh)`` + write index, stacked over
  layers by ``nn.scan``) — donated through the scan so XLA updates it
  in place;
- sampling is greedy (``temperature=0``) or temperature-scaled
  categorical with a threaded PRNG key.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.transformer import Transformer, TransformerConfig


def _decode_model(config: TransformerConfig) -> Transformer:
    return Transformer(config, decode=True)


def prefill(config: TransformerConfig, params, tokens: jnp.ndarray,
            true_len: Optional[jnp.ndarray] = None):
    """Run the prompt through the decode-mode model, fill the cache.

    ``tokens``: (B, S) right-padded prompts; ``true_len``: the actual
    prompt length(s) — a scalar shared by the batch or a (B,) vector for
    RAGGED batches (defaults to S). Each row's write position resets to
    its own length, so its generated tokens land contiguously after its
    prompt; a shorter row's pad tail stays causally masked until
    overwritten. Returns (next_token_logits, cache) where logits are
    each row's LAST REAL token's.
    """
    model = _decode_model(config)
    B, S = tokens.shape
    if true_len is None:
        true_len = S
    true_len = jnp.asarray(true_len, jnp.int32)
    if true_len.ndim > 1:
        raise ValueError("true_len must be a scalar or a (B,) vector")
    lens = jnp.broadcast_to(true_len, (B,))

    logits, variables = model.apply({"params": params}, tokens,
                                    mutable=["cache"])
    cache = variables["cache"]
    # the write positions advanced to S (the padded bucket); pull each
    # row back to its true length so its next tokens overwrite the pad
    # tail — pad positions are masked (kv_pos <= q_pos) until overwritten
    cache = jax.tree_util.tree_map_with_path(
        lambda path, leaf: (jnp.broadcast_to(lens, leaf.shape)
                            .astype(leaf.dtype)
                            if path[-1].key == "positions" else leaf),
        cache)
    last = jnp.take_along_axis(
        logits, (lens - 1)[:, None, None], axis=1)[:, 0]
    return last, cache


def decode_step(config: TransformerConfig, params, cache,
                token: jnp.ndarray):
    """One token in, one token's logits out; cache advances by one."""
    model = _decode_model(config)
    logits, variables = model.apply(
        {"params": params, "cache": cache}, token[:, None],
        mutable=["cache"])
    return logits[:, 0], variables["cache"]


def _sample(logits: jnp.ndarray, temperature, rng: Optional[jax.Array],
            greedy: bool) -> jnp.ndarray:
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        rng, logits / temperature, axis=-1).astype(jnp.int32)


def generate(config: TransformerConfig, params, prompt: jnp.ndarray,
             *, max_new_tokens: int,
             true_len: Optional[jnp.ndarray] = None,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jnp.ndarray:
    """Prefill + scan decode; returns (B, max_new_tokens) int32.

    Fully traceable: wrap in ``jax.jit`` (static ``config`` and
    ``max_new_tokens``). ``temperature`` may be a traced array — the
    greedy/sampling split is decided statically by whether it is the
    Python float 0.0, so a serving layer can compile ONE sampling
    program for all temperatures.
    """
    greedy = isinstance(temperature, (int, float)) and temperature == 0.0
    if not greedy:
        if rng is None:
            raise ValueError("sampling (temperature > 0) needs an rng key")
        if isinstance(temperature, (int, float)) and temperature < 0:
            raise ValueError("temperature must be >= 0")
    if rng is None:
        rng = jax.random.key(0)  # unused by greedy; keeps the scan carry

    # cache writes past max_seq_len silently clamp (scatter semantics) —
    # reject overruns where the start is known eagerly. A traced
    # true_len (inside an outer jit, e.g. the serving wrapper) is the
    # caller's contract: the padded prompt width would over-reject.
    if true_len is None:
        start = prompt.shape[1]
    elif isinstance(true_len, jax.core.Tracer):
        start = None
    else:
        start = int(jnp.max(jnp.asarray(true_len)))
    if start is not None and start + max_new_tokens > config.max_seq_len:
        raise ValueError(
            f"prompt length {start} + max_new_tokens "
            f"{max_new_tokens} exceeds max_seq_len {config.max_seq_len}: "
            "cache writes past the end would silently clamp")

    last_logits, cache = prefill(config, params, prompt, true_len)
    rng, sub = jax.random.split(rng)
    first = _sample(last_logits, temperature, sub, greedy)

    def step(carry, _):
        cache, token, rng = carry
        logits, cache = decode_step(config, params, cache, token)
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits, temperature, sub, greedy)
        return (cache, nxt, rng), nxt

    if max_new_tokens == 1:
        return first[:, None]
    (_, _, _), rest = jax.lax.scan(
        step, (cache, first, rng), None, length=max_new_tokens - 1)
    # scan stacks on axis 0: (T-1, B) -> (B, T-1)
    return jnp.concatenate([first[:, None], rest.T], axis=1)


def make_generate(config: TransformerConfig, *, max_new_tokens: int,
                  temperature: float = 0.0):
    """Jitted generate closure: (params, prompt, true_len, rng) -> tokens."""
    import functools

    @functools.partial(jax.jit, donate_argnums=())
    def fn(params, prompt, true_len, rng):
        return generate(config, params, prompt,
                        max_new_tokens=max_new_tokens,
                        true_len=true_len, temperature=temperature,
                        rng=rng)

    return fn
