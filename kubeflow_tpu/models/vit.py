"""Vision Transformer: image classification on the shared encoder blocks.

Widens the model-family coverage beyond the reference's CNN/BERT workload
archetypes (its vision examples are tf_cnn_benchmarks CNNs run as TFJobs,
``/root/reference/tf-controller-examples/tf-cnn/``) with the
transformer-native image workload, built from the same Block stack as the
LM/BERT models so every mesh axis rule (dp/tp/sp, remat, scanned layers)
applies unchanged.

TPU-first choices: the patch stem is a non-overlapping conv (a reshaped
GEMM — tiles the MXU perfectly, unlike small-channel 7×7 stems), 1D RoPE
over raster-ordered patches instead of a learned position table (nothing
extra to shard or resize), mean pooling instead of a [CLS] token (keeps
the sequence length a power of two and the pooling a bandwidth-trivial
reduce).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.transformer import (
    Block,
    RMSNorm,
    TransformerConfig,
    _constrain,
    rope_tables,
)


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    scan_layers: bool = True

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def encoder_config(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=1,  # unused: the stem is a patch conv, not a table
            d_model=self.d_model,
            n_layers=self.n_layers,
            n_heads=self.n_heads,
            n_kv_heads=self.n_heads,
            d_ff=self.d_ff,
            max_seq_len=self.n_patches,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            remat=self.remat,
            scan_layers=self.scan_layers,
            causal=False,  # every patch attends to every patch
        )


def vit_base(num_classes: int = 1000) -> ViTConfig:
    return ViTConfig(num_classes=num_classes)


def vit_large(num_classes: int = 1000) -> ViTConfig:
    return ViTConfig(num_classes=num_classes, d_model=1024, n_layers=24,
                     n_heads=16, d_ff=4096)


def vit_tiny(num_classes: int = 10) -> ViTConfig:
    """Test-sized config."""
    return ViTConfig(image_size=32, patch_size=8, num_classes=num_classes,
                     d_model=64, n_layers=2, n_heads=4, d_ff=128,
                     remat=False, scan_layers=False)


class ViT(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, images: jnp.ndarray,
                 train: bool = True) -> jnp.ndarray:
        """images: (B, H, W, C) -> logits (B, num_classes) float32.

        ``train`` is accepted for API parity with the ResNet family (the
        image train step passes it); the ViT has no train-only state."""
        c = self.config
        ec = c.encoder_config()
        B, H, W, _ = images.shape
        if H != c.image_size or W != c.image_size:
            raise ValueError(
                f"expected {c.image_size}² input, got {H}x{W}")

        # patch stem: non-overlapping conv == one big GEMM over
        # (patch_size² · C)-dim pixels — MXU-shaped by construction
        x = nn.Conv(
            c.d_model, (c.patch_size, c.patch_size),
            strides=(c.patch_size, c.patch_size), padding="VALID",
            use_bias=True, dtype=c.dtype, param_dtype=c.param_dtype,
            name="patch_embed",
        )(images.astype(c.dtype))
        x = x.reshape(B, -1, c.d_model)  # (B, N, D) raster order
        x = _constrain(x, ec.rules, "batch", "seq", None)
        sin, cos = rope_tables(x.shape[1], ec.head_dim, ec.rope_theta)

        block_cls = Block
        if c.remat:
            block_cls = nn.remat(Block, prevent_cse=False)
        if c.scan_layers:
            x, _ = nn.scan(
                block_cls,
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=c.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(ec, name="blocks")(x, (sin, cos))
        else:
            for i in range(c.n_layers):
                x, _ = block_cls(ec, name=f"block_{i}")(x, (sin, cos))

        x = RMSNorm(param_dtype=c.param_dtype, name="final_norm")(x)
        x = jnp.mean(x, axis=1)  # mean pool over patches
        return nn.Dense(
            c.num_classes, dtype=jnp.float32, param_dtype=c.param_dtype,
            name="head",
        )(x.astype(jnp.float32))
