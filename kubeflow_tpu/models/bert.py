"""BERT-family encoder: bidirectional transformer + masked-LM head.

The DDP-BERT archetype of BASELINE.md config 3 (the reference ran BERT as
an opaque PyTorchJob DDP workload, ``/root/reference/kubeflow/pytorch-job/
prototypes/pytorch-job.jsonnet:69-80``); here it is in-framework so the
same mesh/sharding axes apply. TPU-first choices over classic BERT:
RoPE positions instead of learned embeddings (no position table to shard),
RMSNorm, bf16 activations, scanned/remat blocks — weight compatibility
with original BERT checkpoints is a non-goal; the *workload shape*
(bidirectional encoder, MLM objective, base/large sizes) is the parity
target. Reuses the flagship blocks with ``causal=False``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from kubeflow_tpu.models.transformer import (
    Block,
    RMSNorm,
    TransformerConfig,
    _constrain,
    rope_tables,
)

MASK_TOKEN_ID = 103  # conventionally [MASK] in the BERT vocab


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2      # sentence A/B segments
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    scan_layers: bool = True
    # "auto" = the Pallas flash kernels on the TPU backend (the
    # longcontext blocking treatment applied to seq-512 bidirectional,
    # ROADMAP item 3's BERT-MFU lever), the dense XLA path elsewhere —
    # dense is the parity oracle the flash route is gated against
    # (tests/test_bert.py). Force "flash" to run the kernels in the
    # interpreter off-TPU.
    attention_impl: str = "auto"
    # flash tile overrides; None = the shape-keyed tile table
    # (kubeflow_tpu/ops/autotune.py)
    attention_block_q: Any = None
    attention_block_k: Any = None

    def encoder_config(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=self.vocab_size,
            d_model=self.d_model,
            n_layers=self.n_layers,
            n_heads=self.n_heads,
            n_kv_heads=self.n_heads,
            d_ff=self.d_ff,
            max_seq_len=self.max_seq_len,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            remat=self.remat,
            scan_layers=self.scan_layers,
            causal=False,  # the defining difference from the LM flagship
            attention_impl=self.attention_impl,
            attention_block_q=self.attention_block_q,
            attention_block_k=self.attention_block_k,
        )


def bert_base() -> BertConfig:
    return BertConfig()


def bert_large() -> BertConfig:
    return BertConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)


def bert_tiny() -> BertConfig:
    """Test-sized config."""
    return BertConfig(vocab_size=1024, d_model=64, n_layers=2, n_heads=4,
                      d_ff=128, max_seq_len=128, remat=False,
                      scan_layers=False)


class Bert(nn.Module):
    config: BertConfig

    @nn.compact
    def __call__(self, tokens: jnp.ndarray,
                 token_types: jnp.ndarray = None,
                 seq_lengths: jnp.ndarray = None) -> jnp.ndarray:
        """tokens: (B, S) int32 -> MLM logits (B, S, V) float32.

        ``seq_lengths`` is an optional per-row valid-length ``(B,)``
        int32 padding mask: positions at/past a row's length are
        excluded from every attention (dense and flash paths alike);
        logits AT padded positions are unspecified — mask them with the
        MLM loss weights, which real padding already zeroes.
        """
        c = self.config
        ec = c.encoder_config()
        B, S = tokens.shape

        embed = self.param(
            "token_embed",
            nn.initializers.normal(stddev=c.d_model ** -0.5),
            (c.vocab_size, c.d_model),
            c.param_dtype,
        )
        x = jnp.take(embed.astype(c.dtype), tokens, axis=0)
        if c.type_vocab_size:
            type_embed = self.param(
                "type_embed",
                nn.initializers.normal(stddev=c.d_model ** -0.5),
                (c.type_vocab_size, c.d_model),
                c.param_dtype,
            )
            if token_types is None:
                token_types = jnp.zeros_like(tokens)
            x = x + jnp.take(type_embed.astype(c.dtype), token_types, axis=0)
        x = _constrain(x, ec.rules, "batch", "seq", None)
        sin, cos = rope_tables(S, ec.head_dim, ec.rope_theta)

        aux = (sin, cos, seq_lengths)
        block_cls = Block
        if c.remat:
            block_cls = nn.remat(Block, prevent_cse=False)
        if c.scan_layers:
            x, _ = nn.scan(
                block_cls,
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=c.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(ec, name="blocks")(x, aux)
        else:
            for i in range(c.n_layers):
                x, _ = block_cls(ec, name=f"block_{i}")(x, aux)

        x = RMSNorm(param_dtype=c.param_dtype, name="final_norm")(x)
        # MLM head: dense transform + tied-embedding decode (BERT's
        # cls/predictions/transform shape)
        w = self.param("mlm_transform",
                       nn.initializers.normal(stddev=c.d_model ** -0.5),
                       (c.d_model, c.d_model), c.param_dtype)
        x = nn.gelu(jnp.einsum("bsd,de->bse", x, w.astype(c.dtype)))
        logits = jnp.einsum(
            "bsd,vd->bsv", x, embed.astype(c.dtype)
        ).astype(jnp.float32)
        return _constrain(logits, ec.rules, "batch", None, "vocab")


def mask_tokens(rng, tokens: jnp.ndarray, *, mask_prob: float = 0.15,
                mask_id: int = MASK_TOKEN_ID) -> Tuple[jnp.ndarray,
                                                       jnp.ndarray]:
    """The MLM corruption: returns (masked_tokens, weights) where weights
    mark positions whose original token must be predicted."""
    import jax

    mask = jax.random.bernoulli(rng, mask_prob, tokens.shape)
    masked = jnp.where(mask, jnp.full_like(tokens, mask_id), tokens)
    return masked, mask.astype(jnp.float32)
