"""Flagship decoder-only transformer LM, written TPU-first.

The reference platform never sees model internals — its workloads are opaque
container images (``tf_cnn_benchmarks`` via
``/root/reference/kubeflow/examples/prototypes/tf-job-simple-v1.jsonnet:28-38``).
The TPU-native framework ships models in-framework so parallelism axes
(SURVEY.md §2c) are real capabilities: this model exposes logical sharding
axes for DP/TP/SP/EP and stacks its blocks so pipeline stages can shard the
leading layer axis.

Design notes (TPU-first):
- bf16 activations, fp32 params/optimizer; big fused einsums for the MXU.
- ``nn.scan`` over blocks: one traced block, stacked params — fast compiles
  and a natural ``stage`` axis for pipeline parallelism.
- ``nn.remat`` per block trades FLOPs for HBM.
- MoE uses exact dense top-k dispatch (one-hot combine einsum): static
  shapes, XLA-friendly; experts shard over the ``expert`` logical axis. A
  capacity-based all_to_all dispatch is the planned fast path for large E.
- Sequence-parallel regions: norms/residual activations carry a ``seq``
  sharding constraint so the tp group shards the sequence dim between the
  matmul regions (Megatron-SP layout).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from kubeflow_tpu.parallel.mesh import (
    AxisRules,
    DEFAULT_RULES,
    logical_to_mesh_axes,
    shard_constraint,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 2048
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    n_experts: int = 0            # 0 => dense MLP
    experts_per_token: int = 2
    moe_capacity_factor: float = 0.0  # 0 => exact dense dispatch; >0 => GShard
    # capacity dispatch via kubeflow_tpu.ops.moe (the large-E fast path)
    dtype: Any = jnp.bfloat16     # activation/compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True
    scan_layers: bool = True
    logits_softcap: float = 0.0
    # attention core: "dense" O(S²) (XLA-fused, fine to moderate S),
    # "blockwise" O(S·block) scan, "flash" Pallas kernel, "ring"/"ulysses"
    # sequence-parallel attention over the seq mesh axis (ppermute KV
    # rotation vs all_to_all seq↔heads re-shard; both long-context),
    # "auto" = flash on the TPU backend and dense (the XLA parity
    # oracle) elsewhere — the BERT/bidirectional route
    attention_impl: str = "dense"
    # flash KV tile edge. None (the default) resolves per kernel key +
    # shape class from the committed tile table
    # (kubeflow_tpu/ops/autotune.py + ops/tile_table.json — seeded with
    # the r5 chip-measured winners: 1024-edge tiles ran fwd+bwd 1.8x
    # the 512 rate at seq 8192; 2048 exceeds scoped VMEM) with an
    # analytic VMEM-budget fallback; an int pins an explicit override
    # for every flash kernel (the pre-PR behavior). Also the blockwise/
    # ring/ulysses KV tile (those cores default to 1024 when None).
    attention_block_k: Optional[int] = None
    # flash q-tile edge, independent of block_k since the autotune
    # plane split the square knob; None = table/auto, int = override
    attention_block_q: Optional[int] = None
    causal: bool = True           # False => bidirectional (encoder/BERT)
    seq_axis: str = "tp"          # mesh axis ring attention shards sequence over
    rules: AxisRules = DEFAULT_RULES  # logical-axis -> mesh-axis sharding rules
    # decode mode only: multi-token applies write from PER-ROW start
    # positions (speculative verification, ragged continuation) instead
    # of the contiguous shared-start prefill fast path
    ragged_decode: bool = False
    # decode mode only: paged KV cache. 0 => dense per-row cache
    # (B, max_seq_len, KH, Dh). >0 => the cache is a POOL of
    # ``kv_pages`` HBM blocks of ``kv_page_size`` tokens each, shared
    # by the batch through a per-row page table ("pages" cache var,
    # (B, max_seq_len/kv_page_size) int32 of physical page ids; the
    # sentinel value ``kv_pages`` marks an unmapped logical page —
    # writes through it scatter-drop). The serving engine owns page
    # allocation (kubeflow_tpu/serving/kvpool.py); the model only
    # reads/writes through the table.
    kv_page_size: int = 0
    kv_pages: int = 0
    # paged decode attention core (kv_page_size > 0, single-token
    # steps): "gather" materializes each row's logical KV view back to
    # a dense (B, max_seq_len, KH, Dh) tensor (the interpret-mode
    # fallback and the bit-parity oracle), "kernel" reads K/V straight
    # through the page table inside a Pallas kernel
    # (ops/paged_attention.py — HBM reads proportional to live pages),
    # "auto" picks the kernel in compiled mode (TPU backend) and the
    # gather elsewhere. Multi-token applies (prefill chunks, ragged
    # continuation) always take the gather path — the kernel is the
    # decode-step hot loop.
    paged_attention_impl: str = "auto"
    # paged kernel KV head-group compute block (ops/paged_attention.py
    # head_block): None = tile-table/auto (safe fallback: the per-head
    # loop, 1); an int overrides and must divide n_kv_heads
    paged_head_block: Optional[int] = None

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    def validate(self) -> None:
        if self.n_heads % max(self.n_kv_heads, 1):
            raise ValueError("n_heads must be a multiple of n_kv_heads")
        if self.n_experts and self.experts_per_token > self.n_experts:
            raise ValueError("experts_per_token > n_experts")
        if self.attention_impl not in ("dense", "blockwise", "flash",
                                       "ring", "ulysses", "auto"):
            raise ValueError(f"unknown attention_impl {self.attention_impl!r}")
        for knob in ("attention_block_q", "attention_block_k",
                     "paged_head_block"):
            v = getattr(self, knob)
            if v is not None and (not isinstance(v, int)
                                  or isinstance(v, bool) or v < 1):
                raise ValueError(
                    f"{knob} must be None (tile-table/auto) or a "
                    f"positive int, got {v!r}")
        if self.kv_page_size:
            if self.max_seq_len % self.kv_page_size:
                raise ValueError(
                    f"kv_page_size {self.kv_page_size} must divide "
                    f"max_seq_len {self.max_seq_len}")
            if self.kv_pages < 1:
                raise ValueError("paged decode needs kv_pages >= 1")
        if self.paged_attention_impl not in ("auto", "gather", "kernel"):
            raise ValueError(
                f"unknown paged_attention_impl "
                f"{self.paged_attention_impl!r}; valid: auto, gather, "
                "kernel")


def _constrain(x, rules: AxisRules, *names):
    """Logical sharding constraint; silently a no-op outside a mesh context."""
    return shard_constraint(x, names, rules)


# KV tile for the non-Pallas cores (blockwise scan, ring/ulysses inner
# loop) when attention_block_k is None: those cores have no tile table —
# 1024 is simply the pre-autotune default, kept so old behavior holds
_UNTUNED_BLOCK_K = 1024


class RMSNorm(nn.Module):
    eps: float = 1e-6
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        dtype = x.dtype
        x = x.astype(jnp.float32)
        scale = self.param(
            "scale", nn.initializers.ones, (x.shape[-1],), self.param_dtype
        )
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        x = x * jax.lax.rsqrt(var + self.eps)
        return (x * scale).astype(dtype)


def rope_tables(seq_len: int, head_dim: int, theta: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    freqs = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    angles = jnp.outer(pos, freqs)  # (S, Dh/2)
    return jnp.sin(angles), jnp.cos(angles)


def _rotate(x: jnp.ndarray, sin: jnp.ndarray,
            cos: jnp.ndarray) -> jnp.ndarray:
    """The rope rotation core; sin/cos arrive pre-broadcast to x's rank.
    ONE definition — training, prefill, and the per-row decode step must
    rotate identically or generation diverges from prefill."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )


def apply_rope(x: jnp.ndarray, sin: jnp.ndarray, cos: jnp.ndarray) -> jnp.ndarray:
    """x: (B, S, H, Dh); rotate pairs (even, odd) halves interleaved as split."""
    return _rotate(x, sin[None, :, None, :].astype(x.dtype),
                   cos[None, :, None, :].astype(x.dtype))


class Attention(nn.Module):
    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, sin, cos, kv_len=None):
        c = self.config
        B, S, D = x.shape
        H, KH, Dh = c.n_heads, c.n_kv_heads, c.head_dim
        init = nn.initializers.normal(stddev=D ** -0.5)

        wq = self.param("q_proj", init, (D, H, Dh), c.param_dtype)
        wk = self.param("k_proj", init, (D, KH, Dh), c.param_dtype)
        wv = self.param("v_proj", init, (D, KH, Dh), c.param_dtype)
        wo = self.param("o_proj", init, (H, Dh, D), c.param_dtype)

        q = jnp.einsum("bsd,dhk->bshk", x, wq.astype(c.dtype))
        k = jnp.einsum("bsd,dhk->bshk", x, wk.astype(c.dtype))
        v = jnp.einsum("bsd,dhk->bshk", x, wv.astype(c.dtype))

        if self.decode:
            out = self._decode_attend(q, k, v, sin, cos)
            out = jnp.einsum("bshk,hkd->bsd", out, wo.astype(c.dtype))
            return _constrain(out, c.rules, "batch", "seq", None)

        if c.attention_impl in ("ring", "ulysses"):
            # sequence stays sharded through attention (SP paths); heads
            # replicate — the inverse of the tensor-parallel dense layout
            q = _constrain(q, c.rules, "batch", "seq", None, None)
        else:
            q = _constrain(q, c.rules, "batch", None, "heads", None)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)

        if c.attention_impl != "ulysses":
            # GQA repeat for the cores that want full heads; ulysses
            # repeats AFTER its KV all_to_alls so the collectives carry
            # only the distinct KV heads (kubeflow_tpu/ops/attention.py)
            from kubeflow_tpu.ops.attention import gqa_repeat

            k, v = gqa_repeat(q, k, v)

        out = self._attend(q, k, v, kv_len=kv_len)
        out = jnp.einsum("bshk,hkd->bsd", out, wo.astype(c.dtype))
        return _constrain(out, c.rules, "batch", "seq", None)

    def _decode_attend(self, q, k, v, sin_full, cos_full):
        """Autoregressive attention with a KV cache (static shapes).

        ``sin_full``/``cos_full`` span ``max_seq_len``. The cache carries
        PER-ROW write positions: every row's tokens sit contiguously at
        their logical positions (physical slot == logical position), so
        masking stays purely causal even for ragged batches — everything
        under one jit with no data-dependent shapes (one compiled prefill
        per prompt bucket, one compiled step).

        - multi-token (S > 1): each row writes S tokens from its OWN
          current position (fresh prefill: 0; prefix continuation and
          speculative verification: ragged per-row starts); the caller
          then resets positions to each row's true length (see
          :func:`kubeflow_tpu.models.decode.prefill`) — a row's pad
          tail is masked (kv_pos > its positions) until the generated
          tokens overwrite it;
        - step (S == 1): per-row scatter write + per-row rope position.
        """
        c = self.config
        if c.kv_page_size:
            return self._paged_decode_attend(q, k, v, sin_full, cos_full)
        B, S, KH, Dh = k.shape
        Smax = c.max_seq_len

        pos_var = self.variable("cache", "positions",
                                lambda: jnp.zeros((B,), jnp.int32))
        ck = self.variable("cache", "k", jnp.zeros, (B, Smax, KH, Dh),
                           c.dtype)
        cv = self.variable("cache", "v", jnp.zeros, (B, Smax, KH, Dh),
                           c.dtype)
        pos = pos_var.value  # (B,)

        from kubeflow_tpu.ops.attention import NEG_INF, gqa_repeat

        if S == 1:
            # one token per row at its own position
            sin = jnp.take(sin_full, pos, axis=0)[:, None, None, :].astype(
                q.dtype)
            cos = jnp.take(cos_full, pos, axis=0)[:, None, None, :].astype(
                q.dtype)
            q = _rotate(q, sin, cos)
            k = _rotate(k, sin, cos)
            rows = jnp.arange(B)
            ck.value = ck.value.at[rows, pos].set(k[:, 0])
            cv.value = cv.value.at[rows, pos].set(v[:, 0])
            q_pos = pos[:, None]  # (B, 1)
        elif c.ragged_decode:
            # multi-token with per-row starts (speculative verify,
            # ragged prefix continuation): per-row rope gather + one
            # batched scatter. Statically selected — the common
            # shared-start prefill keeps its contiguous slice-update.
            q_pos = pos[:, None] + jnp.arange(S)[None, :]  # (B, S)
            sin = jnp.take(sin_full, q_pos, axis=0)[:, :, None, :].astype(
                q.dtype)
            cos = jnp.take(cos_full, q_pos, axis=0)[:, :, None, :].astype(
                q.dtype)
            q = _rotate(q, sin, cos)
            k = _rotate(k, sin, cos)
            rows2d = jnp.broadcast_to(jnp.arange(B)[:, None], (B, S))
            ck.value = ck.value.at[rows2d, q_pos].set(k)
            cv.value = cv.value.at[rows2d, q_pos].set(v)
        else:
            # prefill: rows share a start (a fresh cache starts at 0;
            # the engine's 1-row prefix continuation shares trivially)
            idx = pos[0]
            sin = jax.lax.dynamic_slice_in_dim(sin_full, idx, S, 0)
            cos = jax.lax.dynamic_slice_in_dim(cos_full, idx, S, 0)
            q = apply_rope(q, sin, cos)
            k = apply_rope(k, sin, cos)
            ck.value = jax.lax.dynamic_update_slice_in_dim(ck.value, k,
                                                           idx, axis=1)
            cv.value = jax.lax.dynamic_update_slice_in_dim(cv.value, v,
                                                           idx, axis=1)
            q_pos = (idx + jnp.arange(S))[None, :]  # (1, S) → rows share
        pos_var.value = pos + S

        kc, vc = gqa_repeat(q, ck.value, cv.value)
        logits = jnp.einsum("bshd,bthd->bhst", q, kc).astype(jnp.float32)
        logits = logits * (Dh ** -0.5)
        kv_pos = jnp.arange(Smax)
        # (B or 1, S, Smax): per-row causal bound
        mask = kv_pos[None, None, :] <= q_pos[:, :, None]
        logits = jnp.where(mask[:, None], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhst,bthd->bshd", probs, vc)

    def _paged_decode_attend(self, q, k, v, sin_full, cos_full):
        """Autoregressive attention over a PAGED KV pool.

        The cache is a pool of ``kv_pages`` HBM blocks of
        ``kv_page_size`` tokens shared by the whole batch; each row maps
        logical pages to physical pages through its "pages" table row.
        One code path serves every S (step, prefill chunk, ragged
        continuation): writes scatter each token to
        ``(pages[b, pos // ps], pos % ps)`` and reads gather the row's
        logical view back to ``(B, max_seq_len, KH, Dh)`` before the
        exact attention math of the dense path — live positions carry
        identical values, garbage positions are masked to NEG_INF
        exactly as dense masks its unwritten tail, so greedy decode is
        token-identical to the dense cache.

        Safety contract with the allocator (serving/kvpool.py):

        - a logical page mapped to the sentinel id ``kv_pages`` (or a
          position past ``max_seq_len``) writes out of bounds, which
          scatter DROPS — idle/disarmed rows can step forever without
          touching live pages;
        - reads through the sentinel clamp to an arbitrary real page;
          those positions are causally masked, and the exactly-zero
          masked probabilities keep garbage out of the output bitwise;
        - two rows never map the same WRITABLE page; prefix pages are
          shared read-only (rows only write at positions >= their own
          start, which the engine keeps past the shared region).
        """
        c = self.config
        B, S, KH, Dh = k.shape
        Smax = c.max_seq_len
        ps = c.kv_page_size
        n_log = Smax // ps
        P = c.kv_pages

        pos_var = self.variable("cache", "positions",
                                lambda: jnp.zeros((B,), jnp.int32))
        pages_var = self.variable(
            "cache", "pages", lambda: jnp.full((B, n_log), P, jnp.int32))
        ck = self.variable("cache", "k", jnp.zeros, (P, ps, KH, Dh),
                           c.dtype)
        cv = self.variable("cache", "v", jnp.zeros, (P, ps, KH, Dh),
                           c.dtype)
        pos = pos_var.value        # (B,)
        pages = pages_var.value    # (B, n_log)

        from kubeflow_tpu.ops.attention import NEG_INF, gqa_repeat

        q_pos = pos[:, None] + jnp.arange(S)[None, :]       # (B, S)
        safe_pos = jnp.minimum(q_pos, Smax - 1)
        sin = jnp.take(sin_full, safe_pos, axis=0)[:, :, None, :].astype(
            q.dtype)
        cos = jnp.take(cos_full, safe_pos, axis=0)[:, :, None, :].astype(
            q.dtype)
        q = _rotate(q, sin, cos)
        k = _rotate(k, sin, cos)
        # physical write targets; overruns and unmapped pages resolve to
        # pool index P, which the scatter drops
        pg = jnp.take_along_axis(pages, safe_pos // ps, axis=1)  # (B, S)
        pg = jnp.where(q_pos < Smax, pg, P)
        off = q_pos % ps
        ck.value = ck.value.at[pg, off].set(k, mode="drop")
        cv.value = cv.value.at[pg, off].set(v, mode="drop")
        pos_var.value = pos + S

        impl = c.paged_attention_impl
        if S == 1 and (impl == "kernel" or (impl == "auto"
                                            and jax.default_backend()
                                            == "tpu")):
            # decode-step hot loop: read K/V straight through the page
            # table inside the Pallas kernel — HBM traffic proportional
            # to live pages, no dense view, no QH-wide GQA copy. The
            # gather below remains the bit-parity oracle (greedy streams
            # are asserted token-identical, tests/test_engine_paged.py)
            # and the multi-token (chunk/ragged) path.
            from kubeflow_tpu.ops.paged_attention import (
                paged_decode_attention,
            )

            out = paged_decode_attention(
                q[:, 0], ck.value, cv.value, pages, pos,
                sm_scale=Dh ** -0.5, head_block=c.paged_head_block)
            return out[:, None]

        # gather each row's logical view: (B, n_log, ps, KH, Dh) ->
        # (B, Smax, KH, Dh); sentinel entries clamp to a real page and
        # are masked below
        kc = jnp.take(ck.value, pages, axis=0,
                      mode="clip").reshape(B, Smax, KH, Dh)
        vc = jnp.take(cv.value, pages, axis=0,
                      mode="clip").reshape(B, Smax, KH, Dh)
        kc, vc = gqa_repeat(q, kc, vc)
        logits = jnp.einsum("bshd,bthd->bhst", q, kc).astype(jnp.float32)
        logits = logits * (Dh ** -0.5)
        kv_pos = jnp.arange(Smax)
        mask = kv_pos[None, None, :] <= q_pos[:, :, None]   # (B, S, Smax)
        logits = jnp.where(mask[:, None], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        return jnp.einsum("bhst,bthd->bshd", probs, vc)

    def _attend(self, q, k, v, kv_len=None):
        """Dispatch to the configured attention core (causal per config).

        ``attention_impl="auto"`` routes through the flash kernels on
        the TPU backend and the dense XLA path elsewhere — dense is the
        parity oracle the flash path is gated against (the BERT
        bidirectional route, tests/test_bert.py). ``kv_len`` is the
        per-row valid-length padding mask; only the dense and flash
        cores implement it, so any other impl refuses it loudly.
        """
        c = self.config
        from kubeflow_tpu.ops import attention as att  # local: no cycle

        impl = c.attention_impl
        if impl == "auto":
            impl = "flash" if jax.default_backend() == "tpu" else "dense"
        if kv_len is not None and impl not in ("dense", "flash"):
            raise ValueError(
                f"kv_len padding mask is not supported by "
                f"attention_impl={impl!r} (dense and flash only)")
        block_k = c.attention_block_k or _UNTUNED_BLOCK_K
        if impl == "dense":
            return att.reference_attention(q, k, v, causal=c.causal,
                                           kv_len=kv_len)
        if impl == "blockwise":
            return att.blockwise_attention(
                q, k, v, causal=c.causal, block_k=block_k
            )
        if impl == "flash":
            from kubeflow_tpu.ops import autotune

            # flash requires block | seq: explicit overrides are fitted
            # to the largest divisor within their budget (the pre-split
            # behavior, now per knob); None stays None so the kernels
            # resolve each kernel key from the tile table. Degenerate
            # divisors fall back to blockwise, as before.
            S = q.shape[1]
            if autotune.fit_block(
                    S, block_k if c.attention_block_k else
                    autotune.MAX_TILE_EDGE) < 16:
                if kv_len is not None:
                    raise ValueError(
                        f"kv_len padding mask needs a flash-tileable "
                        f"seq len, got {S}")
                return att.blockwise_attention(
                    q, k, v, causal=c.causal, block_k=block_k
                )
            bq = (autotune.fit_block(S, c.attention_block_q)
                  if c.attention_block_q else None)
            bk = (autotune.fit_block(S, c.attention_block_k)
                  if c.attention_block_k else None)
            return att.flash_attention(q, k, v, c.causal, bq, bk, None,
                                       None, kv_len)
        # ring / ulysses: sequence-parallel over the seq mesh axis;
        # partial-manual shard_map (batch/other axes stay auto)
        from kubeflow_tpu import compat

        mesh = compat.current_mesh()
        if mesh.empty or c.seq_axis not in mesh.axis_names:
            k, v = att.gqa_repeat(q, k, v)  # ulysses deferred the repeat
            return att.blockwise_attention(
                q, k, v, causal=c.causal, block_k=block_k
            )
        import functools

        from jax.sharding import PartitionSpec as P

        if c.attention_impl == "ulysses":
            core = functools.partial(
                att.ulysses_attention, axis_name=c.seq_axis,
                causal=c.causal, block_k=block_k)
        else:
            core = functools.partial(
                att.ring_attention, axis_name=c.seq_axis, causal=c.causal)
        spec = P(None, c.seq_axis, None, None)
        fn = compat.shard_map(
            core,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            axis_names={c.seq_axis},
        )
        return fn(q, k, v)


class Mlp(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        D, F = c.d_model, c.d_ff
        init = nn.initializers.normal(stddev=D ** -0.5)
        w_gate = self.param("gate_proj", init, (D, F), c.param_dtype)
        w_up = self.param("up_proj", init, (D, F), c.param_dtype)
        w_down = self.param("down_proj", init, (F, D), c.param_dtype)
        h = jax.nn.silu(x @ w_gate.astype(c.dtype)) * (x @ w_up.astype(c.dtype))
        h = _constrain(h, c.rules, "batch", None, "mlp")
        return _constrain(h @ w_down.astype(c.dtype), c.rules, "batch", "seq", None)


class MoeMlp(nn.Module):
    """Exact top-k MoE with dense one-hot dispatch (static shapes)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        c = self.config
        D, F, E, K = c.d_model, c.d_ff, c.n_experts, c.experts_per_token
        init = nn.initializers.normal(stddev=D ** -0.5)
        w_router = self.param("router", init, (D, E), jnp.float32)
        w_gate = self.param("gate_proj", init, (E, D, F), c.param_dtype)
        w_up = self.param("up_proj", init, (E, D, F), c.param_dtype)
        w_down = self.param("down_proj", init, (E, F, D), c.param_dtype)

        gate_logits = x.astype(jnp.float32) @ w_router  # (B, S, E)

        if c.moe_capacity_factor > 0:
            # GShard capacity dispatch: experts run once over (E, C, D)
            # buffers; with "expert"-sharded weights XLA inserts the
            # AllToAll over the ep group (kubeflow_tpu/ops/moe.py)
            from kubeflow_tpu.ops.moe import capacity_moe  # local: no cycle

            B, S, _ = x.shape

            def expert_fn(xe):  # (E, C, D) -> (E, C, D)
                h = jnp.einsum("ecd,edf->ecf", xe, w_gate.astype(xe.dtype))
                u = jnp.einsum("ecd,edf->ecf", xe, w_up.astype(xe.dtype))
                h = jax.nn.silu(h) * u
                h = _constrain(h, c.rules, "expert", None, "expert_mlp")
                return jnp.einsum("ecf,efd->ecd", h, w_down.astype(xe.dtype))

            y, aux = capacity_moe(
                x.reshape(B * S, D),
                gate_logits.reshape(B * S, E),
                expert_fn,
                k=K,
                capacity_factor=c.moe_capacity_factor,
            )
            self.sow("losses", "moe_aux", aux)
            return _constrain(y.reshape(B, S, D), c.rules, "batch", "seq", None)

        weights, idx = jax.lax.top_k(gate_logits, K)
        weights = jax.nn.softmax(weights, axis=-1)      # (B, S, K)
        # combine[b, s, e] = sum_k weights[b,s,k] * [idx[b,s,k] == e]
        combine = jnp.sum(
            jax.nn.one_hot(idx, E, dtype=jnp.float32) * weights[..., None], axis=2
        )  # (B, S, E)
        combine = combine.astype(c.dtype)

        # Dense dispatch: every expert sees every token, masked by combine.
        # Experts shard over the "expert" logical axis (EP); with E experts on
        # e_p shards each device computes E/e_p of the einsum's leading dim.
        h = jnp.einsum("bsd,edf->bsef", x, w_gate.astype(c.dtype))
        u = jnp.einsum("bsd,edf->bsef", x, w_up.astype(c.dtype))
        h = jax.nn.silu(h) * u
        # batch keeps the dp axis here (expert weights are dp-sharded, so
        # XLA gathers expert shards within the dp group); a capacity-based
        # all_to_all dispatch that truly keeps experts resident is the
        # planned fast path.
        h = _constrain(h, c.rules, "batch", None, None, "expert_mlp")
        y = jnp.einsum("bsef,efd->bsed", h, w_down.astype(c.dtype))
        y = jnp.einsum("bsed,bse->bsd", y, combine)

        # load-balancing auxiliary loss (Switch-style): mean prob * fraction routed
        probs = jax.nn.softmax(gate_logits, axis=-1)
        density = jnp.mean(combine.astype(jnp.float32) > 0, axis=(0, 1))
        mean_prob = jnp.mean(probs, axis=(0, 1))
        self.sow("losses", "moe_aux", E * jnp.sum(density * mean_prob))
        return _constrain(y, c.rules, "batch", "seq", None)


class Block(nn.Module):
    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, aux):
        # aux is (sin, cos) or (sin, cos, kv_len) — the optional third
        # element is the per-row valid-length padding mask the BERT
        # encoder threads through every block (models/bert.py)
        sin, cos = aux[0], aux[1]
        kv_len = aux[2] if len(aux) > 2 else None
        c = self.config
        h = RMSNorm(param_dtype=c.param_dtype, name="attn_norm")(x)
        x = x + Attention(c, decode=self.decode, name="attn")(h, sin, cos,
                                                              kv_len)
        h = RMSNorm(param_dtype=c.param_dtype, name="mlp_norm")(x)
        mlp = MoeMlp(c, name="moe") if c.n_experts else Mlp(c, name="mlp")
        x = x + mlp(h)
        return x, None


class Transformer(nn.Module):
    config: TransformerConfig
    # autoregressive mode: attention maintains a "cache" collection (KV
    # cache + write index, stacked over layers by nn.scan); apply with
    # mutable=["cache"] — see kubeflow_tpu/models/decode.py
    decode: bool = False
    # return the post-final-norm hidden states (B, S, D) instead of
    # logits: the long-context training path computes the vocab
    # projection CHUNKED inside the loss (train/trainer.py:
    # chunked_next_token_loss) — materializing (B, S, V) f32 logits at
    # seq 65536 is ~8.4 GB and capsizes HBM before attention does
    return_hidden: bool = False

    @nn.compact
    def __call__(self, tokens: jnp.ndarray) -> jnp.ndarray:
        """tokens: (B, S) int32 -> logits (B, S, V) float32."""
        c = self.config
        c.validate()
        B, S = tokens.shape
        # tied in/out embedding: d^-0.5 init keeps untrained logits O(1) so
        # the initial loss sits near ln(vocab) instead of exploding
        embed = self.param(
            "token_embed",
            nn.initializers.normal(stddev=c.d_model ** -0.5),
            (c.vocab_size, c.d_model),
            c.param_dtype,
        )
        x = jnp.take(embed.astype(c.dtype), tokens, axis=0)
        x = _constrain(x, c.rules, "batch", "seq", None)
        # decode mode uses absolute positions: full tables, sliced at the
        # cache index inside each attention
        sin, cos = rope_tables(c.max_seq_len if self.decode else S,
                               c.head_dim, c.rope_theta)

        block_cls = Block
        if c.remat and not self.decode:
            block_cls = nn.remat(Block, prevent_cse=False)
        if c.scan_layers:
            x, _ = nn.scan(
                block_cls,
                variable_axes={"params": 0, "losses": 0, "cache": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=c.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(c, decode=self.decode, name="blocks")(x, (sin, cos))
        else:
            for i in range(c.n_layers):
                x, _ = block_cls(c, decode=self.decode,
                                 name=f"block_{i}")(x, (sin, cos))

        x = RMSNorm(param_dtype=c.param_dtype, name="final_norm")(x)
        if self.return_hidden:
            return _constrain(x, c.rules, "batch", "seq", None)
        logits = jnp.einsum(
            "bsd,vd->bsv", x, embed.astype(c.dtype)
        ).astype(jnp.float32)
        if c.logits_softcap:
            logits = c.logits_softcap * jnp.tanh(logits / c.logits_softcap)
        return _constrain(logits, c.rules, "batch", None, "vocab")


# ---------------------------------------------------------------------------
# Parameter sharding: param-path -> logical axes -> PartitionSpec
# ---------------------------------------------------------------------------

_PARAM_AXES = {
    "token_embed": ("vocab", "embed"),
    "q_proj": ("embed", "heads", "kv"),
    "k_proj": ("embed", "heads", "kv"),
    "v_proj": ("embed", "heads", "kv"),
    "o_proj": ("heads", "kv", "embed"),
    "gate_proj": ("embed", "mlp"),
    "up_proj": ("embed", "mlp"),
    "down_proj": ("mlp", "embed"),
    "router": ("embed", None),
    "scale": (None,),
}

_MOE_PARAM_AXES = {
    "gate_proj": ("expert", "embed", "expert_mlp"),
    "up_proj": ("expert", "embed", "expert_mlp"),
    "down_proj": ("expert", "expert_mlp", "embed"),
}


def _path_names(path) -> Tuple[str, ...]:
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "name"):
            names.append(str(p.name))
    return tuple(names)


def leaf_logical_axes(path, leaf) -> Tuple[Optional[str], ...]:
    """Logical axes for one pytree leaf, by param-name matching.

    Works on raw param trees and on whole optimizer/train states (optax's
    mu/nu mirror the param tree, so the same trailing names match; unknown
    leaves and scalars fall back to replicated).
    """
    names = _path_names(path)
    name = names[-1] if names else ""
    ndim = getattr(leaf, "ndim", 0)  # non-array leaves (e.g. a python-int
    if ndim == 0:                    # TrainState.step) replicate
        return ()
    in_moe = "moe" in names
    table = _MOE_PARAM_AXES if in_moe and name in _MOE_PARAM_AXES else _PARAM_AXES
    axes = table.get(name)
    if axes is None:
        return (None,) * ndim
    if "blocks" in names:  # scanned: leading layer axis
        axes = (None,) + tuple(axes)
    if len(axes) != ndim:
        raise ValueError(f"axes {axes} rank != leaf {names} rank {ndim}")
    return tuple(axes)


def param_logical_axes(params) -> Any:
    """Logical-axis tuples for every param leaf, keyed by path name matching.

    Scanned blocks carry a leading layer axis; it maps to the ``stage``
    logical axis only under pipeline parallelism, so here it is ``None``
    (replicated layer stack = no pp) — the pipeline wrapper re-annotates it.
    """
    return jax.tree_util.tree_map_with_path(leaf_logical_axes, params)


def param_partition_specs(params, rules: AxisRules = DEFAULT_RULES) -> Any:
    axes = param_logical_axes(params)
    return jax.tree_util.tree_map(
        lambda a: logical_to_mesh_axes(a, rules),
        axes,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def tiny_config(**overrides) -> TransformerConfig:
    """A config small enough for CPU tests but exercising every code path."""
    base = dict(
        vocab_size=256,
        d_model=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq_len=64,
        dtype=jnp.float32,
        remat=False,
    )
    base.update(overrides)
    return TransformerConfig(**base)
