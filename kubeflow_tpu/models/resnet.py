"""ResNet-v1.5 family in JAX/Flax — the benchmark workload.

The reference's headline workload is ``tf_cnn_benchmarks`` ResNet-50 run as a
TFJob (``/root/reference/kubeflow/examples/prototypes/tf-job-simple-v1.jsonnet:28-38``,
``/root/reference/tf-controller-examples/tf-cnn/create_job_specs.py:101-120``).
That code lives outside the reference repo; here the model is in-framework so
the kubebench-equivalent pipeline (``kubeflow_tpu/bench``) benchmarks a real
training loop on TPU.

TPU notes: NHWC layout (XLA's native conv layout on TPU), bf16 compute with
fp32 BN statistics, no data-dependent control flow.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # BN compute dtype for the *output*; statistics are always accumulated
    # in f32 inside flax. bf16 halves the activation traffic of every
    # norm+relu — on TPU the model is HBM-bound, not FLOP-bound, there.
    bn_dtype: Any = jnp.bfloat16
    # shared BN constants — every norm in the model (stem, blocks, and
    # the fused bn2conv3 path) reads these, so a fused/unfused A/B can
    # never diverge on a hardcoded momentum or epsilon
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    # "conv": plain 7x7/2 stem. "space_to_depth": rearrange 224²×3 images
    # into 56²×48 blocks first (MLPerf-style): the 7x7 conv over 3 channels
    # wastes almost the whole 128-lane MXU contraction; over 48 channels it
    # tiles well. Mathematically the same function class (the equivalent
    # 2x2/1 conv sees every original pixel of the 4x4 block).
    stem: str = "space_to_depth"
    # int8 forward-saved conv inputs (ops/act_compress.py): halves the
    # backward's activation read traffic on the HBM-bound train step at
    # the cost of bounded gradient quantization error — PERF.md's open
    # bandwidth lever; loss-parity gated in tests/test_act_compress.py
    act_compress: bool = False
    # fuse bn2-apply+ReLU into conv3's GEMM input side (ops/bnconv.py):
    # removes one full read+write of the mid-block activation per
    # bottleneck — PERF.md's named normalize-pass lever; parity gated
    # in tests/test_bnconv.py
    fused_bn_conv: bool = False


class FusedBnReluConv(nn.Module):
    """``relu(batchnorm(x)) @ 1x1-conv`` with the normalize pass fused
    into the GEMM's input side (``ops/bnconv.py``): the (N, H, W, C)
    activation is read ONCE instead of read + write + read. Owns the
    same BatchNorm bookkeeping (scale/bias params, running batch_stats,
    f32 statistics) and conv kernel shape as the ``nn.BatchNorm`` +
    ``nn.Conv`` pair it replaces; statistics gradients flow through the
    plain jnp mean/var below — the custom_vjp only covers the GEMM
    sandwich. Flag-gated (``ResNetConfig.fused_bn_conv``) and REJECTED
    on the round-5 chip A/B (−39% img/s, +34 GB/step: the custom-op
    boundary breaks XLA's bn3-stats-into-conv3 fusion and bf16
    backward chains — PERF.md); kept in-tree, off by default, as the
    documented negative result."""

    features: int
    use_running_average: bool
    dtype: Any
    param_dtype: Any
    # the dtype the unfused path would materialize the BN output in;
    # threaded into the fused op's act_dtype so bn_dtype != f32 rounds
    # identically on both sides of an A/B
    bn_dtype: Any = jnp.float32
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x):
        from kubeflow_tpu.ops.bnconv import fused_scale_relu_matmul

        C = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (C,),
                           self.param_dtype)
        bias = self.param("bias", nn.initializers.zeros, (C,),
                          self.param_dtype)
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (1, 1, C, self.features), self.param_dtype)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((C,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((C,), jnp.float32))
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32).reshape(-1, C)
            mean = jnp.mean(xf, axis=0)
            var = jnp.mean(jnp.square(xf), axis=0) - jnp.square(mean)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mean
                ra_var.value = m * ra_var.value + (1 - m) * var
        a = scale.astype(jnp.float32) * jax.lax.rsqrt(var + self.epsilon)
        b = bias.astype(jnp.float32) - mean * a
        lead = x.shape[:-1]
        out = fused_scale_relu_matmul(
            x.reshape(-1, C).astype(self.dtype), a, b,
            kernel.reshape(C, self.features).astype(self.dtype),
            None, self.bn_dtype)
        return out.reshape(*lead, self.features)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any
    param_dtype: Any
    bn_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5
    act_compress: bool = False
    fused_bn_conv: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        if self.act_compress:
            from kubeflow_tpu.ops.act_compress import Int8Conv

            # same param names/shapes as nn.Conv — checkpoints carry over
            conv = partial(Int8Conv, dtype=self.dtype,
                           param_dtype=self.param_dtype)
        else:
            conv = partial(
                nn.Conv, use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype
            )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=self.bn_epsilon,
            # statistics are always reduced in f32 inside flax; bn_dtype only
            # sets the normalized output's dtype
            dtype=self.bn_dtype,
            param_dtype=self.param_dtype,
        )
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides), name="conv2")(y)
        if self.fused_bn_conv:
            # bn2 -> relu -> conv3 in one pass over the conv2 output;
            # same bn_dtype/momentum/epsilon as the norm partial — the
            # constants come from ResNetConfig so they cannot drift
            y = FusedBnReluConv(
                self.filters * 4, use_running_average=not train,
                dtype=self.dtype, param_dtype=self.param_dtype,
                bn_dtype=self.bn_dtype,
                momentum=self.bn_momentum, epsilon=self.bn_epsilon,
                name="bn2conv3")(y)
        else:
            y = nn.relu(norm(name="bn2")(y))
            y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="proj_conv",
            )(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y.astype(residual.dtype))


class ResNet(nn.Module):
    config: ResNetConfig = ResNetConfig()

    @nn.compact
    def __call__(self, images: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """images: (B, H, W, 3) -> logits (B, num_classes) float32."""
        c = self.config
        if c.act_compress and c.fused_bn_conv:
            # the fused bn2conv3 path bypasses the Int8Conv wrapper for
            # conv3 — allowing both would silently measure an
            # undocumented hybrid in any A/B
            raise ValueError(
                "act_compress and fused_bn_conv cannot combine: conv3 "
                "would lose activation compression inside the fused op")
        x = images.astype(c.dtype)
        if c.stem == "space_to_depth":
            # Fold 4×4 pixel blocks into channels: 224²×3 → 56²×48. The
            # MXU contracts over KH·KW·Cin; at Cin=3 the (8,128)-tiled
            # input pads 3→8 channels and wastes most of the systolic
            # array, so the stem conv runs an order of magnitude below
            # peak (MLPerf ResNet uses the same rearrangement). The 2×2
            # stride-1 conv below sees every pixel of an 8×8 patch —
            # same receptive field class as the 7×7/2+maxpool stem it
            # replaces, at one third the FLOPs.
            B, H, W, C = x.shape
            if H % 4 or W % 4:
                raise ValueError(f"space_to_depth stem needs H,W % 4 == 0, "
                                 f"got {H}x{W}")
            x = x.reshape(B, H // 4, 4, W // 4, 4, C)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // 4, W // 4,
                                                      16 * C)
            x = nn.Conv(
                c.width, (2, 2), strides=(1, 1), padding="SAME",
                use_bias=False, dtype=c.dtype, param_dtype=c.param_dtype,
                name="stem_conv_s2d",
            )(x)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=c.bn_momentum,
                epsilon=c.bn_epsilon, dtype=c.bn_dtype,
                param_dtype=c.param_dtype, name="stem_bn",
            )(x)
            x = nn.relu(x)  # already 56²; the maxpool's downsample is folded
        else:
            x = nn.Conv(
                c.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                use_bias=False, dtype=c.dtype, param_dtype=c.param_dtype,
                name="stem_conv",
            )(x)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=c.bn_momentum,
                epsilon=c.bn_epsilon, dtype=c.bn_dtype,
                param_dtype=c.param_dtype, name="stem_bn",
            )(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(c.stage_sizes):
            for j in range(n_blocks):
                x = BottleneckBlock(
                    filters=c.width * 2 ** i,
                    strides=2 if j == 0 and i > 0 else 1,
                    dtype=c.dtype,
                    param_dtype=c.param_dtype,
                    bn_dtype=c.bn_dtype,
                    bn_momentum=c.bn_momentum,
                    bn_epsilon=c.bn_epsilon,
                    act_compress=c.act_compress,
                    fused_bn_conv=c.fused_bn_conv,
                    name=f"stage{i}_block{j}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(
            c.num_classes, dtype=jnp.float32, param_dtype=c.param_dtype, name="head",
        )(x.astype(jnp.float32))


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(ResNetConfig(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw))


def resnet18_thin(num_classes: int = 10) -> ResNet:
    """Small variant for CPU tests (plain conv stem: test inputs are tiny)."""
    return ResNet(ResNetConfig(stage_sizes=(1, 1), num_classes=num_classes, width=16,
                               dtype=jnp.float32, bn_dtype=jnp.float32,
                               stem="conv"))
