"""ResNet-v1.5 family in JAX/Flax — the benchmark workload.

The reference's headline workload is ``tf_cnn_benchmarks`` ResNet-50 run as a
TFJob (``/root/reference/kubeflow/examples/prototypes/tf-job-simple-v1.jsonnet:28-38``,
``/root/reference/tf-controller-examples/tf-cnn/create_job_specs.py:101-120``).
That code lives outside the reference repo; here the model is in-framework so
the kubebench-equivalent pipeline (``kubeflow_tpu/bench``) benchmarks a real
training loop on TPU.

TPU notes: NHWC layout (XLA's native conv layout on TPU), bf16 compute with
fp32 BN statistics, no data-dependent control flow.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(
            nn.Conv, use_bias=False, dtype=self.dtype, param_dtype=self.param_dtype
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=jnp.float32,
            param_dtype=self.param_dtype,
        )
        residual = x
        y = conv(self.filters, (1, 1), name="conv1")(x)
        y = nn.relu(norm(name="bn1")(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides), name="conv2")(y)
        y = nn.relu(norm(name="bn2")(y))
        y = conv(self.filters * 4, (1, 1), name="conv3")(y)
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides),
                name="proj_conv",
            )(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y.astype(residual.dtype))


class ResNet(nn.Module):
    config: ResNetConfig = ResNetConfig()

    @nn.compact
    def __call__(self, images: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        """images: (B, H, W, 3) -> logits (B, num_classes) float32."""
        c = self.config
        x = images.astype(c.dtype)
        x = nn.Conv(
            c.width, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=c.dtype, param_dtype=c.param_dtype, name="stem_conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=jnp.float32, param_dtype=c.param_dtype, name="stem_bn",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(c.stage_sizes):
            for j in range(n_blocks):
                x = BottleneckBlock(
                    filters=c.width * 2 ** i,
                    strides=2 if j == 0 and i > 0 else 1,
                    dtype=c.dtype,
                    param_dtype=c.param_dtype,
                    name=f"stage{i}_block{j}",
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(
            c.num_classes, dtype=jnp.float32, param_dtype=c.param_dtype, name="head",
        )(x.astype(jnp.float32))


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(ResNetConfig(stage_sizes=(3, 4, 6, 3), num_classes=num_classes, **kw))


def resnet18_thin(num_classes: int = 10) -> ResNet:
    """Small variant for CPU tests."""
    return ResNet(ResNetConfig(stage_sizes=(1, 1), num_classes=num_classes, width=16,
                               dtype=jnp.float32))
