"""Deployment CLI (kfctl parity): ``python -m kubeflow_tpu.cli <cmd>``."""

from kubeflow_tpu.cli.main import main  # noqa: F401
