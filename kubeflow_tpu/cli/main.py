"""``ctl`` — the deployment CLI (kfctl parity).

Subcommand surface mirrors kfctl: init/generate/apply/delete/show/version
(``/root/reference/bootstrap/cmd/kfctl/cmd/{init,generate,apply,delete,
root}.go``), plus ``components`` to list the registry. An *app directory*
holds ``app.yaml`` (the DeploymentConfig) and generated ``manifests/``;
phases mirror the coordinator's ALL/PLATFORM/K8S split
(``coordinator.go:715-917``) with platform provisioning delegated to the
platform layer.
"""

from __future__ import annotations

import argparse
import logging
import os
import re
import sys
from typing import List, Optional

import yaml

import kubeflow_tpu
from kubeflow_tpu.config import DeploymentConfig, preset
from kubeflow_tpu.k8s.apply import apply_all, delete_all
from kubeflow_tpu.k8s.client import (
    API_NOT_FOUND,
    ApiError,
    HttpKubeClient,
    KubeClient,
)
from kubeflow_tpu.k8s.fakefile import FileBackedFakeClient
from kubeflow_tpu.k8s.objects import Obj
from kubeflow_tpu.manifests import list_components, render_all

log = logging.getLogger("ctl")

APP_YAML = "app.yaml"
MANIFEST_DIR = "manifests"


def _app_config(app_dir: str) -> DeploymentConfig:
    path = os.path.join(app_dir, APP_YAML)
    if not os.path.exists(path):
        raise SystemExit(f"{path} not found — run `ctl init` first")
    return DeploymentConfig.load(path)


def _client(args) -> KubeClient:
    if args.server:
        return HttpKubeClient(base_url=args.server, verify=not args.insecure)
    state = args.fake_state or os.path.join(args.app_dir, ".cluster.json")
    return FileBackedFakeClient(state)


def _manifest_path(app_dir: str) -> str:
    return os.path.join(app_dir, MANIFEST_DIR)


def cmd_init(args) -> int:
    app_dir = args.app_dir
    os.makedirs(app_dir, exist_ok=True)
    path = os.path.join(app_dir, APP_YAML)
    if os.path.exists(path) and not args.force:
        raise SystemExit(f"{path} exists (use --force to overwrite)")
    name = args.name or os.path.basename(os.path.abspath(app_dir))
    try:
        config = preset(args.preset, name)
    except KeyError as e:
        raise SystemExit(e.args[0]) from e
    if args.platform:
        config.platform = args.platform
    config.validate()
    config.save(path)
    print(f"initialized {path} (preset={args.preset}, platform={config.platform})")
    return 0


def _sync_fake_state(config: DeploymentConfig, args) -> None:
    """--fake-state must point the platform and k8s phases at the SAME
    cluster-state file, or fake TPU nodes land in a different 'cluster'
    than the workload manifests."""
    if config.platform == "local" and getattr(args, "fake_state", None):
        config.platform_params["state_file"] = args.fake_state


def _platform_phase(config: DeploymentConfig, app_dir: str, action: str,
                    provision: bool) -> None:
    from kubeflow_tpu.platform import get_platform

    platform = get_platform(config.platform)
    report = getattr(platform, action)(config, app_dir,
                                       dry_run=not provision)
    if report.get("dry_run"):
        hint = "" if provision else " (pass --provision to execute)"
        print(f"platform {action} plan{hint}:")
        for cmd in report.get("commands", []):
            print("  " + (" ".join(cmd) if isinstance(cmd, list)
                          else str(cmd)))
        if report.get("note"):
            print(f"  note: {report['note']}")
    else:
        print(f"platform {action}: "
              + ", ".join(f"{k}={v}" for k, v in report.items()
                          if k != "dry_run"))


def cmd_generate(args) -> int:
    config = _app_config(args.app_dir)
    phase = getattr(args, "resource", "all")
    if phase in ("all", "platform"):
        from kubeflow_tpu.platform import get_platform

        paths = get_platform(config.platform).generate(config, args.app_dir)
        if paths:
            print(f"generated platform config: {', '.join(paths)}")
    if phase in ("all", "k8s"):
        objs = render_all(config)
        out_dir = _manifest_path(args.app_dir)
        os.makedirs(out_dir, exist_ok=True)
        for f in os.listdir(out_dir):
            if f.endswith(".yaml"):
                os.remove(os.path.join(out_dir, f))
        for i, obj in enumerate(objs):
            md = obj.get("metadata", {})
            fname = (f"{i:03d}_{obj['kind'].lower()}_"
                     f"{md.get('name', 'unnamed')}.yaml")
            with open(os.path.join(out_dir, fname), "w") as f:
                yaml.safe_dump(obj, f, sort_keys=False)
        print(f"generated {len(objs)} manifests in {out_dir}")
    return 0


def _load_manifests(app_dir: str) -> List[Obj]:
    out_dir = _manifest_path(app_dir)
    if not os.path.isdir(out_dir):
        raise SystemExit(f"{out_dir} not found — run `ctl generate` first")
    objs = []
    for fname in sorted(os.listdir(out_dir)):
        if fname.endswith(".yaml"):
            with open(os.path.join(out_dir, fname)) as f:
                objs.append(yaml.safe_load(f))
    return objs


def cmd_apply(args) -> int:
    config = _app_config(args.app_dir)
    _sync_fake_state(config, args)
    phase = getattr(args, "resource", "all")
    if phase in ("all", "platform"):
        _platform_phase(config, args.app_dir, "apply", args.provision)
    if phase in ("all", "k8s"):
        objs = _load_manifests(args.app_dir)
        client = _client(args)
        applied = apply_all(client, objs)
        print(f"applied {len(applied)} objects")
    return 0


def cmd_delete(args) -> int:
    config = _app_config(args.app_dir)
    _sync_fake_state(config, args)
    phase = getattr(args, "resource", "all")
    if phase in ("all", "k8s"):
        objs = _load_manifests(args.app_dir)
        client = _client(args)
        delete_all(client, objs)
        print(f"deleted {len(objs)} objects")
    if phase in ("all", "platform"):
        _platform_phase(config, args.app_dir, "delete", args.provision)
    return 0


def cmd_show(args) -> int:
    config = _app_config(args.app_dir)
    docs = render_all(config)
    print(yaml.safe_dump_all(docs, sort_keys=False), end="")
    return 0


def cmd_components(args) -> int:
    for comp in list_components():
        print(f"{comp.name:20s} {comp.description}")
        if args.verbose:
            for k, v in sorted(comp.defaults.items()):
                print(f"  {k} = {v!r}")
    return 0


def cmd_images(args) -> int:
    """Release tooling (reference ``releasing/`` parity): list every image
    the app renders; ``--retag``/``--registry`` pin new coordinates into
    app.yaml so the next generate/apply ships them."""
    from kubeflow_tpu.manifests.images import (
        digest_map_from_cluster,
        pin_config,
        rendered_images,
        retag_config,
    )

    if args.bump:
        # the freshness bot (reference py/kubeflow/kubeflow/ci +
        # releasing/auto-update parity): scan a tag catalog for newer
        # component images, rewrite + changelog + review branch.
        # propose_updates loads app.yaml itself — no _app_config here.
        if args.pin or args.retag or args.registry:
            raise SystemExit("--bump cannot be combined with "
                             "--pin/--retag/--registry")
        from kubeflow_tpu.manifests.autoupdate import propose_updates

        report = propose_updates(args.app_dir, args.bump,
                                 write=args.write,
                                 git_branch=args.git_branch)
        for b in report["bumps"]:
            print(f"{b['component']}.{b['param']}: {b['old_tag']} -> "
                  f"{b['new_tag']}")
        if not report["bumps"]:
            print("all images current")
        elif report["written"]:
            print(f"wrote {len(report['bumps'])} bump(s) to app.yaml "
                  "+ image-bumps.md"
                  + (f" on branch {report['branch']}"
                     if report["branch"] else ""))
        else:
            print(f"{len(report['bumps'])} bump(s) available "
                  "(re-run with --write to apply)")
        if report.get("git_error"):
            print(f"GIT ERROR: {report['git_error']}")
            return 1
        return 0
    config = _app_config(args.app_dir)
    if args.pin:
        if args.retag or args.registry:
            raise SystemExit("--pin cannot be combined with "
                             "--retag/--registry (pin first, or retag "
                             "first and then pin the new tags)")
        ambiguous = []
        if args.pin == "cluster":
            digests, ambiguous = digest_map_from_cluster(_client(args))
        else:
            with open(args.pin) as f:
                digests = yaml.safe_load(f) or {}
            digests = digests.get("images", digests)
        changes, missing = pin_config(config, digests)
        config.save(os.path.join(args.app_dir, APP_YAML))
        # the lock records {original tagged ref: digest} so it feeds
        # straight back into `--pin FILE` for another app dir; merge
        # with any existing lock (a re-pin with nothing to change must
        # not wipe the release record)
        lock_path = os.path.join(args.app_dir, "images.lock.yaml")
        lock: dict = {"images": {}}
        if os.path.exists(lock_path):
            with open(lock_path) as f:
                prior = yaml.safe_load(f) or {}
            # accept both lock shapes --pin FILE accepts: a bare
            # {image: digest} map or the {"images": {...}} wrapper
            images = prior.get("images", prior)
            if isinstance(images, dict):
                lock["images"].update(images)
        lock["images"].update(
            {old: new.rsplit("@", 1)[1] for old, new in changes.items()})
        with open(lock_path, "w") as f:
            yaml.safe_dump(lock, f, sort_keys=True)
        for old, new in sorted(changes.items()):
            print(f"{old} -> {new}")
        for img in ambiguous:
            print(f"AMBIGUOUS {img} (running with multiple digests — "
                  "mid-rollout?)")
        for img in missing:
            if img not in ambiguous:
                print(f"UNRESOLVED {img} (not running on the cluster / "
                      "not in the digest file)")
        print(f"pinned {len(changes)} image(s) "
              f"({len(missing)} unresolved); lock: {lock_path}")
        return 0 if not missing else 1
    if args.retag or args.registry:
        if not args.retag:
            raise SystemExit("--registry requires --retag TAG")
        changes = retag_config(config, args.retag, args.registry or "")
        with open(os.path.join(args.app_dir, "app.yaml"), "w") as f:
            f.write(config.to_yaml())
        for old, new in sorted(changes.items()):
            print(f"{old} -> {new}")
        print(f"retagged {len(changes)} image(s); run `ctl generate` to "
              "re-render")
        return 0
    for where, ctr, image in rendered_images(config):
        print(f"{where:45s} {ctr:12s} {image}")
    return 0


def cmd_gc(args) -> int:
    """Prune cluster objects this deployment no longer renders.

    The reference's gc tool cleans stale deployments
    (``/root/reference/bootstrap/cmd/gc/main.go``); here staleness is
    precise: every rendered object carries ``app.kubernetes.io/part-of``
    (:func:`render_all`), so anything in the cluster wearing this
    deployment's label that the current manifests don't contain was left
    behind by a removed component — delete it (kubectl apply --prune
    role)."""
    from kubeflow_tpu.k8s.apply import prune
    from kubeflow_tpu.k8s.objects import obj_key
    from kubeflow_tpu.manifests.registry import PART_OF_LABEL

    config = _app_config(args.app_dir)
    _sync_fake_state(config, args)
    desired = _load_manifests(args.app_dir)
    client = _client(args)
    selector = {PART_OF_LABEL: config.name}
    # observed kinds = kinds we render now ∪ every kind any builtin
    # component renders (a removed component may have held the only
    # object of its kind)
    kinds = {(obj["apiVersion"], obj["kind"]) for obj in desired}
    kinds |= {("apps/v1", "Deployment"), ("apps/v1", "StatefulSet"),
              ("v1", "Service"), ("v1", "ConfigMap"), ("v1", "Secret"),
              ("v1", "ServiceAccount"), ("v1", "PersistentVolumeClaim"),
              ("rbac.authorization.k8s.io/v1", "ClusterRole"),
              ("rbac.authorization.k8s.io/v1", "ClusterRoleBinding"),
              ("rbac.authorization.k8s.io/v1", "Role"),
              ("rbac.authorization.k8s.io/v1", "RoleBinding"),
              ("apiextensions.k8s.io/v1", "CustomResourceDefinition"),
              ("networking.k8s.io/v1", "NetworkPolicy"),
              ("networking.k8s.io/v1", "Ingress"),
              ("networking.istio.io/v1beta1", "Gateway"),
              ("networking.istio.io/v1beta1", "VirtualService"),
              ("networking.istio.io/v1beta1", "DestinationRule"),
              ("cloud.google.com/v1", "BackendConfig"),
              ("networking.gke.io/v1", "ManagedCertificate"),
              ("admissionregistration.k8s.io/v1",
               "MutatingWebhookConfiguration")}
    observed = []
    for api, kind in sorted(kinds):
        if kind == "Namespace":
            continue  # never gc the namespace out from under the app
        if kind == "PersistentVolumeClaim" and not args.include_pvcs:
            # PVCs hold state (training logs, the model registry);
            # pruning one deletes data, not just config — opt-in only
            continue
        try:
            observed.extend(client.list(api, kind,
                                        label_selector=selector))
        except ApiError:
            continue  # kind not served (e.g. CRD already gone)
    want = {obj_key(d) for d in desired}
    stale = [obj for obj in observed if obj_key(obj) not in want]
    if args.dry_run:
        for obj in stale:
            print(f"would delete {obj_key(obj)}")
        print(f"{len(stale)} stale object(s) (dry run)")
        return 0
    pruned = prune(client, desired, stale)
    for obj in pruned:
        print(f"deleted {obj_key(obj)}")
    print(f"pruned {len(pruned)} stale object(s)")
    return 0


_SCAFFOLD_TEMPLATE = '''\
"""{title} component."""

from __future__ import annotations

from typing import Any, Dict, List

from kubeflow_tpu.config.deployment import DeploymentConfig
from kubeflow_tpu.k8s import objects as o
from kubeflow_tpu.manifests.registry import register

DEFAULTS: Dict[str, Any] = {{
    "image": "kubeflow-tpu/platform:v1alpha1",
    "replicas": 1,
}}


@register("{name}", DEFAULTS, "{title}")
def render(config: DeploymentConfig, params: Dict[str, Any]) -> List[o.Obj]:
    ns = config.namespace
    name = "{name}"
    pod = o.pod_spec([
        o.container(name, params["image"]),
    ])
    return [
        o.deployment(name, ns, pod, replicas=params["replicas"]),
        o.service(name, ns, {{"app": name}},
                  [{{"name": "http", "port": 80, "targetPort": 8080}}]),
    ]
'''

_SCAFFOLD_TEST_TEMPLATE = '''\
"""Golden test for the {name} component."""

import {pyname}  # noqa: F401 — importing runs the @register call

from kubeflow_tpu.config.deployment import ComponentSpec, DeploymentConfig
from kubeflow_tpu.manifests.registry import render_component


def test_{pyname}_golden():
    cfg = DeploymentConfig(name="d", platform="local",
                           components=[ComponentSpec("{name}")])
    objs = render_component(cfg, cfg.components[0])
    assert [o["kind"] for o in objs] == ["Deployment", "Service"]
'''


def cmd_scaffold(args) -> int:
    """New-component stub (reference ``kubeflow/new-package-stub`` role):
    a registered renderer module + its golden test, ready to edit."""
    name = args.name
    if not re.match(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$", name):
        raise SystemExit(f"component name {name!r} must be a DNS-1123 label")
    pyname = name.replace("-", "_")
    out_dir = args.out or "."
    os.makedirs(out_dir, exist_ok=True)
    title = name.replace("-", " ")
    comp_path = os.path.join(out_dir, f"{pyname}.py")
    test_path = os.path.join(out_dir, f"test_{pyname}.py")
    for path in (comp_path, test_path):
        if os.path.exists(path) and not args.force:
            raise SystemExit(f"{path} exists (use --force to overwrite)")
    with open(comp_path, "w") as f:
        f.write(_SCAFFOLD_TEMPLATE.format(name=name, title=title))
    with open(test_path, "w") as f:
        f.write(_SCAFFOLD_TEST_TEMPLATE.format(name=name, pyname=pyname))
    print(f"scaffolded {comp_path} + {test_path}")
    print("import the module (so @register runs) and add it to your "
          "deployment's components")
    return 0


def cmd_promote(args) -> int:
    """Promote a model version to production: registry stage transition
    plus the serving traffic split, in one step.

    The modeldb↔tf-serving glue the reference never had: the registry
    records WHICH version is production
    (:mod:`kubeflow_tpu.serving.registry`), the serving component's
    ``traffic_split`` decides WHERE traffic goes — promote keeps them in
    lockstep. ``--canary N`` sends N% to the new version and the rest to
    the current production version instead of cutting over.
    """
    import json as _json
    import urllib.error
    import urllib.request

    config = _app_config(args.app_dir)
    spec = next((c for c in config.components if c.name == "serving"), None)
    if spec is None:
        raise SystemExit("app has no 'serving' component to promote into")
    version = f"v{int(args.version)}"
    if args.canary:
        if not 0 < args.canary < 100:
            raise SystemExit("--canary must be in (0, 100)")
        current = spec.params.get("traffic_split") or {}
        stable = next(
            (v for v, w in sorted(current.items(), key=lambda kv: -kv[1])
             if v != version),
            spec.params.get("version", "v1"))
        if stable == version:
            raise SystemExit(
                f"{version} is already the only serving version — a "
                "canary against itself is meaningless; promote without "
                "--canary")
        split = {stable: 100 - args.canary, version: args.canary}
    else:
        split = {version: 100}

    # registry first: a rejected transition must not leave app.yaml
    # routing traffic to a version the registry refused. A canary is
    # marked STAGING — production stays on the version carrying the
    # bulk of the traffic until the full cutover.
    stage = "staging" if args.canary else "production"
    if args.registry_url:
        url = (f"{args.registry_url.rstrip('/')}/api/registry/models/"
               f"{args.model}/versions/{int(args.version)}:transition")
        req = urllib.request.Request(
            url, data=_json.dumps({"stage": stage}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                entry = _json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise SystemExit(
                f"registry transition failed: {e.code} {e.read().decode()}")
        except (urllib.error.URLError, OSError) as e:
            raise SystemExit(f"registry unreachable: {e}")
        print(f"registry: {args.model} v{entry['version']} -> "
              f"{entry['stage']}")

    spec.params["traffic_split"] = split
    with open(os.path.join(args.app_dir, APP_YAML), "w") as f:
        f.write(config.to_yaml())
    print(f"serving traffic_split -> {split}")
    print("run `ctl generate` + `ctl apply` to roll the split out")
    return 0


def cmd_status(args) -> int:
    """One-look deployment health from the cluster: the Application
    aggregate (grouped component readiness) plus live TpuJobs — the CLI
    face of the dashboard's health panel."""
    from kubeflow_tpu.operators.application import (
        API_VERSION as APP_API,
        APPLICATION_KIND,
    )

    config = _app_config(args.app_dir)
    _sync_fake_state(config, args)
    client = _client(args)
    ns = config.namespace

    def list_or_absent(api, kind):
        try:
            return client.list(api, kind, ns)
        except ApiError as e:
            if e.code == API_NOT_FOUND:
                return []  # CRD not installed on this cluster
            # auth/server failures must not masquerade as "nothing there"
            raise SystemExit(f"status: cluster error listing {kind}: "
                             f"{e.code} {e.message}")

    apps = list_or_absent(APP_API, APPLICATION_KIND)
    if not apps:
        print(f"no Application CRs in {ns!r} — is the 'application' "
              "component deployed (and the controller running)?")
    for app in apps:
        status = app.get("status", {}) or {}
        print(f"application {app['metadata']['name']}: "
              f"{status.get('phase', 'Unknown')} "
              f"({status.get('ready', '—')} components ready)")
        for comp in status.get("components", []):
            if not comp.get("ready") or args.verbose:
                mark = "ok" if comp.get("ready") else "NOT READY"
                print(f"  {comp['kind']}/{comp['name']}: {mark} "
                      f"({comp.get('detail', '')})")

    from kubeflow_tpu.manifests.components.tpujob_operator import (
        API_VERSION as JOB_API,
        TPUJOB_KIND,
    )

    jobs = list_or_absent(JOB_API, TPUJOB_KIND)
    if jobs:
        print(f"tpujobs in {ns!r}:")
        for job in jobs:
            status = job.get("status", {}) or {}
            workers = status.get("workers", {}) or {}
            print(f"  {job['metadata']['name']}: "
                  f"{status.get('phase', 'Pending')} "
                  f"(workers {workers.get('Running', 0)} running / "
                  f"{workers.get('Failed', 0)} failed, "
                  f"restarts {status.get('restarts', 0)})")
    return 0


def cmd_trace_top(args) -> int:
    from kubeflow_tpu.bench.trace_tools import main as trace_main

    argv = [args.trace_dir, "--top", str(args.top)]
    if args.json:
        argv.append("--json")
    return trace_main(argv)


def cmd_version(args) -> int:
    print(f"ctl (kubeflow_tpu) {kubeflow_tpu.__version__}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ctl", description="TPU-native ML platform deployment CLI",
    )
    p.add_argument("-v", "--verbose", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    def app_cmd(name, fn, help_):
        sp = sub.add_parser(name, help=help_)
        sp.add_argument("app_dir", help="application directory")
        sp.set_defaults(fn=fn)
        return sp

    sp = app_cmd("init", cmd_init, "scaffold an app dir with app.yaml")
    sp.add_argument("--preset", default="standard",
                    help="config preset (minimal|standard|gcp-tpu)")
    sp.add_argument("--name", default=None, help="deployment name")
    sp.add_argument("--platform", default=None,
                    help="override platform (local|gcp-tpu|existing)")
    sp.add_argument("--force", action="store_true")

    sp = app_cmd("generate", cmd_generate,
                 "render platform config + manifests from app.yaml")
    sp.add_argument("resource", nargs="?", default="all",
                    choices=("all", "platform", "k8s"),
                    help="phase to generate (kfctl resource enum)")

    for name, fn, help_ in (
        ("apply", cmd_apply, "apply generated manifests to the cluster"),
        ("delete", cmd_delete, "delete applied objects"),
    ):
        sp = app_cmd(name, fn, help_)
        sp.add_argument("resource", nargs="?", default="all",
                        choices=("all", "platform", "k8s"),
                        help="phase to act on (kfctl resource enum)")
        sp.add_argument("--server", default=None,
                        help="API server URL (default: in-cluster or fake)")
        sp.add_argument("--insecure", action="store_true",
                        help="skip TLS verification")
        sp.add_argument("--fake-state", default=None,
                        help="file-backed fake cluster state path")
        sp.add_argument("--provision", action="store_true",
                        help="execute the platform plan instead of dry-run")

    app_cmd("show", cmd_show, "print rendered manifests")

    sp = app_cmd("images", cmd_images,
                 "list rendered images / retag or digest-pin a release")
    sp.add_argument("--retag", default=None, metavar="TAG",
                    help="pin all component images to TAG in app.yaml")
    sp.add_argument("--registry", default=None,
                    help="also move images to this registry (with --retag)")
    sp.add_argument("--pin", default=None, metavar="cluster|FILE",
                    help="rewrite images to content digests: 'cluster' "
                         "resolves from running pods' imageIDs, FILE is "
                         "a yaml {image: sha256:...} map; writes "
                         "images.lock.yaml")
    sp.add_argument("--bump", default=None, metavar="CATALOG",
                    help="scan CATALOG (yaml: image base -> [tags]) for "
                         "newer component images (the auto-update bot)")
    sp.add_argument("--write", action="store_true",
                    help="with --bump: rewrite app.yaml + image-bumps.md")
    sp.add_argument("--git-branch", default=None, metavar="NAME",
                    help="with --bump --write: commit the bump to this "
                         "branch for review (the PR-equivalent)")
    sp.add_argument("--server", default=None,
                    help="API server URL (with --pin cluster)")
    sp.add_argument("--insecure", action="store_true")
    sp.add_argument("--fake-state", default=None,
                    help="file-backed fake cluster state path")

    sp = app_cmd("gc", cmd_gc,
                 "prune cluster objects no longer in the manifests")
    sp.add_argument("--dry-run", action="store_true",
                    help="list stale objects without deleting")
    sp.add_argument("--include-pvcs", action="store_true",
                    help="also prune stale PersistentVolumeClaims "
                         "(DELETES THE DATA they hold)")
    sp.add_argument("--server", default=None,
                    help="API server URL (default: in-cluster or fake)")
    sp.add_argument("--insecure", action="store_true",
                    help="skip TLS verification")
    sp.add_argument("--fake-state", default=None,
                    help="file-backed fake cluster state path")

    sp = app_cmd("status", cmd_status,
                 "deployment health: Application aggregate + TpuJobs")
    sp.add_argument("--server", default=None,
                    help="API server URL (default: in-cluster or fake)")
    sp.add_argument("--insecure", action="store_true",
                    help="skip TLS verification")
    sp.add_argument("--fake-state", default=None,
                    help="file-backed fake cluster state path")
    sp.add_argument("-v", "--verbose", action="store_true",
                    default=argparse.SUPPRESS,
                    help="also list healthy components")

    sp = app_cmd("promote", cmd_promote,
                 "promote a model version: registry stage + traffic split")
    sp.add_argument("model", help="registry model name")
    sp.add_argument("version", type=int, help="version number to promote")
    sp.add_argument("--canary", type=int, default=0, metavar="PCT",
                    help="send PCT%% to the new version instead of 100")
    sp.add_argument("--registry-url", default=None,
                    help="model-registry base URL (e.g. through the edge "
                         "proxy: https://host/registry); omitted = only "
                         "the serving split is updated")

    sp = sub.add_parser("scaffold", help="generate a new component stub")
    sp.add_argument("name", help="component name (DNS-1123 label)")
    sp.add_argument("--out", default=None, help="output directory")
    sp.add_argument("--force", action="store_true")
    sp.set_defaults(fn=cmd_scaffold)

    sp = sub.add_parser("components", help="list available components")
    # SUPPRESS keeps the global -v value instead of overwriting it with False
    sp.add_argument("-v", "--verbose", action="store_true",
                    default=argparse.SUPPRESS)
    sp.set_defaults(fn=cmd_components)

    sp = sub.add_parser("trace-top",
                        help="per-op device-time table from a profiler "
                             "trace dir (the auditable PERF.md breakdown)")
    sp.add_argument("trace_dir")
    sp.add_argument("--top", type=int, default=20)
    sp.add_argument("--json", action="store_true")
    sp.set_defaults(fn=cmd_trace_top)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if getattr(args, "verbose", False) else logging.INFO,
        format="%(levelname)s %(name)s: %(message)s",
    )
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
