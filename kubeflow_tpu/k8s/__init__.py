"""Kubernetes layer: typed object builders, clients (fake + HTTP), apply engine."""

from kubeflow_tpu.k8s.client import (  # noqa: F401
    ApiError,
    FakeKubeClient,
    HttpKubeClient,
    KubeClient,
    WatchEvent,
    register_plural,
)
from kubeflow_tpu.k8s.helpers import (  # noqa: F401
    create_if_absent,
    delete_ignore_missing,
    update_status_ignore_missing,
)
from kubeflow_tpu.k8s import objects  # noqa: F401
