"""File-backed fake cluster: FakeKubeClient persisted to a JSON file.

Lets the CLI's apply/delete/show cycle run end-to-end on a laptop with no
API server — the local-dev answer to the reference's minikube path
(``/root/reference/bootstrap/pkg/kfapp/minikube/minikube.go``).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from kubeflow_tpu.k8s.client import FakeKubeClient
from kubeflow_tpu.k8s.objects import Obj


class FileBackedFakeClient(FakeKubeClient):
    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                dump = json.load(f)
            max_uid = max_rv = 0
            for obj in dump.get("objects", []):
                key = self._key(
                    obj["apiVersion"], obj["kind"],
                    obj.get("metadata", {}).get("namespace", ""),
                    obj["metadata"]["name"],
                )
                self._store[key] = obj
                md = obj.get("metadata", {})
                uid = md.get("uid", "")
                if uid.startswith("uid-") and uid[4:].isdigit():
                    max_uid = max(max_uid, int(uid[4:]))
                rv = md.get("resourceVersion", "")
                if str(rv).isdigit():
                    max_rv = max(max_rv, int(rv))
            # resume counters past persisted values so new objects never
            # collide with restored uids (cascade delete keys on uid)
            import itertools

            self._uid = itertools.count(max_uid + 1)
            self._rv = itertools.count(max_rv + 1)

    def save(self) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(self.path, "w") as f:
            json.dump({"objects": list(self._store.values())}, f, indent=1)

    # persist after every mutation so CLI invocations compose
    def create(self, obj: Obj) -> Obj:
        out = super().create(obj)
        self.save()
        return out

    def update(self, obj: Obj) -> Obj:
        out = super().update(obj)
        self.save()
        return out

    def update_status(self, obj: Obj) -> Obj:
        out = super().update_status(obj)
        self.save()
        return out

    def delete(self, api_version: str, kind: str, namespace: str, name: str) -> None:
        super().delete(api_version, kind, namespace, name)
        self.save()
