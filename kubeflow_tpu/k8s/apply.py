"""Dynamic apply engine: ordered create-or-update, delete, prune.

Replaces the reference's kustomize Apply path — resmap evaluation + dynamic
create per object with RESTMapper ordering (``/root/reference/bootstrap/pkg/
kfapp/kustomize/kustomize.go:255-476``) — with an explicit kind ordering and
retry/backoff (the reference wraps cloud calls in the same pattern,
``gcp.go:328-371``).
"""

from __future__ import annotations

import logging
import time
from typing import Iterable, List, Optional, Sequence

from kubeflow_tpu.k8s.client import ApiError, KubeClient
from kubeflow_tpu.k8s.objects import Obj, obj_key
from kubeflow_tpu.utils.clock import Sleep

log = logging.getLogger(__name__)

# creation order: cluster scaffolding before workloads, CRDs before CRs.
_KIND_ORDER = [
    "CustomResourceDefinition",
    "Namespace",
    "ServiceAccount",
    "ClusterRole",
    "ClusterRoleBinding",
    "Role",
    "RoleBinding",
    "ConfigMap",
    "Secret",
    "Service",
    "PersistentVolumeClaim",
    "Deployment",
    "StatefulSet",
    "DaemonSet",
    "Pod",
]


def _order(obj: Obj) -> int:
    kind = obj.get("kind", "")
    try:
        return _KIND_ORDER.index(kind)
    except ValueError:
        return len(_KIND_ORDER)  # CRs and unknown kinds last


def sort_for_apply(objs: Iterable[Obj]) -> List[Obj]:
    return sorted(objs, key=_order)


def apply_all(
    client: KubeClient,
    objs: Iterable[Obj],
    *,
    retries: int = 3,
    backoff_s: float = 2.0,
    sleep: Optional[Sleep] = None,
) -> List[Obj]:
    """Apply objects in dependency order; per-object retry with backoff.

    ``sleep`` is injectable (the TPU003 contract, defaulted to the real
    sleep by reference) so the retry/backoff path runs deterministically
    under test instead of burning real seconds."""
    do_sleep: Sleep = sleep if sleep is not None else time.sleep
    applied = []
    for obj in sort_for_apply(objs):
        last: Optional[Exception] = None
        for attempt in range(retries):
            try:
                applied.append(client.apply(obj))
                log.info("applied %s", obj_key(obj))
                last = None
                break
            except ApiError as e:
                last = e
                log.warning(
                    "apply %s failed (attempt %d): %s", obj_key(obj), attempt + 1, e
                )
                if attempt < retries - 1:  # no sleep after the final attempt
                    do_sleep(backoff_s * (2 ** attempt))
        if last is not None:
            raise last
    return applied


def delete_all(client: KubeClient, objs: Iterable[Obj]) -> None:
    """Delete in reverse apply order, ignoring already-gone objects."""
    for obj in reversed(sort_for_apply(objs)):
        md = obj.get("metadata", {})
        try:
            client.delete(
                obj["apiVersion"], obj["kind"], md.get("namespace", ""), md["name"]
            )
            log.info("deleted %s", obj_key(obj))
        except ApiError as e:
            if e.code != 404:
                raise


def prune(
    client: KubeClient,
    desired: Sequence[Obj],
    observed: Sequence[Obj],
) -> List[Obj]:
    """Delete observed objects that are no longer desired; returns pruned."""
    want = {obj_key(o) for o in desired}
    pruned = []
    for obj in observed:
        if obj_key(obj) not in want:
            md = obj["metadata"]
            try:
                client.delete(
                    obj["apiVersion"], obj["kind"], md.get("namespace", ""),
                    md["name"],
                )
                pruned.append(obj)
            except ApiError as e:
                if e.code != 404:
                    raise
    return pruned
