"""Typed builders for Kubernetes objects.

Replaces the reference's ksonnet/jsonnet manifest layer (the ~320 *.jsonnet/
*.libsonnet files under ``/root/reference/kubeflow/``): components here are
plain Python functions returning these dict-shaped objects, golden-tested the
same way the reference golden-tests jsonnet output
(``/root/reference/kubeflow/tf-training/tests/tf-job_test.jsonnet``).

Objects are canonical Kubernetes dicts (what you'd get from YAML), built by
helpers that enforce the fields the platform relies on. Keeping dicts (not
classes) means serialization, diffing, and server round-trips are identity.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence

Obj = Dict[str, Any]


def metadata(
    name: str,
    namespace: Optional[str] = None,
    labels: Optional[Mapping[str, str]] = None,
    annotations: Optional[Mapping[str, str]] = None,
) -> Obj:
    md: Obj = {"name": name}
    if namespace:
        md["namespace"] = namespace
    if labels:
        md["labels"] = dict(labels)
    if annotations:
        md["annotations"] = dict(annotations)
    return md


def namespace(name: str, labels: Optional[Mapping[str, str]] = None) -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "Namespace",
        "metadata": metadata(name, labels=labels),
    }


def config_map(name: str, ns: str, data: Mapping[str, str], **md) -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": metadata(name, ns, **md),
        "data": dict(data),
    }


def secret(name: str, ns: str, string_data: Mapping[str, str]) -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "Secret",
        "metadata": metadata(name, ns),
        "type": "Opaque",
        "stringData": dict(string_data),
    }


def service_account(name: str, ns: str) -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": metadata(name, ns),
    }


def service(
    name: str,
    ns: str,
    selector: Mapping[str, str],
    ports: Sequence[Mapping[str, Any]],
    *,
    headless: bool = False,
    labels: Optional[Mapping[str, str]] = None,
    annotations: Optional[Mapping[str, str]] = None,
) -> Obj:
    spec: Obj = {"selector": dict(selector), "ports": [dict(p) for p in ports]}
    if headless:
        spec["clusterIP"] = "None"
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": metadata(name, ns, labels=labels, annotations=annotations),
        "spec": spec,
    }


def container(
    name: str,
    image: str,
    *,
    command: Optional[Sequence[str]] = None,
    args: Optional[Sequence[str]] = None,
    env: Optional[Mapping[str, str]] = None,
    ports: Optional[Sequence[int]] = None,
    resources: Optional[Mapping[str, Any]] = None,
    volume_mounts: Optional[Sequence[Mapping[str, str]]] = None,
) -> Obj:
    c: Obj = {"name": name, "image": image}
    if command:
        c["command"] = list(command)
    if args:
        c["args"] = list(args)
    if env:
        c["env"] = [{"name": k, "value": str(v)} for k, v in env.items()]
    if ports:
        c["ports"] = [{"containerPort": p} for p in ports]
    if resources:
        c["resources"] = dict(resources)
    if volume_mounts:
        c["volumeMounts"] = [dict(m) for m in volume_mounts]
    return c


def pod_spec(
    containers: Sequence[Obj],
    *,
    service_account_name: Optional[str] = None,
    volumes: Optional[Sequence[Obj]] = None,
    node_selector: Optional[Mapping[str, str]] = None,
    restart_policy: Optional[str] = None,
    scheduler_name: Optional[str] = None,
    host_network: bool = False,
) -> Obj:
    spec: Obj = {"containers": [dict(c) for c in containers]}
    if service_account_name:
        spec["serviceAccountName"] = service_account_name
    if volumes:
        spec["volumes"] = [dict(v) for v in volumes]
    if node_selector:
        spec["nodeSelector"] = dict(node_selector)
    if restart_policy:
        spec["restartPolicy"] = restart_policy
    if scheduler_name:
        spec["schedulerName"] = scheduler_name
    if host_network:
        spec["hostNetwork"] = True
    return spec


def deployment(
    name: str,
    ns: str,
    pod: Obj,
    *,
    replicas: int = 1,
    labels: Optional[Mapping[str, str]] = None,
    annotations: Optional[Mapping[str, str]] = None,
) -> Obj:
    labels = dict(labels or {"app": name})
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": metadata(name, ns, labels=labels, annotations=annotations),
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {"metadata": {"labels": labels}, "spec": pod},
        },
    }


def stateful_set(
    name: str,
    ns: str,
    pod: Obj,
    *,
    replicas: int = 1,
    service_name: Optional[str] = None,
    labels: Optional[Mapping[str, str]] = None,
) -> Obj:
    labels = dict(labels or {"app": name})
    return {
        "apiVersion": "apps/v1",
        "kind": "StatefulSet",
        "metadata": metadata(name, ns, labels=labels),
        "spec": {
            "replicas": replicas,
            "serviceName": service_name or name,
            "selector": {"matchLabels": labels},
            "template": {"metadata": {"labels": labels}, "spec": pod},
        },
    }


def pod(name: str, ns: str, spec: Obj, labels: Optional[Mapping[str, str]] = None,
        annotations: Optional[Mapping[str, str]] = None) -> Obj:
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": metadata(name, ns, labels=labels, annotations=annotations),
        "spec": spec,
    }


def role(name: str, ns: str, rules: Sequence[Mapping[str, Any]]) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "Role",
        "metadata": metadata(name, ns),
        "rules": [dict(r) for r in rules],
    }


def cluster_role(name: str, rules: Sequence[Mapping[str, Any]]) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": metadata(name),
        "rules": [dict(r) for r in rules],
    }


def role_binding(name: str, ns: str, role_name: str, sa: str, sa_ns: str,
                 cluster: bool = False) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "RoleBinding",
        "metadata": metadata(name, ns),
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole" if cluster else "Role",
            "name": role_name,
        },
        "subjects": [{"kind": "ServiceAccount", "name": sa, "namespace": sa_ns}],
    }


def cluster_role_binding(name: str, role_name: str, sa: str, sa_ns: str) -> Obj:
    return {
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": metadata(name),
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": role_name,
        },
        "subjects": [{"kind": "ServiceAccount", "name": sa, "namespace": sa_ns}],
    }


def network_policy(
    name: str,
    ns: str,
    pod_selector: Mapping[str, str],
    *,
    from_pod_labels: Sequence[Mapping[str, str]] = (),
    from_namespace_labels: Sequence[Mapping[str, str]] = (),
    ports: Sequence[int] = (),
) -> Obj:
    """Ingress-only NetworkPolicy: selected pods accept traffic solely from
    the listed pod/namespace selectors (header-trusting web services must
    not be reachable by arbitrary in-cluster pods)."""
    peers: list = [{"podSelector": {"matchLabels": dict(l)}}
                   for l in from_pod_labels]
    peers += [{"namespaceSelector": {"matchLabels": dict(l)}}
              for l in from_namespace_labels]
    if not peers:
        # an empty "from" list means ALL sources to the NetworkPolicy API —
        # the opposite of what a caller of a lockdown helper intends
        raise ValueError("network_policy needs at least one allowed peer")
    rule: Dict[str, Any] = {"from": peers}
    if ports:
        rule["ports"] = [{"protocol": "TCP", "port": p} for p in ports]
    return {
        "apiVersion": "networking.k8s.io/v1",
        "kind": "NetworkPolicy",
        "metadata": metadata(name, ns),
        "spec": {
            "podSelector": {"matchLabels": dict(pod_selector)},
            "policyTypes": ["Ingress"],
            "ingress": [rule],
        },
    }


def crd(
    plural: str,
    group: str,
    kind: str,
    *,
    versions: Sequence[str] = ("v1",),
    scope: str = "Namespaced",
    short_names: Sequence[str] = (),
    printer_columns: Sequence[Mapping[str, str]] = (),
    schema: Optional[Obj] = None,
) -> Obj:
    vers: List[Obj] = []
    for i, v in enumerate(versions):
        entry: Obj = {"name": v, "served": True, "storage": i == 0}
        entry["schema"] = {
            "openAPIV3Schema": schema or {"type": "object",
                                          "x-kubernetes-preserve-unknown-fields": True}
        }
        if printer_columns:
            entry["additionalPrinterColumns"] = [dict(c) for c in printer_columns]
        vers.append(entry)
    names: Obj = {"plural": plural, "singular": kind.lower(), "kind": kind}
    if short_names:
        names["shortNames"] = list(short_names)
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": metadata(f"{plural}.{group}"),
        "spec": {
            "group": group,
            "names": names,
            "scope": scope,
            "versions": vers,
        },
    }


# --- small helpers the engine uses ---------------------------------------

def gvk(obj: Obj) -> str:
    return f"{obj.get('apiVersion', '')}/{obj.get('kind', '')}"


def obj_key(obj: Obj) -> str:
    md = obj.get("metadata", {})
    return f"{gvk(obj)}/{md.get('namespace', '')}/{md.get('name', '')}"


def set_owner(obj: Obj, owner: Obj, *, controller: bool = True) -> Obj:
    """Attach an ownerReference so cascade-delete works (fake + real server)."""
    ref = {
        "apiVersion": owner["apiVersion"],
        "kind": owner["kind"],
        "name": owner["metadata"]["name"],
        "uid": owner["metadata"].get("uid", ""),
        "controller": controller,
    }
    obj.setdefault("metadata", {}).setdefault("ownerReferences", []).append(ref)
    return obj
