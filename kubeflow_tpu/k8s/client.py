"""Kubernetes API clients: an in-memory fake and a stdlib HTTP client.

The reference leans on client-go's dynamic client + RESTMapper for
server-side apply (``/root/reference/bootstrap/pkg/kfapp/kustomize/
kustomize.go:378-476``) and on real CI clusters for anything resembling an
integration test (SURVEY.md §4). This framework inverts that: every control-
plane component programs against :class:`KubeClient`, and the
:class:`FakeKubeClient` is a faithful-enough API server (uids,
resourceVersions, watches, ownerReference cascade delete) that operators run
in unit tests. :class:`HttpKubeClient` is the in-cluster implementation on
the same interface — stdlib only, service-account token auth.
"""

from __future__ import annotations

import abc
import copy
import itertools
import json
import os
import queue
import ssl
import threading
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Tuple

from kubeflow_tpu.k8s.objects import Obj

API_NOT_FOUND = 404
API_CONFLICT = 409


class ApiError(Exception):
    def __init__(self, code: int, message: str):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.message = message


@dataclass(frozen=True)
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    object: Obj


def _meta(obj: Obj) -> Tuple[str, str]:
    md = obj.get("metadata", {})
    return md.get("namespace", ""), md["name"]


class KubeClient(abc.ABC):
    """Dynamic-typed CRUD + watch over (apiVersion, kind)."""

    @abc.abstractmethod
    def create(self, obj: Obj) -> Obj: ...

    @abc.abstractmethod
    def get(self, api_version: str, kind: str, namespace: str, name: str) -> Obj: ...

    @abc.abstractmethod
    def list(self, api_version: str, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Mapping[str, Optional[str]]] = None,
             ) -> List[Obj]: ...
    # a selector value of None selects on label EXISTENCE (k8s bare-key
    # form ``labelSelector=key``); a string selects on equality

    @abc.abstractmethod
    def update(self, obj: Obj) -> Obj: ...

    @abc.abstractmethod
    def update_status(self, obj: Obj) -> Obj: ...

    @abc.abstractmethod
    def delete(self, api_version: str, kind: str, namespace: str, name: str) -> None: ...

    @abc.abstractmethod
    def watch(self, api_version: str, kind: str,
              namespace: Optional[str] = None) -> "queue.Queue[WatchEvent]": ...

    # -- conveniences shared by implementations --

    def get_or_none(self, api_version: str, kind: str, namespace: str,
                    name: str) -> Optional[Obj]:
        try:
            return self.get(api_version, kind, namespace, name)
        except ApiError as e:
            if e.code == API_NOT_FOUND:
                return None
            raise

    def apply(self, obj: Obj) -> Obj:
        """Create-or-update by name (the engine's server-side apply)."""
        ns, name = _meta(obj)
        existing = self.get_or_none(obj["apiVersion"], obj["kind"], ns, name)
        if existing is None:
            return self.create(obj)
        merged = copy.deepcopy(obj)
        md = merged.setdefault("metadata", {})
        md["resourceVersion"] = existing["metadata"].get("resourceVersion")
        md["uid"] = existing["metadata"].get("uid")
        if "status" in existing and "status" not in merged:
            merged["status"] = existing["status"]
        return self.update(merged)


def _match_labels(obj: Obj, selector: Optional[Mapping[str, Optional[str]]]
                  ) -> bool:
    """Equality selector; a ``None`` value means *existence* (the k8s
    bare-key selector form) — the scheduler's occupancy scan filters on
    "has an assigned-slice label at all" so it reads O(assigned pods),
    not O(cluster)."""
    if not selector:
        return True
    labels = obj.get("metadata", {}).get("labels", {}) or {}
    return all(k in labels if v is None else labels.get(k) == v
               for k, v in selector.items())


class FakeKubeClient(KubeClient):
    """In-memory API server: the framework's envtest equivalent."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._store: Dict[Tuple[str, str, str, str], Obj] = {}
        self._uid = itertools.count(1)
        self._rv = itertools.count(1)
        self._watchers: List[Tuple[Tuple[str, str], Optional[str],
                                   "queue.Queue[WatchEvent]"]] = []

    def _key(self, api_version: str, kind: str, ns: str, name: str):
        return (api_version, kind, ns, name)

    def _notify(self, event_type: str, obj: Obj) -> None:
        gk = (obj["apiVersion"], obj["kind"])
        ns = obj.get("metadata", {}).get("namespace", "")
        for (w_gk, w_ns, q) in list(self._watchers):
            if w_gk == gk and (w_ns is None or w_ns == ns):
                q.put(WatchEvent(event_type, copy.deepcopy(obj)))

    def create(self, obj: Obj) -> Obj:
        with self._lock:
            ns, name = _meta(obj)
            key = self._key(obj["apiVersion"], obj["kind"], ns, name)
            if key in self._store:
                raise ApiError(API_CONFLICT, f"{key} already exists")
            stored = copy.deepcopy(obj)
            md = stored.setdefault("metadata", {})
            md["uid"] = f"uid-{next(self._uid)}"
            md["resourceVersion"] = str(next(self._rv))
            self._store[key] = stored
            self._notify("ADDED", stored)
            return copy.deepcopy(stored)

    def get(self, api_version: str, kind: str, namespace: str, name: str) -> Obj:
        with self._lock:
            key = self._key(api_version, kind, namespace, name)
            if key not in self._store:
                raise ApiError(API_NOT_FOUND, f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(self._store[key])

    def list(self, api_version: str, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Mapping[str, Optional[str]]] = None,
             ) -> List[Obj]:
        with self._lock:
            out = []
            for (av, k, ns, _), obj in self._store.items():
                if av == api_version and k == kind and (
                    namespace is None or ns == namespace
                ) and _match_labels(obj, label_selector):
                    out.append(copy.deepcopy(obj))
            return out

    def _update(self, obj: Obj, *, status_only: bool) -> Obj:
        with self._lock:
            ns, name = _meta(obj)
            key = self._key(obj["apiVersion"], obj["kind"], ns, name)
            if key not in self._store:
                raise ApiError(API_NOT_FOUND, f"{key} not found")
            current = self._store[key]
            stored = copy.deepcopy(obj)
            md = stored.setdefault("metadata", {})
            if status_only:
                # status subresource: only status changes land
                merged = copy.deepcopy(current)
                merged["status"] = copy.deepcopy(obj.get("status", {}))
                stored = merged
                md = stored["metadata"]
            md["uid"] = current["metadata"]["uid"]
            md["resourceVersion"] = str(next(self._rv))
            self._store[key] = stored
            self._notify("MODIFIED", stored)
            return copy.deepcopy(stored)

    def update(self, obj: Obj) -> Obj:
        return self._update(obj, status_only=False)

    def update_status(self, obj: Obj) -> Obj:
        return self._update(obj, status_only=True)

    def delete(self, api_version: str, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            key = self._key(api_version, kind, namespace, name)
            if key not in self._store:
                raise ApiError(API_NOT_FOUND, f"{kind} {namespace}/{name} not found")
            obj = self._store.pop(key)
            self._notify("DELETED", obj)
            self._cascade_delete(obj)

    def _cascade_delete(self, owner: Obj) -> None:
        owner_uid = owner.get("metadata", {}).get("uid")
        if not owner_uid:
            return
        children = []
        for key, obj in list(self._store.items()):
            for ref in obj.get("metadata", {}).get("ownerReferences", []) or []:
                if ref.get("uid") == owner_uid:
                    children.append(key)
                    break
        for (av, k, ns, name) in children:
            if (av, k, ns, name) in self._store:
                self.delete(av, k, ns, name)

    def watch(self, api_version: str, kind: str,
              namespace: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            # replay current state first so watchers never miss pre-existing objects
            for obj in self.list(api_version, kind, namespace):
                q.put(WatchEvent("ADDED", obj))
            self._watchers.append(((api_version, kind), namespace, q))
        return q


# --------------------------------------------------------------------------
# In-cluster HTTP client (stdlib only)
# --------------------------------------------------------------------------

SA_TOKEN_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/token"
SA_CA_PATH = "/var/run/secrets/kubernetes.io/serviceaccount/ca.crt"

# api group resource paths need the plural; a static table covers the kinds
# the platform touches, CRDs register theirs via `register_plural`.
_PLURALS: Dict[str, str] = {
    "Namespace": "namespaces",
    "Pod": "pods",
    "Service": "services",
    "ConfigMap": "configmaps",
    "Secret": "secrets",
    "ServiceAccount": "serviceaccounts",
    "Deployment": "deployments",
    "StatefulSet": "statefulsets",
    "DaemonSet": "daemonsets",
    "Role": "roles",
    "RoleBinding": "rolebindings",
    "ClusterRole": "clusterroles",
    "ClusterRoleBinding": "clusterrolebindings",
    "CustomResourceDefinition": "customresourcedefinitions",
    "Event": "events",
    "ResourceQuota": "resourcequotas",
    "PersistentVolumeClaim": "persistentvolumeclaims",
    "NetworkPolicy": "networkpolicies",
    "VirtualService": "virtualservices",
    "DestinationRule": "destinationrules",
    "Gateway": "gateways",
    "MutatingWebhookConfiguration": "mutatingwebhookconfigurations",
}

_CLUSTER_SCOPED = {
    "Namespace", "ClusterRole", "ClusterRoleBinding",
    "CustomResourceDefinition", "MutatingWebhookConfiguration",
}


def register_plural(kind: str, plural: str, cluster_scoped: bool = False) -> None:
    _PLURALS[kind] = plural
    if cluster_scoped:
        _CLUSTER_SCOPED.add(kind)


class HttpKubeClient(KubeClient):
    """Talks to a real API server with stdlib urllib; in-cluster defaults."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_path: Optional[str] = None,
        verify: bool = True,
    ) -> None:
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        self.base_url = (base_url or f"https://{host}:{port}").rstrip("/")
        if token is None and os.path.exists(SA_TOKEN_PATH):
            with open(SA_TOKEN_PATH) as f:
                token = f.read().strip()
        self.token = token
        ca = ca_path or (SA_CA_PATH if os.path.exists(SA_CA_PATH) else None)
        if not verify:
            self._ctx = ssl._create_unverified_context()  # noqa: S323 — explicit opt-in
        else:
            self._ctx = ssl.create_default_context(cafile=ca)

    def _path(self, api_version: str, kind: str, namespace: str,
              name: Optional[str] = None, *, subresource: str = "") -> str:
        plural = _PLURALS.get(kind, kind.lower() + "s")
        if api_version == "v1":
            prefix = "/api/v1"
        else:
            prefix = f"/apis/{api_version}"
        if kind in _CLUSTER_SCOPED or not namespace:
            p = f"{prefix}/{plural}"
        else:
            p = f"{prefix}/namespaces/{namespace}/{plural}"
        if name:
            p += f"/{name}"
        if subresource:
            p += f"/{subresource}"
        return p

    def _request(self, method: str, path: str, body: Optional[Obj] = None,
                 query: str = "") -> Any:
        url = self.base_url + path + (f"?{query}" if query else "")
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, context=self._ctx, timeout=60) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            raise ApiError(e.code, e.read().decode(errors="replace")) from e

    def create(self, obj: Obj) -> Obj:
        ns, _ = _meta(obj)
        return self._request(
            "POST", self._path(obj["apiVersion"], obj["kind"], ns), obj
        )

    def get(self, api_version: str, kind: str, namespace: str, name: str) -> Obj:
        return self._request("GET", self._path(api_version, kind, namespace, name))

    def list(self, api_version: str, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Mapping[str, Optional[str]]] = None,
             ) -> List[Obj]:
        query = ""
        if label_selector:
            # None value -> bare-key existence selector (k8s grammar)
            sel = ",".join(k if v is None else f"{k}={v}"
                           for k, v in label_selector.items())
            query = f"labelSelector={urllib.request.quote(sel)}"
        body = self._request(
            "GET", self._path(api_version, kind, namespace or ""), query=query
        )
        items = body.get("items", [])
        for item in items:  # list items omit apiVersion/kind; restore them
            item.setdefault("apiVersion", api_version)
            item.setdefault("kind", kind)
        return items

    def update(self, obj: Obj) -> Obj:
        ns, name = _meta(obj)
        return self._request(
            "PUT", self._path(obj["apiVersion"], obj["kind"], ns, name), obj
        )

    def update_status(self, obj: Obj) -> Obj:
        ns, name = _meta(obj)
        return self._request(
            "PUT",
            self._path(obj["apiVersion"], obj["kind"], ns, name,
                       subresource="status"),
            obj,
        )

    def delete(self, api_version: str, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", self._path(api_version, kind, namespace, name))

    def watch(self, api_version: str, kind: str,
              namespace: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        """Stream watch events into a queue from a background thread."""
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        path = self._path(api_version, kind, namespace or "")

        def pump() -> None:
            url = self.base_url + path + "?watch=true"
            req = urllib.request.Request(url)
            req.add_header("Accept", "application/json")
            if self.token:
                req.add_header("Authorization", f"Bearer {self.token}")
            while True:
                try:
                    # re-list on every (re)connect: events raised while the
                    # watch was down must not be lost (reconcile is
                    # idempotent, duplicate ADDEDs are harmless)
                    for obj in self.list(api_version, kind, namespace):
                        q.put(WatchEvent("ADDED", obj))
                    with urllib.request.urlopen(req, context=self._ctx) as resp:
                        for line in resp:
                            if not line.strip():
                                continue
                            evt = json.loads(line)
                            q.put(WatchEvent(evt["type"], evt["object"]))
                except Exception:  # noqa: BLE001 — reconnect forever
                    import time

                    # a watch must outlive API-server outages: reconnect
                    # forever (daemon thread; dies with the process)
                    time.sleep(2)  # tpulint: disable=TPU003,TPU005

        threading.Thread(target=pump, daemon=True).start()
        return q
