"""Controller-side client idioms shared by every operator.

The 409-tolerant create, 404-tolerant delete, and 404-tolerant status
update appear in every reconcile loop (the reference's controllers get
them from controller-runtime's client wrappers); one implementation here
keeps conflict/not-found policy in a single place.
"""

from __future__ import annotations

from typing import Optional

from kubeflow_tpu.k8s.client import ApiError, KubeClient
from kubeflow_tpu.k8s.objects import Obj


def create_if_absent(client: KubeClient, obj: Obj) -> bool:
    """Create; an existing object (409) is success. Returns True if created."""
    try:
        client.create(obj)
        return True
    except ApiError as e:
        if e.code != 409:
            raise
        return False


def delete_ignore_missing(client: KubeClient, api_version: str, kind: str,
                          namespace: str, name: str) -> bool:
    """Delete; an already-gone object (404) is success. True if deleted."""
    try:
        client.delete(api_version, kind, namespace, name)
        return True
    except ApiError as e:
        if e.code != 404:
            raise
        return False


def update_status_ignore_missing(client: KubeClient,
                                 obj: Obj) -> Optional[Obj]:
    """Write status; a concurrently-deleted object (404) is a no-op."""
    try:
        return client.update_status(obj)
    except ApiError as e:
        if e.code != 404:
            raise
        return None
