"""Lint orchestration: walk files, run checkers, suppress, diff baseline.

The pipeline per run:

1. :func:`walk_paths` parses every target file into a ModuleInfo;
2. every registered checker sees every module (then ``finalize()``);
3. pragma suppression drops findings the code explicitly allowlists;
4. the committed baseline splits the rest into grandfathered vs *new* —
   only new findings gate (exit nonzero in the CLI, assert in tier-1).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

from kubeflow_tpu.analysis import baseline as baseline_mod
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import all_checkers, create_checkers
from kubeflow_tpu.analysis.walker import ModuleInfo, walk_paths

DEFAULT_PATHS = ("kubeflow_tpu",)


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@dataclasses.dataclass
class LintReport:
    # (finding, source line text) for everything checkers emitted and
    # pragmas did not suppress; line text rides along for fingerprints
    findings: List[Tuple[Finding, str]]
    new: List[Finding]            # findings not covered by the baseline
    suppressed: int               # pragma-suppressed count
    files: int                    # modules scanned

    @property
    def baselined(self) -> int:
        return len(self.findings) - len(self.new)

    def rule_counts(self) -> Dict[str, Tuple[int, int]]:
        """rule -> (total findings, new findings), rules with any."""
        out: Dict[str, List[int]] = {}
        for f, _ in self.findings:
            out.setdefault(f.rule, [0, 0])[0] += 1
        for f in self.new:
            out.setdefault(f.rule, [0, 0])[1] += 1
        return {r: (t, n) for r, (t, n) in sorted(out.items())}

    def rule_table(self) -> str:
        """Per-rule finding-count summary (total/baselined/new)."""
        rows = [("rule", "findings", "baselined", "new")]
        for rule, (total, new) in self.rule_counts().items():
            rows.append((rule, str(total), str(total - new), str(new)))
        if len(rows) == 1:
            return "tpulint: no findings by any rule"
        widths = [max(len(r[i]) for r in rows) for i in range(4)]
        return "\n".join(
            "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
            for row in rows)

    def diff_table(self) -> str:
        """NEW-findings-vs-baseline table: one row per (rule, file),
        so a CI regression names the rule and the file in the failure
        output instead of just exiting nonzero."""
        by: Dict[Tuple[str, str], int] = {}
        for f in self.new:
            by[(f.rule, f.path)] = by.get((f.rule, f.path), 0) + 1
        lines = ["new findings vs baseline (rule, file, count):"]
        for (rule, path), n in sorted(by.items()):
            lines.append(f"  {rule}  {path}  +{n}")
        return "\n".join(lines)

    def format(self, show_baselined: bool = False) -> str:
        lines: List[str] = []
        if show_baselined:
            lines += [f.format() for f, _ in self.findings]
        else:
            lines += [f.format() for f in self.new]
        lines.append(
            f"tpulint: {self.files} files, {len(self.new)} new finding(s), "
            f"{self.baselined} baselined, {self.suppressed} suppressed")
        return "\n".join(lines)


def lint_modules(modules: Sequence[ModuleInfo],
                 rules: Optional[Sequence[str]] = None,
                 ) -> Tuple[List[Tuple[Finding, str]], int]:
    """Run checkers over already-parsed modules; returns the surviving
    (finding, line_text) pairs and the pragma-suppressed count."""
    checkers = create_checkers(rules)
    by_rel: Dict[str, ModuleInfo] = {m.rel: m for m in modules}
    raw: List[Finding] = []
    for module in modules:
        for checker in checkers:
            raw.extend(checker.check(module))
    for checker in checkers:
        raw.extend(checker.finalize())

    kept: List[Tuple[Finding, str]] = []
    suppressed = 0
    for f in raw:
        module = by_rel.get(f.path)
        if module is not None and module.pragmas.suppresses(f):
            suppressed += 1
            continue
        line_text = module.line_text(f.line) if module is not None else ""
        kept.append((f, line_text))
    # stable order: path, line, rule — checker iteration order must not
    # leak into baselines or CI output
    kept.sort(key=lambda p: (p[0].path, p[0].line, p[0].rule))
    return kept, suppressed


def run_lint(paths: Optional[Sequence[str]] = None,
             root: Optional[str] = None,
             rules: Optional[Sequence[str]] = None,
             baseline_path: Optional[str] = None,
             allow_unknown_rules: bool = False) -> LintReport:
    """Lint ``paths`` (default: the kubeflow_tpu package) against the
    committed baseline. ``baseline_path=''`` disables baselining.

    Raises :class:`baseline.BaselineRuleGap` when the baseline records
    a covered-rule set and an active rule is absent from it — the
    baseline predates the rule, so its findings cannot be gated.
    ``allow_unknown_rules=True`` skips that check (the
    ``--baseline-update`` path, which exists to close the gap)."""
    root = root or repo_root()
    modules = list(walk_paths(paths or DEFAULT_PATHS, root))
    kept, suppressed = lint_modules(modules, rules)

    if baseline_path is None:
        baseline_path = os.path.join(root, baseline_mod.DEFAULT_BASELINE)
    payload = baseline_mod.load_payload(baseline_path) \
        if baseline_path else {}
    if baseline_path and not allow_unknown_rules:
        active = ([r.upper() for r in rules] if rules
                  else list(all_checkers()))
        baseline_mod.check_rule_coverage(baseline_path, payload, active)
    base = payload.get("findings", {}) if payload else {}
    new = baseline_mod.new_findings(kept, base)
    return LintReport(findings=kept, new=new, suppressed=suppressed,
                      files=len(modules))


def update_baseline(report: LintReport, root: Optional[str] = None,
                    baseline_path: Optional[str] = None) -> str:
    root = root or repo_root()
    path = baseline_path or os.path.join(root, baseline_mod.DEFAULT_BASELINE)
    baseline_mod.save(path, report.findings, rules=sorted(all_checkers()))
    return path
