"""Structured findings: what a checker emits, and how it is fingerprinted.

A finding carries everything the CLI, the baseline, and CI need: rule
id, severity, location, message, and a fix hint. The fingerprint
deliberately ignores line *numbers* — it hashes the rule, the file, and
the normalized source text of the flagged line — so unrelated edits
above a grandfathered finding do not invalidate the baseline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Optional, Tuple

# severity ladder; "error" findings gate CI, "warning" findings are
# reported but (by default) still gate — the split exists so a checker
# can express confidence, not so warnings can be ignored
SEVERITIES = ("error", "warning")


def normalize_path(path: str) -> str:
    """Repo-relative posix form: forward slashes, no leading ``./`` —
    the same finding must fingerprint identically on every platform,
    or a baseline refresh from another machine shuffles every entry."""
    p = path.replace(os.sep, "/").replace("\\", "/")
    while p.startswith("./"):
        p = p[2:]
    return p


def normalize_line(text: str) -> str:
    """Source line → fingerprint form: strip indentation, trailing
    comments (so adding a pragma or annotation next to a line does not
    change its identity), and whitespace runs."""
    code = text.split("#", 1)[0]
    return " ".join(code.split())


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str                 # "TPU001"
    severity: str             # one of SEVERITIES
    path: str                 # repo-relative posix path
    line: int                 # 1-based line of the offending node
    message: str              # what is wrong
    hint: str = ""            # how to fix it
    # statement span (start, end) — pragma suppression accepts a pragma
    # on any line of the span, so a multi-line construct (a while loop,
    # a BlockSpec call) can carry its pragma where it reads best
    span: Optional[Tuple[int, int]] = None

    def __post_init__(self) -> None:
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity {self.severity!r} not in {SEVERITIES}")

    @property
    def span_lines(self) -> Tuple[int, int]:
        return self.span if self.span is not None else (self.line, self.line)

    def fingerprint(self, line_text: str) -> str:
        """Stable identity for baselining; ``line_text`` is the source
        of ``self.line`` (the caller owns file access)."""
        key = (f"{self.rule}|{normalize_path(self.path)}|"
               f"{normalize_line(line_text)}")
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        loc = f"{self.path}:{self.line}"
        out = f"{loc}: {self.rule} [{self.severity}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out
