"""tpulint — AST-based static analysis for TPU kernels and platform wiring.

A platform that schedules whole TPU slices cannot afford to discover
tile-illegality or nondeterministic control loops at runtime. PR 1
fixed a Mosaic tile-legality bug in ``ops/bnconv.py`` by hand (lane-dim
blocks below 128 emit illegal tiles in compiled mode) and threaded an
injectable clock through the autoscaler; tpulint turns both classes of
bug into machine-checked rules so they stay fixed as the codebase grows
— the ``kfctl check`` role from the reference, pointed at kernels.

Layout:

- :mod:`findings`  — the structured :class:`Finding` record
- :mod:`walker`    — per-file parse (:class:`ModuleInfo`) + repo walk
- :mod:`pragmas`   — inline ``# tpulint: disable=TPU00x`` suppression
- :mod:`registry`  — pluggable checker registry (``@register_checker``)
- :mod:`baseline`  — committed grandfather file for pre-existing debt
- :mod:`runner`    — orchestration: walk → check → suppress → diff
- :mod:`cfg`       — per-function statement-level control-flow graphs
                     (built once per function via ``cfg_for``, shared
                     by both dataflow planes)
- :mod:`callgraph` — class-scoped ``self._foo()`` call resolution
- :mod:`locksets`  — must-hold lock-set dataflow + guard inference
- :mod:`tracetaint` — may-taint traced-value dataflow + jit-site
                     inventory (the compile-plane rules' core)
- :mod:`compileaudit` — static jit-site inventory × recorded
                     ``kftpu_compile_seconds`` events join
- :mod:`checkers`  — the shipped rules TPU001–TPU018

Rule catalog (details in ``docs/ANALYSIS.md``):

==========  ==================================================
TPU001      tile-legality: BlockSpec lane/sublane floors + the
            committed tile table's entries (ops/tile_table.json)
TPU002      host calls reachable inside jit/Pallas bodies
TPU003      raw wall clock in controllers (inject a Clock)
TPU004      wiring drift: component URLs/ports/RBAC vs presets
TPU005      retry/poll loops with no deadline or max-attempts
TPU006      version-gated jax APIs outside ``compat/``
TPU007      mesh-axis names vs the declared vocabulary
TPU008      PartitionSpecs illegal by their own shape
TPU009      collectives over axes no shard_map region binds
TPU010      unguarded writes to lock-guarded shared state
TPU011      blocking I/O / foreign callbacks under a held lock
TPU012      re-entrant acquisition of a non-reentrant Lock
TPU013      kftpu_* metric help/label-key contract drift
TPU014      Python control flow on a traced value in a jit region
TPU015      recompile hazards: jit-in-loop, per-call callables,
            non-hashable/traced/unbucketed static arguments
TPU016      donated argument read after the jitted call
TPU017      implicit host sync (.item()/float()/np.asarray/...)
            in step loops and decode admit paths
TPU018      jax.jit sites in serving/train/elastic bypassing
            CompileLedger.timed_compile
==========  ==================================================
"""

from kubeflow_tpu.analysis.findings import Finding, SEVERITIES
from kubeflow_tpu.analysis.registry import (
    Checker,
    all_checkers,
    create_checkers,
    register_checker,
)
from kubeflow_tpu.analysis.runner import LintReport, run_lint
from kubeflow_tpu.analysis.walker import ModuleInfo, walk_paths

__all__ = [
    "Checker",
    "Finding",
    "LintReport",
    "ModuleInfo",
    "SEVERITIES",
    "all_checkers",
    "create_checkers",
    "register_checker",
    "run_lint",
    "walk_paths",
]
