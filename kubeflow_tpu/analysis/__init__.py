"""tpulint — AST-based static analysis for TPU kernels and platform wiring.

A platform that schedules whole TPU slices cannot afford to discover
tile-illegality or nondeterministic control loops at runtime. PR 1
fixed a Mosaic tile-legality bug in ``ops/bnconv.py`` by hand (lane-dim
blocks below 128 emit illegal tiles in compiled mode) and threaded an
injectable clock through the autoscaler; tpulint turns both classes of
bug into machine-checked rules so they stay fixed as the codebase grows
— the ``kfctl check`` role from the reference, pointed at kernels.

Layout:

- :mod:`findings`  — the structured :class:`Finding` record
- :mod:`walker`    — per-file parse (:class:`ModuleInfo`) + repo walk
- :mod:`pragmas`   — inline ``# tpulint: disable=TPU00x`` suppression
- :mod:`registry`  — pluggable checker registry (``@register_checker``)
- :mod:`baseline`  — committed grandfather file for pre-existing debt
- :mod:`runner`    — orchestration: walk → check → suppress → diff
- :mod:`cfg`       — per-function statement-level control-flow graphs
- :mod:`callgraph` — class-scoped ``self._foo()`` call resolution
- :mod:`locksets`  — must-hold lock-set dataflow + guard inference
- :mod:`checkers`  — the shipped rules TPU001–TPU013

Rule catalog (details in ``docs/ANALYSIS.md``):

==========  ==================================================
TPU001      tile-legality: BlockSpec lane/sublane floors + the
            committed tile table's entries (ops/tile_table.json)
TPU002      host calls reachable inside jit/Pallas bodies
TPU003      raw wall clock in controllers (inject a Clock)
TPU004      wiring drift: component URLs/ports/RBAC vs presets
TPU005      retry/poll loops with no deadline or max-attempts
TPU006      version-gated jax APIs outside ``compat/``
TPU007      mesh-axis names vs the declared vocabulary
TPU008      PartitionSpecs illegal by their own shape
TPU009      collectives over axes no shard_map region binds
TPU010      unguarded writes to lock-guarded shared state
TPU011      blocking I/O / foreign callbacks under a held lock
TPU012      re-entrant acquisition of a non-reentrant Lock
TPU013      kftpu_* metric help/label-key contract drift
==========  ==================================================
"""

from kubeflow_tpu.analysis.findings import Finding, SEVERITIES
from kubeflow_tpu.analysis.registry import (
    Checker,
    all_checkers,
    create_checkers,
    register_checker,
)
from kubeflow_tpu.analysis.runner import LintReport, run_lint
from kubeflow_tpu.analysis.walker import ModuleInfo, walk_paths

__all__ = [
    "Checker",
    "Finding",
    "LintReport",
    "ModuleInfo",
    "SEVERITIES",
    "all_checkers",
    "create_checkers",
    "register_checker",
    "run_lint",
    "walk_paths",
]
