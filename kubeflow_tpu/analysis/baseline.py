"""Baseline file: grandfathered findings that do not gate CI.

The baseline maps finding fingerprints (rule + file + normalized source
line, see :mod:`findings`) to occurrence counts. A run is clean when,
for every fingerprint, the current count is <= the baselined count —
moving a grandfathered line or editing unrelated code nearby does not
trip the gate, but *adding* a new violation (even one textually
identical to a baselined one elsewhere in the same file... a new
occurrence) does.

The file is committed (``tpulint_baseline.json``) and shrunk over time:
``scripts/run_tpulint.py --baseline-update`` rewrites it from the
current findings, so fixing debt and updating is one command.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from kubeflow_tpu.analysis.findings import Finding, normalize_path

BASELINE_VERSION = 1
DEFAULT_BASELINE = "tpulint_baseline.json"


class BaselineRuleGap(ValueError):
    """The baseline predates one or more active rules: its gate
    semantics for them are undefined (every finding would read as
    'new'), so the run refuses with the fix spelled out instead of
    failing cryptically."""

    def __init__(self, path: str, missing: Sequence[str]) -> None:
        rules = ", ".join(sorted(missing))
        super().__init__(
            f"rule(s) {rules} unknown in baseline {path} (the baseline "
            "predates them) — triage their findings, then rerun "
            "scripts/run_tpulint.py --baseline-update to record the "
            "covered rule set")
        self.path = path
        self.missing = tuple(sorted(missing))


def fingerprint_counts(
        findings: Iterable[Tuple[Finding, str]]) -> Dict[str, dict]:
    """(finding, line_text) pairs → {fingerprint: {meta..., count}}."""
    out: Dict[str, dict] = {}
    for f, line_text in findings:
        fp = f.fingerprint(line_text)
        if fp in out:
            out[fp]["count"] += 1
        else:
            out[fp] = {"rule": f.rule, "path": normalize_path(f.path),
                       "message": f.message, "count": 1}
    return out


def load_payload(path: str) -> Dict[str, object]:
    """The whole baseline payload ({} when the file does not exist)."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {BASELINE_VERSION}")
    return data


def load(path: str) -> Dict[str, dict]:
    return load_payload(path).get("findings", {})  # type: ignore[return-value]


def check_rule_coverage(path: str, payload: Dict[str, object],
                        active: Iterable[str]) -> None:
    """Raise :class:`BaselineRuleGap` when ``active`` rules are absent
    from the payload's recorded ``rules`` list. Baselines written
    before the coverage contract (no ``rules`` key) are exempt — they
    cannot distinguish 'rule predates me' from 'rule was clean'."""
    if not payload:
        return
    covered = payload.get("rules")
    if not isinstance(covered, list):
        return
    missing = set(active) - set(covered)
    if missing:
        raise BaselineRuleGap(path, sorted(missing))


def save(path: str, findings: Iterable[Tuple[Finding, str]],
         rules: Optional[Sequence[str]] = None) -> None:
    # deterministic, review-friendly order: by path, then rule, then
    # occurrence key (the fingerprint) — a refresh after fixing one
    # file touches that file's block only, never reshuffles the rest
    counts = fingerprint_counts(findings)
    ordered = dict(sorted(
        counts.items(),
        key=lambda kv: (kv[1]["path"], kv[1]["rule"], kv[0])))
    payload: Dict[str, object] = {
        "version": BASELINE_VERSION,
        "comment": "tpulint grandfathered findings; regenerate with "
                   "scripts/run_tpulint.py --baseline-update",
        "findings": ordered,
    }
    if rules is not None:
        # the covered-rule record: a future run whose active rules
        # exceed this list fails with BaselineRuleGap instead of
        # reporting every pre-existing finding of the new rule as new
        payload["rules"] = sorted(rules)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def new_findings(findings: List[Tuple[Finding, str]],
                 baseline: Dict[str, dict]) -> List[Finding]:
    """Findings beyond the baselined occurrence counts. Within one
    fingerprint the *earliest* occurrences are treated as grandfathered
    and the overflow is reported (deterministic, if arbitrary)."""
    remaining = {fp: meta.get("count", 1) for fp, meta in baseline.items()}
    out: List[Finding] = []
    for f, line_text in findings:
        fp = f.fingerprint(line_text)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            out.append(f)
    return out
