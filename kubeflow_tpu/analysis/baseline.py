"""Baseline file: grandfathered findings that do not gate CI.

The baseline maps finding fingerprints (rule + file + normalized source
line, see :mod:`findings`) to occurrence counts. A run is clean when,
for every fingerprint, the current count is <= the baselined count —
moving a grandfathered line or editing unrelated code nearby does not
trip the gate, but *adding* a new violation (even one textually
identical to a baselined one elsewhere in the same file... a new
occurrence) does.

The file is committed (``tpulint_baseline.json``) and shrunk over time:
``scripts/run_tpulint.py --baseline-update`` rewrites it from the
current findings, so fixing debt and updating is one command.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Tuple

from kubeflow_tpu.analysis.findings import Finding, normalize_path

BASELINE_VERSION = 1
DEFAULT_BASELINE = "tpulint_baseline.json"


def fingerprint_counts(
        findings: Iterable[Tuple[Finding, str]]) -> Dict[str, dict]:
    """(finding, line_text) pairs → {fingerprint: {meta..., count}}."""
    out: Dict[str, dict] = {}
    for f, line_text in findings:
        fp = f.fingerprint(line_text)
        if fp in out:
            out[fp]["count"] += 1
        else:
            out[fp] = {"rule": f.rule, "path": normalize_path(f.path),
                       "message": f.message, "count": 1}
    return out


def load(path: str) -> Dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}, "
            f"expected {BASELINE_VERSION}")
    return data.get("findings", {})


def save(path: str, findings: Iterable[Tuple[Finding, str]]) -> None:
    # deterministic, review-friendly order: by path, then rule, then
    # occurrence key (the fingerprint) — a refresh after fixing one
    # file touches that file's block only, never reshuffles the rest
    counts = fingerprint_counts(findings)
    ordered = dict(sorted(
        counts.items(),
        key=lambda kv: (kv[1]["path"], kv[1]["rule"], kv[0])))
    payload = {
        "version": BASELINE_VERSION,
        "comment": "tpulint grandfathered findings; regenerate with "
                   "scripts/run_tpulint.py --baseline-update",
        "findings": ordered,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=False)
        f.write("\n")


def new_findings(findings: List[Tuple[Finding, str]],
                 baseline: Dict[str, dict]) -> List[Finding]:
    """Findings beyond the baselined occurrence counts. Within one
    fingerprint the *earliest* occurrences are treated as grandfathered
    and the overflow is reported (deterministic, if arbitrary)."""
    remaining = {fp: meta.get("count", 1) for fp, meta in baseline.items()}
    out: List[Finding] = []
    for f, line_text in findings:
        fp = f.fingerprint(line_text)
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            out.append(f)
    return out
