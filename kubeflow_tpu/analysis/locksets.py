"""Lock-set abstract interpretation + guard inference (RacerD-style).

The dataflow core under the TPU010–TPU012 concurrency rules. Per
class, per method, a forward **must-hold** analysis over the
:mod:`cfg` graph computes which ``threading.Lock``/``RLock`` instance
attributes are held at every statement:

- ``with self._lock:`` acquires at the WITH_ENTER node and releases at
  the synthetic WITH_EXIT node on fall-through; on exception paths the
  release is modeled indirectly — a ``try`` handler's fan-in includes
  the pre-acquisition state, so the must-intersection never carries a
  with-held lock into a handler that can be reached without it;
- bare ``self._lock.acquire()`` / ``.release()`` calls move the state
  at their statement; an ``acquire(...)`` *with arguments* (timeout /
  blocking=False) may fail, so it never enters the must-held set — it
  still counts as a may-acquire for the re-entrancy rule;
- joins intersect (must-analysis): a lock is "held here" only when
  every path to here holds it — the direction that starves false
  positives, per the analysis plane's contract.

**Entry-state conventions** (the documented intraprocedural limits):

- a method named ``*_locked`` (the repo's caller-holds-the-lock naming
  convention, e.g. ``_evict_for_one_locked``) starts with every class
  lock held;
- a private method (leading ``_``) whose every same-class call site
  holds lock L starts with L held — one bounded round of call-site
  context propagation over :mod:`callgraph`, so helpers extracted out
  of a ``with`` block do not read as unlocked code.

**Guard inference**: an instance attribute is *guarded* by lock L when
the majority (> ``GUARD_THRESHOLD`` = 0.5, at least
``GUARD_MIN_LOCKED_SITES`` = 2 locked sites) of its access sites
across the class — reads and writes, ``__init__`` excluded
(construction happens-before publication) — hold L. TPU010 flags the
minority: a write at a site holding nothing.

Everything is memoized per :class:`ModuleInfo` via
:func:`lock_analysis`, so the three consuming checkers share one
analysis pass per file and lint wall time stays flat as the rule count
grows.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from kubeflow_tpu.analysis import callgraph as cg
from kubeflow_tpu.analysis import cfg as cfg_mod
from kubeflow_tpu.analysis.walker import ModuleInfo

GUARD_THRESHOLD = 0.5          # strict majority of access sites
GUARD_MIN_LOCKED_SITES = 2     # one locked site proves nothing
_PROPAGATION_ROUNDS = 3        # call-site entry-state fixpoint bound

# lock constructors we track; Condition/Semaphore/Event have different
# semantics and are deliberately out of scope
_LOCK_CTORS = {"Lock": "lock", "threading.Lock": "lock",
               "RLock": "rlock", "threading.RLock": "rlock"}

# container methods that mutate their receiver: ``self._d.update(...)``
# is a write to ``_d`` for guard purposes
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "popleft", "appendleft", "remove",
             "discard", "clear", "sort", "reverse"}


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` → "X" (only the direct two-level form)."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def iter_exprs(root: ast.AST) -> Iterator[ast.AST]:
    """Walk ``root`` without descending into nested function/class
    bodies or lambdas — their code runs on some other path, later."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def _stmt_exprs(cn: cfg_mod.CfgNode) -> Iterator[ast.AST]:
    """The expressions evaluated *at* a CFG node — a branch header
    evaluates only its test, not its body (the body has its own
    nodes)."""
    stmt = cn.node
    if stmt is None:
        return
    if cn.kind == cfg_mod.WITH_ENTER:
        for item in stmt.items:
            yield from iter_exprs(item.context_expr)
        return
    if cn.kind == cfg_mod.WITH_EXIT:
        return
    if isinstance(stmt, (ast.If, ast.While)):
        yield from iter_exprs(stmt.test)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        yield from iter_exprs(stmt.iter)
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Try)):
        return
    elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
        yield from iter_exprs(stmt.subject)
    else:
        yield from iter_exprs(stmt)


@dataclasses.dataclass
class LockDecl:
    name: str            # attribute name ("_lock")
    kind: str            # "lock" | "rlock"
    lineno: int


@dataclasses.dataclass
class AcquireSite:
    lock: str
    node: ast.AST        # the with statement / acquire call
    held_before: FrozenSet[str]
    must: bool           # False for acquire(timeout=...) forms


@dataclasses.dataclass
class AccessSite:
    attr: str
    method: str
    node: ast.AST        # the self.<attr> Attribute node
    stmt: ast.AST        # enclosing statement (finding anchor/span)
    is_write: bool
    held: FrozenSet[str]


class MethodLocks:
    """Lock-set results for one method."""

    def __init__(self, fn, graph: cfg_mod.Cfg,
                 held_in: Dict[int, Optional[FrozenSet[str]]],
                 acquires: List[AcquireSite]) -> None:
        self.fn = fn
        self.cfg = graph
        self.held_in = held_in
        self.acquires = acquires
        self.may_acquire: Set[str] = {a.lock for a in acquires}

    def held_for_stmt(self, stmt: ast.AST) -> Optional[FrozenSet[str]]:
        cn = self.cfg.stmt_node.get(stmt)
        if cn is None:
            return None
        return self.held_in.get(cn.nid)


def _with_locks(stmt, locks: Dict[str, LockDecl]) -> Set[str]:
    out: Set[str] = set()
    for item in stmt.items:
        attr = _self_attr(item.context_expr)
        if attr is not None and attr in locks:
            out.add(attr)
    return out


def _acquire_release_in(cn: cfg_mod.CfgNode, locks: Dict[str, LockDecl],
                        ) -> List[Tuple[str, str, bool, ast.AST]]:
    """(op, lock, must, node) for acquire()/release() calls evaluated
    at this CFG node, in source order."""
    out = []
    for node in _stmt_exprs(cn):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) \
                or func.attr not in ("acquire", "release"):
            continue
        attr = _self_attr(func.value)
        if attr is None or attr not in locks:
            continue
        must = not (node.args or node.keywords)
        out.append((func.attr, attr, must, node))
    return sorted(out, key=lambda t: (t[3].lineno, t[3].col_offset))


def analyze_method(fn, locks: Dict[str, LockDecl],
                   entry: FrozenSet[str],
                   graph: Optional[cfg_mod.Cfg] = None) -> MethodLocks:
    if graph is None:
        graph = cfg_mod.build_cfg(fn)
    held_in: Dict[int, Optional[FrozenSet[str]]] = {
        n.nid: None for n in graph.nodes}
    held_in[graph.entry.nid] = entry

    def transfer(cn: cfg_mod.CfgNode,
                 state: FrozenSet[str]) -> FrozenSet[str]:
        if cn.kind == cfg_mod.WITH_ENTER:
            return state | _with_locks(cn.node, locks)
        if cn.kind == cfg_mod.WITH_EXIT:
            return state - _with_locks(cn.with_node, locks)
        out = state
        for op, lk, must, _node in _acquire_release_in(cn, locks):
            if op == "acquire" and must:
                out = out | {lk}
            elif op == "release":
                out = out - {lk}
        return out

    worklist = [graph.entry.nid]
    while worklist:
        nid = worklist.pop()
        state = held_in[nid]
        if state is None:
            continue
        out = transfer(graph.nodes[nid], state)
        for s in graph.nodes[nid].succs:
            cur = held_in[s]
            new = out if cur is None else (cur & out)
            if cur is None or new != cur:
                held_in[s] = frozenset(new)
                worklist.append(s)

    # acquire sites read the *fixpoint* in-states (a first-visit state
    # is an over-approximation that would manufacture re-entry FPs);
    # textual acquires at unreachable nodes still count for may-acquire
    acquires: List[AcquireSite] = []
    for cn in graph.nodes:
        before = held_in.get(cn.nid)
        if cn.kind == cfg_mod.WITH_ENTER:
            for lk in sorted(_with_locks(cn.node, locks)):
                acquires.append(AcquireSite(
                    lock=lk, node=cn.node,
                    held_before=before if before is not None
                    else frozenset(), must=True))
        elif cn.kind == cfg_mod.STMT:
            for op, lk, must, node in _acquire_release_in(cn, locks):
                if op == "acquire":
                    acquires.append(AcquireSite(
                        lock=lk, node=node,
                        held_before=before if before is not None
                        else frozenset(), must=must))
    return MethodLocks(fn, graph, held_in, acquires)


class ClassLockAnalysis:
    """Everything the lock rules need to know about one class."""

    def __init__(self, module: ModuleInfo, cls: ast.ClassDef) -> None:
        self.module = module
        self.cls = cls
        self.locks = self._find_locks(cg.methods_of(cls))
        self.graph: Optional[cg.ClassGraph] = None
        self.methods: Dict[str, MethodLocks] = {}
        # three views of each method's lock states over one shared CFG:
        # - ``methods`` (FULL): convention + propagated context — what
        #   suppression rules (TPU010/011) read; an assumption may
        #   excuse a write;
        # - ``proven``: call-site-propagated context only (plus the
        #   *_locked convention when the class has exactly ONE lock,
        #   where the suffix is unambiguous) — what propagation itself
        #   reads, so an assumption never launders into proof;
        # - ``local``: what the method body itself proves (plus the
        #   single-lock convention) — what the deadlock verdict
        #   (TPU012) reads; context-dependent deadlocks are reported
        #   ONCE, at the outermost call site that establishes the
        #   context, via the may-acquire closure
        self.proven: Dict[str, MethodLocks] = {}
        self.local: Dict[str, MethodLocks] = {}
        self.attr_sites: Dict[str, List[AccessSite]] = {}
        self.guards: Dict[str, str] = {}
        self.may_acquire: Dict[str, Set[str]] = {}
        if self.locks:
            # the (costlier) call graph only exists for classes that
            # actually own a lock — most classes skip the whole pass
            self.graph = cg.class_graph(cls)
            self._analyze()

    # -- lock discovery ----------------------------------------------------

    def _find_locks(self, methods) -> Dict[str, LockDecl]:
        out: Dict[str, LockDecl] = {}
        for fn in methods.values():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                kind = _LOCK_CTORS.get(_dotted(node.value.func) or "")
                if kind is None:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        out[attr] = LockDecl(attr, kind, node.lineno)
        return out

    # -- per-method analysis with bounded context propagation --------------

    def _entry_for(self, name: str) -> FrozenSet[str]:
        if name.endswith("_locked"):
            # the caller-holds-the-lock naming convention
            return frozenset(self.locks)
        return frozenset()

    def _analyze(self) -> None:
        # convention-seeded entry locks are assumption-grade; in a
        # multi-lock class the *_locked suffix cannot say WHICH lock
        # the caller holds, so the proven twin drops them there
        multi = len(self.locks) > 1
        convention: Dict[str, FrozenSet[str]] = {
            name: self._entry_for(name) for name in self.graph.methods}
        ctxs: Dict[str, FrozenSet[str]] = {
            name: frozenset() for name in self.graph.methods}
        stale = set(self.graph.methods)
        for _ in range(_PROPAGATION_ROUNDS):
            for name in stale:
                fn = self.graph.methods[name]
                full = analyze_method(
                    fn, self.locks, convention[name] | ctxs[name],
                    graph=cfg_mod.cfg_for(self.module, fn))
                proven_entry = ctxs[name] if multi \
                    else convention[name] | ctxs[name]
                self.methods[name] = full
                self.proven[name] = analyze_method(
                    fn, self.locks, proven_entry, graph=full.cfg)
            stale = set()
            for name in self.graph.methods:
                if not name.startswith("_") or name.startswith("__"):
                    continue  # public/dunder: callable from anywhere
                site_holds = [
                    held for held in self._call_site_holds(name)
                    if held is not None]
                if not site_holds:
                    continue
                # only PROVEN holds propagate — an assumption must not
                # launder into proof one call-hop down
                ctx = ctxs[name] | frozenset.intersection(*site_holds)
                if ctx != ctxs[name]:
                    ctxs[name] = ctx
                    stale.add(name)
            if not stale:
                break
        for name, fn in self.graph.methods.items():
            self.local[name] = analyze_method(
                fn, self.locks,
                frozenset() if multi else convention[name],
                graph=self.methods[name].cfg)
        self._collect_access_sites()
        self._infer_guards()
        per_method = {name: m.may_acquire
                      for name, m in self.methods.items()}
        # close over DIRECT call edges only: a call inside a nested
        # def runs later (usually on another thread) and a Lock only
        # deadlocks against its own thread
        self.may_acquire = cg.transitive(self.graph.direct_calls,
                                         per_method)

    def _call_site_holds(self, callee: str,
                         ) -> Iterator[Optional[FrozenSet[str]]]:
        for name, sites in self.graph.call_sites.items():
            if name not in self.proven:
                continue
            for call, target in sites:
                if target == callee:
                    yield self.held_at(name, call, mode="proven")

    # -- locating facts ----------------------------------------------------

    def enclosing_stmt(self, method: str,
                       node: ast.AST) -> Optional[ast.AST]:
        """Walk parents up to a CFG statement of ``method``; None when
        the node sits inside a nested def (whose execution context is
        unknown)."""
        ml = self.methods.get(method)
        if ml is None:
            return None
        cur: Optional[ast.AST] = node
        while cur is not None and cur is not ml.fn:
            if cur in ml.cfg.stmt_node:
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and cur is not node:
                    return None  # inside a nested def's body
                return cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return None  # crossed into a nested def
            cur = self.module.parents.get(cur)
        return None

    def held_at(self, method: str, node: ast.AST,
                mode: str = "full") -> Optional[FrozenSet[str]]:
        stmt = self.enclosing_stmt(method, node)
        if stmt is None:
            return None
        table = {"full": self.methods, "proven": self.proven,
                 "local": self.local}[mode]
        return table[method].held_for_stmt(stmt)

    # -- access sites + guard inference ------------------------------------

    def _classify_access(self, attr_node: ast.Attribute,
                         ) -> Optional[bool]:
        """None = not a data access (callback invocation / lock);
        True = write, False = read."""
        parent = self.module.parents.get(attr_node)
        if isinstance(parent, ast.Call) and parent.func is attr_node:
            return None  # the attr itself is being called
        if isinstance(attr_node.ctx, (ast.Store, ast.Del)):
            return True
        if isinstance(parent, ast.Subscript) and parent.value is attr_node \
                and isinstance(parent.ctx, (ast.Store, ast.Del)):
            return True
        if isinstance(parent, ast.Attribute) and parent.attr in _MUTATORS:
            gp = self.module.parents.get(parent)
            if isinstance(gp, ast.Call) and gp.func is parent:
                return True
        return False

    def _collect_access_sites(self) -> None:
        for name, fn in self.graph.methods.items():
            if name == "__init__":
                continue  # construction happens-before publication
            for node in ast.walk(fn):
                attr = _self_attr(node) if isinstance(node, ast.Attribute) \
                    else None
                if attr is None or attr in self.locks \
                        or attr in self.graph.methods:
                    continue
                is_write = self._classify_access(node)
                if is_write is None:
                    continue
                stmt = self.enclosing_stmt(name, node)
                if stmt is None:
                    continue  # nested def / unlocatable
                held = self.methods[name].held_for_stmt(stmt)
                if held is None:
                    continue  # unreachable statement
                self.attr_sites.setdefault(attr, []).append(AccessSite(
                    attr=attr, method=name, node=node, stmt=stmt,
                    is_write=is_write, held=held))

    def _infer_guards(self) -> None:
        for attr, sites in self.attr_sites.items():
            total = len(sites)
            if total == 0:
                continue
            best_lock, best_count = None, 0
            for lock in self.locks:
                count = sum(1 for s in sites if lock in s.held)
                if count > best_count:
                    best_lock, best_count = lock, count
            if (best_lock is not None
                    and best_count >= GUARD_MIN_LOCKED_SITES
                    and best_count / total > GUARD_THRESHOLD):
                self.guards[attr] = best_lock


def lock_analysis(module: ModuleInfo) -> List[ClassLockAnalysis]:
    """All per-class lock analyses for ``module``, computed once and
    memoized on the ModuleInfo — TPU010/011/012 share one pass."""
    cached = getattr(module, "_lock_analysis", None)
    if cached is None:
        cached = [ClassLockAnalysis(module, cls)
                  for cls in cg.classes_in(module.tree)]
        module._lock_analysis = cached
    return cached
