"""File walker and per-module parse state.

:class:`ModuleInfo` is the unit every checker sees: path, source,
parsed AST, and lazily-built indices (parent links, pragma index).
Checkers never open files themselves — tests feed fixture snippets
through :meth:`ModuleInfo.from_source` with a fake repo-relative path,
so rule scoping by path works identically for fixtures and real files.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Sequence

from kubeflow_tpu.analysis.pragmas import PragmaIndex

# directories never worth linting (generated, vendored, caches)
EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "static"}


class ModuleInfo:
    """One parsed source file plus the indices checkers share."""

    def __init__(self, rel: str, source: str, tree: ast.Module) -> None:
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.tree = tree
        self.lines: List[str] = source.splitlines()
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._pragmas: Optional[PragmaIndex] = None

    @classmethod
    def from_source(cls, rel: str, source: str) -> "ModuleInfo":
        return cls(rel, source, ast.parse(source))

    @classmethod
    def from_file(cls, path: str, root: str) -> Optional["ModuleInfo"]:
        """Parse ``path``; returns None on syntax errors (a broken file
        is a CI failure in its own right, not a lint crash)."""
        with open(path, encoding="utf-8", errors="replace") as f:
            source = f.read()
        rel = os.path.relpath(path, root)
        try:
            return cls(rel, source, ast.parse(source))
        except SyntaxError:
            return None

    # -- indices -----------------------------------------------------------

    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        """child node → parent node, for scope walks."""
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    @property
    def pragmas(self) -> PragmaIndex:
        if self._pragmas is None:
            self._pragmas = PragmaIndex(self.source)
        return self._pragmas

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def node_span(self, node: ast.AST) -> tuple:
        end = getattr(node, "end_lineno", None) or node.lineno
        return (node.lineno, end)


def walk_paths(paths: Sequence[str], root: str) -> Iterator[ModuleInfo]:
    """Yield :class:`ModuleInfo` for every parseable ``.py`` under
    ``paths`` (files or directories), relative to ``root``, sorted so
    runs are deterministic."""
    files: List[str] = []
    for p in paths:
        p = os.path.join(root, p) if not os.path.isabs(p) else p
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in EXCLUDE_DIRS
                                 and not d.startswith("."))
            files.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    for path in sorted(set(files)):
        mi = ModuleInfo.from_file(path, root)
        if mi is not None:
            yield mi
