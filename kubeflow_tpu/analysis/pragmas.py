"""Inline suppression: ``# tpulint: disable=TPU001[,TPU002]`` pragmas.

Two scopes:

- **line**: a pragma suppresses findings of the named rules whose
  statement *span* covers the pragma's line — so a pragma inside a
  flagged ``while`` loop or on the closing paren of a multi-line call
  still applies to the finding anchored at the construct's first line;
- **file**: ``# tpulint: disable-file=TPU003`` anywhere in the file
  suppresses the named rules for the whole file (conventionally placed
  in the module docstring area).

``disable=all`` / ``disable-file=all`` suppress every rule. Pragmas are
matched by regex over raw source lines (not the token stream), so a
pragma-shaped string literal would also suppress — acceptable for a
lint tool, and it keeps the scanner immune to tokenize errors.
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, Set

from kubeflow_tpu.analysis.findings import Finding

# the rules group is comma-separated bare tokens; trailing prose after
# the list ("# tpulint: disable=TPU005 serving forever is the point")
# must NOT be absorbed into a rule token and silently void the pragma
_PRAGMA_RE = re.compile(
    r"#\s*tpulint:\s*(?P<scope>disable|disable-file)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


class PragmaIndex:
    """Parsed pragmas for one file: line → rules, plus file-wide rules."""

    def __init__(self, source: str) -> None:
        self.line_rules: Dict[int, Set[str]] = {}
        self.file_rules: Set[str] = set()
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = _PRAGMA_RE.search(text)
            if not m:
                continue
            rules = {r.strip().upper() for r in m.group("rules").split(",")
                     if r.strip()}
            if m.group("scope") == "disable-file":
                self.file_rules |= rules
            else:
                self.line_rules.setdefault(lineno, set()).update(rules)

    def _matches(self, rules: Set[str], rule: str) -> bool:
        return "ALL" in rules or rule.upper() in rules

    def suppresses(self, finding: Finding) -> bool:
        if self._matches(self.file_rules, finding.rule):
            return True
        lo, hi = finding.span_lines
        return any(
            self._matches(rules, finding.rule)
            for lineno, rules in self.line_rules.items()
            if lo <= lineno <= hi)

    def filter(self, findings: Iterable[Finding]) -> list[Finding]:
        return [f for f in findings if not self.suppresses(f)]
