"""Module-local, class-scoped call resolution for the dataflow rules.

``self._foo()`` inside a method resolves to the method body defined on
the same class — nothing more. No inheritance walk (a base class in
another module is invisible to a single-module analysis and guessing
would manufacture false positives), no module-level function chasing,
no attribute-value tracking. That scope is deliberate: the
lock-discipline bugs this supports (TPU012's recursing ``lease()``)
live inside one class by construction, because the lock attribute
itself is class state.

Also resolved, for the checkers that need "what does this class look
like" facts:

- method name → :class:`ast.FunctionDef` (properties included; nested
  defs excluded);
- constructor-injected callables: ``self._x = param`` in ``__init__``
  where ``param`` is a bare constructor parameter — the
  caller-supplied-callback set TPU011 prices as blocking;
- the transitive closure helper :func:`transitive` for per-method
  summaries over the call graph.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]


@dataclasses.dataclass
class ClassGraph:
    node: ast.ClassDef
    methods: Dict[str, FunctionNode]
    # method name -> [(call node, resolved method name), ...] — walked
    # once at construction; consumers (the lockset propagation rounds,
    # TPU012's reachability scan) iterate this instead of re-walking
    # method ASTs
    call_sites: Dict[str, List[Tuple[ast.Call, str]]]
    # same, restricted to calls NOT inside a nested def/lambda: a call
    # in a closure runs later, usually on another thread — it must not
    # feed a same-thread deadlock verdict (a threading.Lock deadlocks
    # only against its own thread)
    direct_call_sites: Dict[str, List[Tuple[ast.Call, str]]]
    # method name -> set of same-class method names it may call
    calls: Dict[str, Set[str]]
    # edge set over direct_call_sites only — the lock-reachability
    # closure (TPU012) walks these
    direct_calls: Dict[str, Set[str]]
    # attr name -> __init__ parameter name it was assigned from
    injected_callables: Dict[str, str]


def methods_of(cls: ast.ClassDef) -> Dict[str, FunctionNode]:
    """Direct methods only — nested defs belong to their method."""
    out: Dict[str, FunctionNode] = {}
    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[stmt.name] = stmt
    return out


def self_calls(fn: FunctionNode, methods: Dict[str, FunctionNode],
               include_nested: bool = True,
               ) -> Iterator[Tuple[ast.Call, str]]:
    """Yield (call node, method name) for every ``self.<m>(...)`` call
    in ``fn`` that resolves to a method of the same class. With
    ``include_nested`` (the default) nested defs are descended — a
    closure calling ``self._foo()`` runs with the same ``self``;
    without it, only calls the method's own control flow executes are
    yielded (a deferred closure runs later, usually on another thread,
    so same-thread facts like deadlock must not walk through it)."""
    if include_nested:
        nodes = ast.walk(fn)
    else:
        def _direct(root):
            stack = list(ast.iter_child_nodes(root))
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda, ast.ClassDef)):
                    continue
                yield node
                stack.extend(ast.iter_child_nodes(node))
        nodes = _direct(fn)
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and func.attr in methods):
            yield node, func.attr


def _init_params(init: Optional[FunctionNode]) -> Set[str]:
    if init is None:
        return set()
    args = init.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    return {n for n in names if n != "self"}


def injected_callables(cls: ast.ClassDef,
                       methods: Dict[str, FunctionNode]) -> Dict[str, str]:
    """``self._x = param`` assignments in ``__init__`` from a bare
    constructor parameter. Only the plain-Name form counts: the
    conditional-default clock idiom (``clock if clock is not None else
    time.monotonic``) is an expression, not a bare name, so injectable
    clocks never land in this set by construction. Names that *say*
    they are clocks are additionally excluded — calling a clock under
    a lock is cheap and everywhere."""
    init = methods.get("__init__")
    params = _init_params(init)
    out: Dict[str, str] = {}
    if init is None:
        return out
    for node in ast.walk(init):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
                and isinstance(node.value, ast.Name)
                and node.value.id in params
                and "clock" not in tgt.attr.lower()):
            out[tgt.attr] = node.value.id
    return out


def class_graph(cls: ast.ClassDef) -> ClassGraph:
    methods = methods_of(cls)
    call_sites = {name: list(self_calls(fn, methods))
                  for name, fn in methods.items()}
    direct_call_sites = {
        name: list(self_calls(fn, methods, include_nested=False))
        for name, fn in methods.items()}
    calls = {name: {m for _, m in sites}
             for name, sites in call_sites.items()}
    direct_calls = {name: {m for _, m in sites}
                    for name, sites in direct_call_sites.items()}
    return ClassGraph(node=cls, methods=methods, call_sites=call_sites,
                      direct_call_sites=direct_call_sites,
                      calls=calls, direct_calls=direct_calls,
                      injected_callables=injected_callables(cls, methods))


def transitive(graph: Dict[str, Set[str]],
               local: Dict[str, Set[str]]) -> Dict[str, Set[str]]:
    """Close per-method summaries over the call graph:
    ``result[m] = local[m] ∪ ⋃ result[callee]``. Plain fixpoint — the
    lattice is finite (sets of lock names) and classes are small."""
    out = {m: set(s) for m, s in local.items()}
    changed = True
    while changed:
        changed = False
        for m, callees in graph.items():
            cur = out.setdefault(m, set())
            for c in callees:
                extra = out.get(c, set()) - cur
                if extra:
                    cur |= extra
                    changed = True
    return out


def classes_in(tree: ast.Module) -> List[ast.ClassDef]:
    """Top-level classes (and classes nested one level in functions are
    skipped — a class built inside a factory closure is rare and its
    lock discipline is the closure's business)."""
    return [n for n in tree.body if isinstance(n, ast.ClassDef)]
