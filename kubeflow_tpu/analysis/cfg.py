"""Per-function control-flow graphs over the existing AST walker.

The lock-discipline rules (TPU010–TPU012) need to answer "which locks
are held *here*" — a property of paths, not of syntax — so pattern
matching stops being enough at exactly this rule family. This module
builds a small statement-level CFG for one function:

- one node per simple statement;
- ``if``/``match`` fork and re-join;
- ``while``/``for`` get a back edge to the header and an exit edge
  (the ``else:`` clause hangs off the exit like CPython's semantics);
- ``with`` is modeled as an **enter** node (the acquisition point)
  plus a synthetic **exit** node that normal fall-through flows
  through. ``raise``/``return`` inside the body edge straight to the
  function exit, NOT through the with-exit node — release-on-unwind
  is instead achieved indirectly: an enclosing ``try``'s handler
  fans in from the with-ENTER node's pre-acquisition state among its
  predecessors, so a must-analysis never sees the lock held in a
  handler unless the whole ``try`` sat inside the ``with``. A rule
  that needs explicit release events on unwind paths (e.g.
  acquire/release pairing) would have to add those edges first;
- ``try`` adds an edge from every node of the body to each handler
  (an exception can surface anywhere), ``finally`` joins all of it.

Nested ``def``/``lambda``/``class`` bodies are opaque single
statements: their code runs at some later call, on some other path —
a different function's CFG.

The graph is deliberately tiny — no expression-level nodes, no
interprocedural edges — because the consumer is an abstract
interpreter over a finite lattice (:mod:`locksets`), and statements
are the granularity findings anchor to.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Union

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]

# node kinds
ENTRY = "entry"           # synthetic function entry
EXIT = "exit"             # synthetic function exit
STMT = "stmt"             # one simple statement / branch header
WITH_ENTER = "with_enter"  # the `with` header — acquisition point
WITH_EXIT = "with_exit"   # synthetic release point after a with body


@dataclasses.dataclass
class CfgNode:
    nid: int
    kind: str
    node: Optional[ast.AST] = None      # the AST statement (None: synthetic)
    succs: List[int] = dataclasses.field(default_factory=list)
    # for WITH_EXIT: the matching With node (so the interpreter knows
    # which context managers this node releases)
    with_node: Optional[ast.With] = None


class Cfg:
    """CFG for one function. ``stmt_node`` maps a statement AST object
    (by identity) to its CfgNode, so analyses can attach facts back to
    source locations."""

    def __init__(self) -> None:
        self.nodes: List[CfgNode] = []
        self.entry = self._new(ENTRY)
        self.exit = self._new(EXIT)
        self.stmt_node: Dict[ast.AST, CfgNode] = {}

    def _new(self, kind: str, node: Optional[ast.AST] = None,
             with_node: Optional[ast.AST] = None) -> CfgNode:
        cn = CfgNode(nid=len(self.nodes), kind=kind, node=node,
                     with_node=with_node)
        self.nodes.append(cn)
        return cn

    def link(self, frm: Sequence[int], to: int) -> None:
        for f in frm:
            if to not in self.nodes[f].succs:
                self.nodes[f].succs.append(to)

    def preds(self) -> Dict[int, List[int]]:
        out: Dict[int, List[int]] = {n.nid: [] for n in self.nodes}
        for n in self.nodes:
            for s in n.succs:
                out[s].append(n.nid)
        return out


# statements that terminate the current path outright
_JUMP = (ast.Return, ast.Raise)
# opaque one-node statements (never descended into)
_OPAQUE = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


class _Builder:
    def __init__(self, fn: FunctionNode) -> None:
        self.cfg = Cfg()
        # (break_targets, continue_target) stack for loop bodies
        self.loops: List[List[int]] = []
        self.continue_targets: List[int] = []
        frontier = self._body(fn.body, [self.cfg.entry.nid])
        self.cfg.link(frontier, self.cfg.exit.nid)

    # every helper takes/returns a *frontier*: the node ids whose
    # successor is the next thing sequenced after the construct

    def _stmt_node(self, stmt: ast.AST, kind: str = STMT,
                   with_node: Optional[ast.AST] = None) -> CfgNode:
        cn = self.cfg._new(kind, stmt, with_node=with_node)
        if kind != WITH_EXIT:
            self.cfg.stmt_node[stmt] = cn
        return cn

    def _body(self, body: Sequence[ast.stmt],
              frontier: List[int]) -> List[int]:
        # an empty frontier (after return/raise/break) still flows on:
        # unreachable statements get nodes with no predecessors, so a
        # finding there has somewhere to anchor
        for stmt in body:
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _stmt(self, stmt: ast.stmt, frontier: List[int]) -> List[int]:
        if isinstance(stmt, ast.If):
            head = self._stmt_node(stmt)
            self.cfg.link(frontier, head.nid)
            then = self._body(stmt.body, [head.nid])
            if stmt.orelse:
                other = self._body(stmt.orelse, [head.nid])
            else:
                other = [head.nid]
            return then + other
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = self._stmt_node(stmt)
            self.cfg.link(frontier, head.nid)
            self.loops.append([])
            self.continue_targets.append(head.nid)
            body_out = self._body(stmt.body, [head.nid])
            self.cfg.link(body_out, head.nid)     # back edge
            breaks = self.loops.pop()
            self.continue_targets.pop()
            exits = [head.nid] + breaks
            if stmt.orelse:
                # else: runs on normal loop exhaustion (not on break)
                exits = self._body(stmt.orelse, [head.nid]) + breaks
            return exits
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            enter = self._stmt_node(stmt, kind=WITH_ENTER)
            self.cfg.link(frontier, enter.nid)
            body_out = self._body(stmt.body, [enter.nid])
            leave = self._stmt_node(stmt, kind=WITH_EXIT, with_node=stmt)
            self.cfg.link(body_out, leave.nid)
            return [leave.nid]
        if isinstance(stmt, ast.Try):
            first = len(self.cfg.nodes)
            body_out = self._body(stmt.body, frontier)
            body_ids = [n.nid for n in self.cfg.nodes[first:]]
            outs: List[int] = []
            for handler in stmt.handlers:
                # the exception may surface before any body statement
                # ran, or after any of them — conservative fan-in
                outs += self._body(handler.body, frontier + body_ids)
            if stmt.orelse:
                body_out = self._body(stmt.orelse, body_out)
            outs += body_out
            if stmt.finalbody:
                outs = self._body(stmt.finalbody, outs)
            return outs
        if isinstance(stmt, ast.Break):
            n = self._stmt_node(stmt)
            self.cfg.link(frontier, n.nid)
            if self.loops:
                self.loops[-1].append(n.nid)
            return []
        if isinstance(stmt, ast.Continue):
            n = self._stmt_node(stmt)
            self.cfg.link(frontier, n.nid)
            if self.continue_targets:
                self.cfg.link([n.nid], self.continue_targets[-1])
            return []
        if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            head = self._stmt_node(stmt)
            self.cfg.link(frontier, head.nid)
            outs = [head.nid]  # no case may match
            for case in stmt.cases:
                outs += self._body(case.body, [head.nid])
            return outs
        # simple statement (incl. opaque nested defs)
        n = self._stmt_node(stmt)
        self.cfg.link(frontier, n.nid)
        if isinstance(stmt, _JUMP):
            self.cfg.link([n.nid], self.cfg.exit.nid)
            return []
        return [n.nid]


def build_cfg(fn: FunctionNode) -> Cfg:
    """Build the statement-level CFG for one function body."""
    return _Builder(fn).cfg


def cfg_for(module, fn: FunctionNode) -> Cfg:
    """The CFG for ``fn``, built once per (module, function) and
    memoized on the ModuleInfo — the lock-set analysis (TPU010–012)
    and the trace-taint analysis (TPU014–017) walk the same graphs,
    so the second dataflow plane must not double the CFG build cost.
    Keyed by AST-node identity: fixture tests that re-parse a module
    get fresh graphs because they get fresh nodes."""
    cache = getattr(module, "_cfg_cache", None)
    if cache is None:
        cache = {}
        module._cfg_cache = cache
    got = cache.get(id(fn))
    if got is None:
        got = build_cfg(fn)
        cache[id(fn)] = got
    return got
