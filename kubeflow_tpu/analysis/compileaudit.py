"""Compile audit: static jit-site inventory × recorded compile events.

The trace-taint plane (:mod:`tracetaint`) knows every ``jax.jit`` /
``pjit`` site in the source; the CompileLedger (PR 18,
``kubeflow_tpu/obs/xprof.py``) records every compilation that actually
happened as ``kftpu_compile_seconds{module,shape_class,generation}``
events. Joining the two converts the ledger from a measurement into an
enforcement mechanism: a jit site is expected to compile **once per
(shape class, backend generation)** — the shape-class grid is exactly
the ``ops/autotune`` bucket vocabulary the engine and the bench suite
key their program inventories on. A site whose runtime compile count
exceeds that expectation is a *recompile storm with a source location
attached* — the dynamic twin of TPU015, which can only flag the storms
that are statically visible.

Artifact formats accepted (all JSON):

- ``CompileLedger.events_payload()``: ``{"compile_events": [...]}``;
- a generic dump: ``{"events": [...]}`` or a top-level list of event
  objects (each needs ``module``; ``shape_class``/``generation``/
  ``seconds`` default);
- a bench artifact whose ``compile`` block is
  ``CompileLedger.summary()``: per-module *totals* only (one synthetic
  event per module) — enough to attribute compile seconds to sites,
  too coarse to count a storm; use ``events_payload()`` for gating.

Matching events to sites is name-based and conservative: the event's
``module`` field (XLA emits ``jit_<fn>``/``pjit_<fn>``; ``timed_compile``
callers pass dotted labels like ``train.step``) is normalized and
compared against each site's wrapped-function name and bound names.
An event that matches no site is reported as *unmatched* — visible,
never gating (the process may legitimately compile library code the
lint scope never parsed).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from kubeflow_tpu.analysis import tracetaint
from kubeflow_tpu.analysis.walker import ModuleInfo

DEFAULT_MAX_PER_SHAPE = 1


@dataclasses.dataclass(frozen=True)
class SiteRef:
    """One static jit site, addressable from a report line."""

    path: str
    line: int
    label: str            # wrapped name or bound-name join
    names: Tuple[str, ...]  # every name the site answers to


@dataclasses.dataclass(frozen=True)
class Storm:
    module: str           # the event's module label, as recorded
    shape_class: str
    generation: str
    count: int
    expected: int
    seconds: float
    site: Optional[SiteRef]   # None: storm in code the scan never saw


@dataclasses.dataclass
class AuditReport:
    events: int
    sites: int
    storms: List[Storm]
    unmatched: List[Tuple[str, int]]   # (module label, event count)

    def format(self) -> str:
        lines = [
            f"compile-audit: {self.events} event(s), {self.sites} static "
            f"jit site(s), {len(self.storms)} storm(s)"]
        for s in self.storms:
            lines.append(
                f"  STORM {s.module!r} shape_class={s.shape_class!r} "
                f"generation={s.generation!r}: {s.count} compiles "
                f"(expected <= {s.expected}, {s.seconds:.3f}s total)")
            if s.site is not None:
                lines.append(
                    f"    -> {s.site.path}:{s.site.line} jit site "
                    f"{s.site.label!r}")
            else:
                lines.append(
                    "    -> no static jit site matched (compiled outside "
                    "the lint scope?)")
        for module, n in self.unmatched:
            lines.append(
                f"  note: {n} event(s) for {module!r} matched no static "
                "jit site (not gating)")
        return "\n".join(lines)


def load_events(data: Any) -> List[Dict[str, Any]]:
    """Normalize any accepted artifact shape into a list of event
    dicts with ``module``/``shape_class``/``generation``/``seconds``."""
    if isinstance(data, dict):
        if "compile_events" in data:
            raw = data["compile_events"]
        elif "events" in data:
            raw = data["events"]
        elif isinstance(data.get("compile"), dict):
            # bench-artifact summary: synthesize per-module aggregates
            block = data["compile"]
            gen = str(block.get("generation", "unknown"))
            raw = [{"module": m, "seconds": s, "shape_class": "unknown",
                    "generation": gen}
                   for m, s in (block.get("by_module") or {}).items()]
        else:
            raise ValueError(
                "unrecognized compile-audit artifact: expected "
                "'compile_events', 'events', a top-level list, or a "
                "bench artifact with a 'compile' block")
    elif isinstance(data, list):
        raw = data
    else:
        raise ValueError(
            f"unrecognized compile-audit artifact of type "
            f"{type(data).__name__}")
    out: List[Dict[str, Any]] = []
    for ev in raw:
        if not isinstance(ev, dict) or "module" not in ev:
            continue
        out.append({
            "module": str(ev["module"]),
            "shape_class": str(ev.get("shape_class") or "unknown"),
            "generation": str(ev.get("generation") or "unknown"),
            "seconds": float(ev.get("seconds") or 0.0),
        })
    return out


def load_events_file(path: str) -> List[Dict[str, Any]]:
    with open(path, encoding="utf-8") as f:
        return load_events(json.load(f))


def _candidates(label: str) -> List[str]:
    """Names an event's module label could answer to: the raw label,
    XLA's ``jit_``/``pjit_`` prefix stripped, and the last dotted
    component of a ``train.step``-style ledger label."""
    names = [label]
    for prefix in ("jit_", "pjit_"):
        if label.startswith(prefix):
            names.append(label[len(prefix):])
    if "." in label:
        names.append(label.rsplit(".", 1)[1])
    return names


def site_inventory(modules: Iterable[ModuleInfo]) -> List[SiteRef]:
    """Every jit site the trace-taint plane found, with the name set
    an event label is matched against (wrapped name, bound names, and
    bound names with a ``self.`` prefix stripped)."""
    out: List[SiteRef] = []
    for module in modules:
        mt = tracetaint.taint_analysis(module)
        for site in mt.sites:
            names = set(site.bound)
            names |= {n.split(".", 1)[1] for n in site.bound
                      if n.startswith("self.")}
            if site.wrapped and not site.wrapped.startswith("<"):
                names.add(site.wrapped)
            # label by the name call sites (and event labels) use: the
            # bound name when there is one, else the wrapped function
            label = ("/".join(sorted(site.bound))
                     or (site.wrapped
                         if site.wrapped
                         and not site.wrapped.startswith("<")
                         else "")
                     or "<anonymous>")
            out.append(SiteRef(path=module.rel, line=site.node.lineno,
                               label=label,
                               names=tuple(sorted(names))))
    return out


def audit(events: Sequence[Dict[str, Any]], sites: Sequence[SiteRef],
          *, max_per_shape: int = DEFAULT_MAX_PER_SHAPE) -> AuditReport:
    """Group events by (module, shape_class, generation); every group
    whose count exceeds ``max_per_shape`` is a storm, attributed to
    the static site whose name set matches the module label."""
    by_name: Dict[str, SiteRef] = {}
    for site in sites:
        for n in site.names:
            # first site wins per name; ambiguity keeps the first in
            # walk order — the report carries path:line either way
            by_name.setdefault(n, site)

    groups: Dict[Tuple[str, str, str], List[Dict[str, Any]]] = {}
    for ev in events:
        key = (ev["module"], ev["shape_class"], ev["generation"])
        groups.setdefault(key, []).append(ev)

    storms: List[Storm] = []
    unmatched: Dict[str, int] = {}
    for (module, sc, gen), evs in sorted(groups.items()):
        site = next(
            (by_name[c] for c in _candidates(module) if c in by_name),
            None)
        if site is None:
            unmatched[module] = unmatched.get(module, 0) + len(evs)
        if len(evs) > max_per_shape:
            storms.append(Storm(
                module=module, shape_class=sc, generation=gen,
                count=len(evs), expected=max_per_shape,
                seconds=sum(e["seconds"] for e in evs), site=site))
    storms.sort(key=lambda s: (-s.count, s.module, s.shape_class))
    return AuditReport(events=len(events), sites=len(sites),
                       storms=storms,
                       unmatched=sorted(unmatched.items()))
