"""TPU007 — mesh-axis-consistency (cross-file).

SPMD axis/sharding mistakes dominate TPU-scale debugging cost: a typo'd
axis name in a ``PartitionSpec`` or a ``psum`` doesn't fail until the
program traces inside a mesh on the real runtime — and on a reduced
test mesh ``spec_for_mesh`` silently *drops* unknown axes, so the typo
can ship. The mesh axis vocabulary is declared centrally
(``parallel/mesh.py:MESH_AXES`` plus any explicit ``Mesh(devs,
("dp",...))`` constructions); every axis-name literal used in a
sharding/collective position must resolve against it.

Follows the wiring-checker (TPU004) finalize pattern: :meth:`check`
collects declarations and usages per module, :meth:`finalize`
cross-references once every module has been seen. Usage positions
collected (string literals only — names/variables are runtime-checked
by the mesh rules table and stay out of scope):

- ``PartitionSpec(...)`` / ``P(...)`` entries (names or tuples of
  names);
- the axis argument of the named collectives (``lax.psum``,
  ``ppermute``, ``all_gather``, ``all_to_all``, ``psum_scatter``,
  ``pmean``/``pmax``/``pmin``, ``axis_index``) — second positional or
  ``axis_name=``;
- ``shard_map(..., axis_names={...})`` manual-axis sets;
- string/tuple defaults of parameters literally named ``axis``,
  ``axis_name``, ``seq_axis``, or ``batch_axis`` (the wrapper-API
  convention in ``ops/``).

If the walk saw no declaration at all (scoped run), the rule stays
silent — same partial-run guard as TPU004.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, List, Set, Tuple

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

SPEC_CALLS = {"PartitionSpec", "P"}
COLLECTIVE_CALLS = {"psum", "pmean", "pmax", "pmin", "ppermute",
                    "all_gather", "all_to_all", "psum_scatter",
                    "axis_index", "axis_size", "pbroadcast", "pvary"}
AXIS_PARAM_NAMES = {"axis", "axis_name", "seq_axis", "batch_axis"}
# calls whose axis is the FIRST positional arg (no array operand):
# axis_index(axis_name) / axis_size(axis_name); everything else takes
# (operand, axis_name, ...)
AXIS_FIRST_CALLS = {"axis_index", "axis_size"}
DECL_TUPLE_NAME = "MESH_AXES"


@dataclasses.dataclass
class _AxisUse:
    axis: str
    context: str                 # "PartitionSpec(...)", "lax.psum", ...
    rel: str
    lineno: int
    span: Tuple[int, int]


def _str_elements(node: ast.AST) -> List[str]:
    """String constants in a (possibly nested) literal: "a",
    ("a", "b"), {"a"}, ["a"]. Non-literal elements are skipped."""
    s = astutil.const_str(node)
    if s is not None:
        return [s]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: List[str] = []
        for el in node.elts:
            out.extend(_str_elements(el))
        return out
    return []


def _is_all_str_tuple(node: ast.AST) -> bool:
    return (isinstance(node, ast.Tuple) and node.elts
            and all(astutil.const_str(e) is not None for e in node.elts))


@register_checker
class MeshAxesChecker(Checker):
    rule = "TPU007"
    name = "mesh-axis-consistency"
    severity = "error"

    def __init__(self) -> None:
        self.declared: Set[str] = set()
        self.decl_sites: List[str] = []
        self.uses: List[_AxisUse] = []

    # -- collection --------------------------------------------------------

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        self._collect_declarations(module)
        self._collect_uses(module)
        return ()

    def _collect_declarations(self, module: ModuleInfo) -> None:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) \
                            and tgt.id == DECL_TUPLE_NAME \
                            and _is_all_str_tuple(node.value):
                        self._declare(node.value, module)
            elif isinstance(node, ast.Call):
                name = (astutil.call_name(node) or "").split(".")[-1]
                if name == "Mesh" and len(node.args) >= 2 \
                        and _is_all_str_tuple(node.args[1]):
                    self._declare(node.args[1], module)

    def _declare(self, tup: ast.AST, module: ModuleInfo) -> None:
        self.declared.update(_str_elements(tup))
        if module.rel not in self.decl_sites:
            self.decl_sites.append(module.rel)

    def _collect_uses(self, module: ModuleInfo) -> None:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                self._collect_call(node, module)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_defaults(node, module)

    def _use(self, axis: str, context: str, node: ast.AST,
             module: ModuleInfo) -> None:
        self.uses.append(_AxisUse(
            axis=axis, context=context, rel=module.rel,
            lineno=node.lineno, span=module.node_span(node)))

    def _collect_call(self, node: ast.Call, module: ModuleInfo) -> None:
        dotted = astutil.call_name(node) or ""
        name = dotted.split(".")[-1]
        if name in SPEC_CALLS:
            for arg in node.args:
                for axis in _str_elements(arg):
                    self._use(axis, f"{name}(...)", node, module)
            return
        if name in COLLECTIVE_CALLS:
            pos = 0 if name in AXIS_FIRST_CALLS else 1
            if len(node.args) > pos:
                for axis in _str_elements(node.args[pos]):
                    self._use(axis, dotted, node, module)
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    for axis in _str_elements(kw.value):
                        self._use(axis, dotted, node, module)
            return
        if name == "shard_map":
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    for axis in _str_elements(kw.value):
                        self._use(axis, "shard_map(axis_names=...)",
                                  node, module)

    def _collect_defaults(self, fn, module: ModuleInfo) -> None:
        args = fn.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):],
                                args.defaults):
            if arg.arg in AXIS_PARAM_NAMES:
                for axis in _str_elements(default):
                    self._use(axis, f"default of {arg.arg}=",
                              default, module)
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and arg.arg in AXIS_PARAM_NAMES:
                for axis in _str_elements(default):
                    self._use(axis, f"default of {arg.arg}=",
                              default, module)

    # -- cross-reference ---------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        if not self.declared:
            return  # scoped run never saw a declaration: stay silent
        known = ", ".join(sorted(self.declared))
        where = ", ".join(self.decl_sites)
        for use in self.uses:
            if use.axis in self.declared:
                continue
            yield Finding(
                rule=self.rule, severity=self.severity, path=use.rel,
                line=use.lineno, span=use.span,
                message=f"axis name {use.axis!r} in {use.context} "
                        f"matches no declared mesh axis ({known})",
                hint=f"mesh axes are declared in {where}; on a reduced "
                     "mesh spec_for_mesh silently drops unknown axes, "
                     "so this typo only fails at TPU scale")
