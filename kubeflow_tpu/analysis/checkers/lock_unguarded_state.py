"""TPU010 — unguarded shared state (lock-set dataflow).

The review ledger's most common concurrency class: a class protects an
instance attribute with ``self._lock`` *almost* everywhere, and the
one bare site is the bug — the ThreadingHTTPServer panel counters
raced exactly this way, and the fleet edge's inflight map was
resurrected by an unlocked ``finish()`` write after its replica was
pruned. Single-pass AST matching cannot see "which locks are held
here"; the :mod:`kubeflow_tpu.analysis.locksets` core can.

Flagged: a **write** (assignment, augmented assignment, subscript
store, or mutating container call like ``.append``/``.update``) to an
attribute the guard inference marked as lock-guarded — the majority of
its access sites across the class hold the same lock — at a site
holding **no lock at all**. Reads stay unflagged (a racy read is
sometimes a deliberate fast-path peek; a racy write corrupts), writes
under a *different* lock stay unflagged (lock splitting is a design,
not an accident), and ``__init__`` writes never count (construction
happens-before publication). The limits of the intraprocedural scope
— ``*_locked`` naming convention, private-helper call-site context —
are documented in docs/ANALYSIS.md.
"""

from __future__ import annotations

from typing import Iterable

from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.locksets import lock_analysis
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo


@register_checker
class UnguardedSharedStateChecker(Checker):
    rule = "TPU010"
    name = "unguarded-shared-state"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for cla in lock_analysis(module):
            if not cla.locks:
                continue
            cls_name = cla.cls.name
            for attr in sorted(cla.guards):
                guard = cla.guards[attr]
                for site in cla.attr_sites.get(attr, ()):
                    if not site.is_write or site.held:
                        continue
                    yield Finding(
                        rule=self.rule, severity=self.severity,
                        path=module.rel, line=site.node.lineno,
                        span=module.node_span(site.stmt),
                        message=(
                            f"write to self.{attr} in "
                            f"{cls_name}.{site.method}() holds no lock, "
                            f"but the attribute is guarded by "
                            f"self.{guard} at its other access sites — "
                            f"a cross-thread read-then-act/lost-update "
                            f"race"),
                        hint=(f"take `with self.{guard}:` around the "
                              f"write (or rename the method *_locked "
                              f"if the caller holds it; pragma a "
                              f"deliberate benign race with why)"))
