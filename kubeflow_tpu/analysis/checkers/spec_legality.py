"""TPU008 — PartitionSpec legality.

Two statically-decidable ways to write an illegal ``PartitionSpec``:

- **duplicate axis**: one mesh axis name appearing in two entries (or
  twice inside one tuple entry) — ``P("tp", "tp")`` or
  ``P(("dp", "dp"), None)``. jax rejects this at trace time, but only
  on the path that actually builds the sharding, which on a CPU test
  mesh may never run.
- **rank overflow** (where inferable): a spec with more entries than
  the array it constrains has dimensions. Sharding is positional, so
  the spec's rank must be <= the array's rank. Inference is
  deliberately conservative (false negatives over false positives):
  only flagged when the constrained value resolves — directly or
  through a single same-scope assignment — to a literal-shaped
  ``jnp.zeros/ones/full/empty`` and the spec is a literal
  ``P(...)``/``PartitionSpec(...)`` call in the same
  ``with_sharding_constraint``/``shard_constraint``-style call.

Per-module rule (no finalize): a spec is illegal by its own shape, not
by cross-file facts.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

SPEC_CALLS = {"PartitionSpec", "P"}
SHAPED_CTORS = {"zeros", "ones", "full", "empty"}
CONSTRAINT_CALLS = {"with_sharding_constraint"}


def _spec_entry_axes(arg: ast.AST) -> List[str]:
    """Axis names of one spec entry: "a" -> [a]; ("a","b") -> [a,b]."""
    s = astutil.const_str(arg)
    if s is not None:
        return [s]
    if isinstance(arg, ast.Tuple):
        return [s for e in arg.elts
                if (s := astutil.const_str(e)) is not None]
    return []


def _literal_shape_rank(node: ast.AST) -> Optional[int]:
    """Rank of a ``jnp.zeros((2, 3))``-style call with a literal
    tuple/list shape (scalar int shape = rank 1); None if unprovable."""
    if not isinstance(node, ast.Call):
        return None
    name = (astutil.call_name(node) or "").split(".")[-1]
    if name not in SHAPED_CTORS:
        return None
    if not node.args:
        return None
    shape = node.args[0]
    if isinstance(shape, (ast.Tuple, ast.List)):
        return len(shape.elts)
    if astutil.const_int(shape) is not None:
        return 1
    return None


@register_checker
class SpecLegalityChecker(Checker):
    rule = "TPU008"
    name = "partitionspec-legality"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = (astutil.call_name(node) or "").split(".")[-1]
            if name in SPEC_CALLS:
                yield from self._check_duplicates(module, node)
            if name in CONSTRAINT_CALLS:
                yield from self._check_rank(module, node)

    def _check_duplicates(self, module: ModuleInfo, node: ast.Call):
        seen = {}
        for arg in node.args:
            for axis in _spec_entry_axes(arg):
                if axis in seen:
                    yield self.finding(
                        module, node,
                        f"axis {axis!r} appears twice in one "
                        "PartitionSpec — an array dim cannot shard "
                        "over the same mesh axis twice",
                        hint="drop one occurrence, or shard the second "
                             "dim over a different axis")
                    return  # one finding per spec call is enough
                seen[axis] = True

    def _check_rank(self, module: ModuleInfo, node: ast.Call):
        if len(node.args) < 2:
            return
        value, spec = node.args[0], node.args[1]
        if not (isinstance(spec, ast.Call)
                and (astutil.call_name(spec) or "").split(".")[-1]
                in SPEC_CALLS):
            return
        rank = _literal_shape_rank(value)
        if rank is None and isinstance(value, ast.Name):
            scope = module.enclosing_function(node) or module.tree
            ranks = [_literal_shape_rank(a)
                     for a in astutil.assignments_to(scope, value.id)]
            known = [r for r in ranks if r is not None]
            if len(ranks) == 1 and len(known) == 1:
                rank = known[0]
        if rank is not None and len(spec.args) > rank:
            yield self.finding(
                module, node,
                f"PartitionSpec has {len(spec.args)} entries but the "
                f"constrained array has rank {rank} — sharding is "
                "positional, so the spec cannot be longer than the "
                "shape",
                hint="trim the spec (trailing None entries are "
                     "implicit) or fix the array shape")
