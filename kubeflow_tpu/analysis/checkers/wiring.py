"""TPU004 — wiring consistency across manifests, presets, and routes.

PR 1 wired the serving proxy, the autoscaler, and the dashboard to each
other *by URL string* (``http://serving-autoscaler:8090``), duplicated
across ``config/presets.py``, component DEFAULTS, and route tables.
Nothing type-checks a URL: rename the Service or change its port in
``manifests/components/autoscaler.py`` and every copy elsewhere drifts
silently until a pod can't reach its peer. Same story for RBAC — a
ClusterRole without its binding renders fine and fails at runtime.

This is a cross-file checker: :meth:`check` collects facts per module,
:meth:`finalize` cross-references them.

Sub-rules:

- **url-port**: any ``http(s)://<host>:<port>`` string literal whose
  host equals a component's Service name (the ``DEFAULTS["name"]`` of a
  ``manifests/components/*`` module) must use one of that component's
  declared ports (any int-valued ``*port*`` key in DEFAULTS). Hosts
  that match no component (127.0.0.1, external DNS) are ignored.
- **preset-component**: every ``ComponentSpec("x")`` in ``config/``
  must name a component registered via ``@register("x", ...)``.
- **rbac-pairing**: a component module that renders ``cluster_role``
  must also render ``cluster_role_binding`` and ``service_account``
  (and the namespaced ``role``/``role_binding`` pair likewise).
- **api-route**: a full-URL literal targeting a route-providing service
  (``http://trace-collector:8095/api/traces:ingest``,
  ``http://centraldashboard:80/api/traces/...``) must name a path the
  provider module actually serves — the provider's ``/api/...`` string
  constants are its route table (``dashboard/server.py``,
  ``obs/service.py``, ``autoscale/service.py``). Renaming a trace
  endpoint without updating its callers is exactly the drift class the
  PR-3 observability wiring added.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

COMPONENTS_DIR = "manifests/components/"
CONFIG_DIR = "config/"

# dotted hosts (IPs, FQDNs) never match a bare Service name, so the
# hostname charset is deliberately dot-free; the optional path group
# feeds the api-route sub-rule (path charset excludes quote/markup
# punctuation so docstring samples like ``http://x:1/api/y`` parse clean)
_URL_RE = re.compile(r"https?://([A-Za-z0-9-]+):(\d+)(/[\w\-./:%~]*)?")

# route-providing services: the module whose "/api/..." string constants
# ARE the service's route table. A full-URL literal elsewhere naming one
# of these hosts must use a path the provider serves.
_ROUTE_PROVIDERS: Dict[str, str] = {
    "centraldashboard": "dashboard/server.py",
    "trace-collector": "obs/service.py",
    "serving-autoscaler": "autoscale/service.py",
}


@dataclasses.dataclass
class _Component:
    component_id: str            # @register("id", ...)
    service_name: str            # DEFAULTS["name"]
    ports: Set[int]              # int values of *port* DEFAULTS keys
    rel: str
    lineno: int


@dataclasses.dataclass
class _UrlRef:
    host: str
    port: int
    rel: str
    lineno: int
    span: Tuple[int, int]
    path: str = ""


def _defaults_dict(module: ModuleInfo) -> Optional[ast.Dict]:
    for node in module.tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "DEFAULTS" \
                        and isinstance(node.value, ast.Dict):
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) \
                    and node.target.id == "DEFAULTS" \
                    and isinstance(node.value, ast.Dict):
                return node.value
    return None


def _register_id(module: ModuleInfo) -> Optional[Tuple[str, int]]:
    for fn in astutil.functions(module.tree):
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) \
                    and (astutil.call_name(dec) or "").endswith("register") \
                    and dec.args:
                cid = astutil.const_str(dec.args[0])
                if cid:
                    return cid, dec.lineno
    return None


def _rendered_rbac_calls(module: ModuleInfo) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call):
            name = (astutil.call_name(node) or "").split(".")[-1]
            if name in ("cluster_role", "cluster_role_binding", "role",
                        "role_binding", "service_account"):
                out.add(name)
    return out


@register_checker
class WiringChecker(Checker):
    rule = "TPU004"
    name = "wiring-consistency"
    severity = "error"

    def __init__(self) -> None:
        self.components: Dict[str, _Component] = {}   # by service name
        self.component_ids: Set[str] = set()
        self.urls: List[_UrlRef] = []
        self.specs: List[Tuple[str, str, int, Tuple[int, int]]] = []
        self.rbac: List[Tuple[str, int, Set[str]]] = []
        self.routes: Dict[str, Set[str]] = {}  # provider host -> paths

    # -- collection --------------------------------------------------------

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if "analysis/" in module.rel:
            # don't lint the linter: rule docstrings quote example URLs
            return ()
        if COMPONENTS_DIR in module.rel:
            self._collect_component(module)
        self._collect_urls(module)
        self._collect_routes(module)
        if CONFIG_DIR in module.rel or COMPONENTS_DIR in module.rel:
            self._collect_component_specs(module)
        return ()

    def _collect_routes(self, module: ModuleInfo) -> None:
        for host, rel in _ROUTE_PROVIDERS.items():
            if not module.rel.endswith(rel):
                continue
            routes = self.routes.setdefault(host, set())
            for node in ast.walk(module.tree):
                s = astutil.const_str(node) \
                    if isinstance(node, ast.Constant) else None
                if s and s.startswith("/api/"):
                    routes.add(s)

    def _collect_component(self, module: ModuleInfo) -> None:
        reg = _register_id(module)
        if reg:
            self.component_ids.add(reg[0])
        # RBAC pairing applies to every component module, including the
        # ones with no DEFAULTS dict (e.g. param-less renderers)
        rbac = _rendered_rbac_calls(module)
        if rbac:
            self.rbac.append((module.rel, 1, rbac))
        defaults = _defaults_dict(module)
        if defaults is None:
            return
        service_name = ""
        ports: Set[int] = set()
        lineno = defaults.lineno
        for key, value in zip(defaults.keys, defaults.values):
            k = astutil.const_str(key) if key is not None else None
            if k is None:
                continue
            if k == "name":
                service_name = astutil.const_str(value) or ""
            elif "port" in k:
                v = astutil.const_int(value)
                if v is not None:
                    ports.add(v)
        if service_name:
            self.components[service_name] = _Component(
                component_id=reg[0] if reg else "",
                service_name=service_name, ports=ports,
                rel=module.rel, lineno=lineno)

    def _collect_urls(self, module: ModuleInfo) -> None:
        for node in ast.walk(module.tree):
            s = astutil.const_str(node) if isinstance(node, ast.Constant) \
                else None
            if not s or "://" not in s:
                continue
            for m in _URL_RE.finditer(s):
                self.urls.append(_UrlRef(
                    host=m.group(1), port=int(m.group(2)),
                    rel=module.rel, lineno=node.lineno,
                    span=module.node_span(node),
                    # strip sentence punctuation from prose-embedded URLs
                    path=(m.group(3) or "").rstrip(".,")))

    def _collect_component_specs(self, module: ModuleInfo) -> None:
        for node in ast.walk(module.tree):
            if (isinstance(node, ast.Call) and node.args
                    and (astutil.call_name(node) or "").split(".")[-1]
                    == "ComponentSpec"):
                cid = astutil.const_str(node.args[0])
                if cid:
                    self.specs.append((cid, module.rel, node.lineno,
                                       module.node_span(node)))

    # -- cross-reference ---------------------------------------------------

    def finalize(self) -> Iterable[Finding]:
        for ref in self.urls:
            comp = self.components.get(ref.host)
            if comp is None or not comp.ports:
                continue
            if ref.port not in comp.ports:
                want = ", ".join(str(p) for p in sorted(comp.ports))
                yield Finding(
                    rule=self.rule, severity=self.severity, path=ref.rel,
                    line=ref.lineno, span=ref.span,
                    message=f"URL http://{ref.host}:{ref.port} does not "
                            f"match component {comp.service_name!r} "
                            f"({comp.rel}), which serves on port(s) "
                            f"{want}",
                    hint="update the URL or the component DEFAULTS — "
                         "by-URL wiring drifts silently")
        for ref in self.urls:
            if not ref.path.startswith("/api/"):
                continue
            routes = self.routes.get(ref.host)
            if not routes:
                # provider module absent from this walk (partial runs)
                continue
            if ref.rel.endswith(_ROUTE_PROVIDERS.get(ref.host, "\0")):
                continue  # the provider's own docstring/examples
            ok = ref.path in routes or any(
                ref.path.startswith(r) for r in routes if r.endswith("/"))
            if not ok:
                provider = _ROUTE_PROVIDERS[ref.host]
                yield Finding(
                    rule=self.rule, severity=self.severity, path=ref.rel,
                    line=ref.lineno, span=ref.span,
                    message=f"URL path {ref.path!r} on service "
                            f"{ref.host!r} matches no route served by "
                            f"{provider}",
                    hint="update the caller or the provider's route "
                         "table — endpoint renames drift silently "
                         "behind by-URL wiring")
        if self.component_ids:
            for cid, rel, lineno, span in self.specs:
                if cid not in self.component_ids:
                    known = ", ".join(sorted(self.component_ids))
                    yield Finding(
                        rule=self.rule, severity=self.severity, path=rel,
                        line=lineno, span=span,
                        message=f"ComponentSpec({cid!r}) names no "
                                "registered manifest component",
                        hint=f"known components: {known}")
        for rel, lineno, calls in self.rbac:
            for role, binding in (("cluster_role", "cluster_role_binding"),
                                  ("role", "role_binding")):
                if role in calls and binding not in calls:
                    yield Finding(
                        rule=self.rule, severity=self.severity, path=rel,
                        line=lineno,
                        message=f"component renders {role} but no "
                                f"{binding}; the role grants nothing "
                                "without its binding",
                        hint=f"render o.{binding}(...) (and the "
                             "service_account it binds) next to the role")
            if ("cluster_role_binding" in calls or "role_binding" in calls) \
                    and "service_account" not in calls:
                yield Finding(
                    rule=self.rule, severity=self.severity, path=rel,
                    line=lineno,
                    message="component renders a role binding but no "
                            "service_account; the binding points at a "
                            "subject that is never created",
                    hint="render o.service_account(name, ns) alongside "
                         "the binding")
