"""TPU012 — re-entrant acquisition of a non-reentrant lock.

The repro-tested PR 11 deadlock: ``ModelMultiplexer.lease()`` held
``self._lock`` and called ``self.get()``, which opens with
``with self._lock:`` — a ``threading.Lock`` is not re-entrant, so the
thread blocked on itself and the whole weight pager wedged. The bug is
invisible to pattern matching because the two acquisitions live in
different methods; it is one call-graph hop plus one lock-set fact.

Flagged, for locks discovered as plain ``threading.Lock`` (``RLock``
attributes are re-entrant by contract and never flagged):

- **direct**: an acquisition (``with self._lock:`` or
  ``self._lock.acquire()``) at a statement where the must-analysis
  already proves the same lock held;
- **via the class call graph**: a ``self._foo()`` call at a statement
  holding lock L, where ``_foo`` — or anything transitively reachable
  from it through same-class ``self.*()`` calls — may acquire L. The
  message names the chain so the fix site is obvious.

The fix is the multiplexer's own post-fix shape: hoist the work out
from under the lock, or split a ``_locked`` variant that the guarded
caller uses (the ``*_locked`` naming convention is how the analysis
knows the caller holds it).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Set

from kubeflow_tpu.analysis import callgraph as cg
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.locksets import lock_analysis
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo


def _chain_to_acquirer(calls: Dict[str, Set[str]],
                       local: Dict[str, Set[str]], start: str,
                       lock: str) -> List[str]:
    """Shortest call chain from ``start`` to a method that locally
    acquires ``lock`` (BFS; ``start`` itself may be the acquirer)."""
    frontier = [[start]]
    seen = {start}
    while frontier:
        path = frontier.pop(0)
        if lock in local.get(path[-1], set()):
            return path
        for callee in sorted(calls.get(path[-1], ())):
            if callee not in seen:
                seen.add(callee)
                frontier.append(path + [callee])
    return [start]


@register_checker
class ReentrantLockChecker(Checker):
    rule = "TPU012"
    name = "reentrant-lock-acquire"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for cla in lock_analysis(module):
            plain = {n for n, d in cla.locks.items() if d.kind == "lock"}
            if not plain:
                continue
            per_method = {name: ml.may_acquire
                          for name, ml in cla.methods.items()}
            # the deadlock verdict reads the LOCAL lock states — what
            # the method body itself proves, plus the *_locked
            # convention only in single-lock classes where the suffix
            # is unambiguous. An assumption may excuse a write under
            # TPU010/011 but never convicts a deadlock, and a
            # context-dependent deadlock (callee acquires under a
            # caller's lock) is reported exactly ONCE, at the call
            # site that establishes the context — not again inside
            # the callee off propagated entry state
            for mname, ml in sorted(cla.local.items()):
                for acq in ml.acquires:
                    if acq.lock in plain and acq.lock in acq.held_before:
                        yield Finding(
                            rule=self.rule, severity=self.severity,
                            path=module.rel, line=acq.node.lineno,
                            span=module.node_span(acq.node),
                            message=(
                                f"{cla.cls.name}.{mname}() re-acquires "
                                f"non-reentrant self.{acq.lock} while "
                                f"already holding it — threading.Lock "
                                f"deadlocks against itself"),
                            hint=("use the *_locked-helper split or an "
                                  "RLock if re-entry is the design"))
            # re-acquisition reachable through the class call graph —
            # DIRECT call sites only: a call inside a nested def runs
            # later, usually on another thread, and a threading.Lock
            # deadlocks only against its own thread
            for mname in sorted(cla.graph.direct_call_sites):
                for call, target in cla.graph.direct_call_sites[mname]:
                    held = cla.held_at(mname, call, mode="local")
                    if not held:
                        continue
                    overlap = sorted(
                        held & plain & cla.may_acquire.get(target, set()))
                    for lock in overlap:
                        chain = _chain_to_acquirer(
                            cla.graph.direct_calls, per_method, target,
                            lock)
                        via = " -> ".join(f"{c}()" for c in chain)
                        yield Finding(
                            rule=self.rule, severity=self.severity,
                            path=module.rel, line=call.lineno,
                            span=self._call_span(module, cla, mname,
                                                 call),
                            message=(
                                f"{cla.cls.name}.{mname}() calls "
                                f"self.{target}() while holding "
                                f"non-reentrant self.{lock}, and "
                                f"{via} acquires it again — the "
                                f"recursing-under-lock deadlock "
                                f"(PR 11 lease() class)"),
                            hint=("re-fault outside the lock or call "
                                  "a *_locked variant that assumes "
                                  "the guard"))

    @staticmethod
    def _call_span(module: ModuleInfo, cla, method: str, call):
        stmt = cla.enclosing_stmt(method, call)
        return module.node_span(stmt if stmt is not None else call)
