"""TPU014 — Python control flow on a traced value inside a jit region.

``if``/``while``/``assert`` on a tracer either raises a
concretization error at trace time (the lucky case) or — when the
value sneaks through as a weakly-typed Python bool via shapes that
happen to be concrete — silently splits the program into per-branch
compilations: the recompile-storm signature the compile ledger sees
as the same module fingerprinting differently per step.

The taint core (:mod:`tracetaint`) decides "traced here": parameters
of jit/pjit/Pallas contexts, ``jnp``/``lax`` results, nested scan/cond
bodies, and module-local helpers called with tainted arguments.
Shape/dtype reads, ``len()``, ``is``/``is not`` tests, and
``isinstance`` are host-decidable and never flagged — branch-on-shape
is the idiom, not the bug. The canonical fixes are ``jax.lax.cond`` /
``jax.lax.while_loop`` / ``jnp.where``, or marking the argument
static at the jit boundary.
"""

from __future__ import annotations

import ast
from typing import Iterable

from kubeflow_tpu.analysis import cfg as cfg_mod
from kubeflow_tpu.analysis import tracetaint
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

_KINDS = {ast.If: "if", ast.While: "while", ast.Assert: "assert"}


@register_checker
class TraceControlFlowChecker(Checker):
    rule = "TPU014"
    name = "traced-control-flow"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        mt = tracetaint.taint_analysis(module)
        for fn, ctx_name in mt.traced_functions():
            ft = mt.taint_of(fn)
            for cn in ft.cfg.nodes:
                stmt = cn.node
                if cn.kind != cfg_mod.STMT or stmt is None:
                    continue
                kind = _KINDS.get(type(stmt))
                if kind is None:
                    continue
                env = ft.taint_in.get(cn.nid)
                if env is None:
                    continue  # unreachable statement
                if not ft._expr(stmt.test, env):
                    continue
                yield self.finding(
                    module, stmt,
                    f"Python `{kind}` on a traced value inside jit "
                    f"context {ctx_name!r}; this concretizes a tracer "
                    "(error) or forks one compilation per branch "
                    "(recompile storm)",
                    hint="use jax.lax.cond / jax.lax.while_loop / "
                         "jnp.where, hoist the decision to the host, "
                         "or mark the argument static at the jit "
                         "boundary (shape/dtype reads are static and "
                         "never flagged)")
