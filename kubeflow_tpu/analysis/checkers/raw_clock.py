"""TPU003 — raw wall clock in control-loop code.

Controllers, reconcilers, and pollers that call ``time.time()`` /
``time.sleep()`` / ``datetime.now()`` directly cannot be tested without
real elapsed time, and their behavior differs run to run. The platform
convention (set by :mod:`kubeflow_tpu.autoscale`) is an injectable
clock: components take ``clock: Clock = None`` and default it to the
real clock **by reference** (``self.clock = clock or time.monotonic``)
— references are fine, *calls* are not.

Recognized injectable patterns that are NOT flagged:

- the conditional-default idiom ``now if now is not None else
  time.time()`` (an explicit ``now=`` parameter IS the injection);
- bare references (``time.monotonic`` without calling it).

Intentional sleep-forever entrypoints (``while True: time.sleep(3600)``
serve loops) carry a line pragma; historical debt lives in the
baseline.
"""

from __future__ import annotations

import ast
from typing import Iterable

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

RAW_CLOCK_CALLS = {
    "time.time", "time.sleep",
    "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today", "date.today",
}

# workload example scripts log wall timestamps by design; the platform
# layers are where determinism matters
SKIP_PREFIXES = ("kubeflow_tpu/examples/",)


def _is_injectable_default(module: ModuleInfo, call: ast.Call) -> bool:
    """True when the call is the fallback arm of the conditional-default
    idiom: ``<x> if <cond> else time.time()``."""
    parent = module.parents.get(call)
    return isinstance(parent, ast.IfExp) and parent.orelse is call


@register_checker
class RawClockChecker(Checker):
    rule = "TPU003"
    name = "raw-clock"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if module.rel.startswith(SKIP_PREFIXES):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node) or ""
            if name not in RAW_CLOCK_CALLS:
                continue
            if _is_injectable_default(module, node):
                continue
            yield self.finding(
                module, node,
                f"raw {name}() in platform code; control flow that "
                "depends on the wall clock is untestable and "
                "nondeterministic",
                hint="accept an injectable clock (see "
                     "kubeflow_tpu.autoscale.policy.Clock) defaulting to "
                     "the real clock by reference, or pragma an "
                     "intentional serve-forever loop")
