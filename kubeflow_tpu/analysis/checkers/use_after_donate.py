"""TPU016 — a donated argument read after the jitted call.

``donate_argnums`` hands the argument's buffer to XLA: after the
call returns, the Python name still points at an array whose storage
may have been aliased into the outputs. Reading it "works" on CPU,
returns garbage-or-raises on TPU, and the failure is shape-dependent
— the worst kind of production surprise. The correct idiom rebinds
the name from the call's result (``state = step(state, batch)``),
which this rule recognizes as safe by construction.

Scope (all conservatism, per the analysis-plane contract):

- only call sites whose callee resolves to a jit site with a
  *literal* ``donate_argnums`` (an unresolvable spec like
  ``(0,) if donate else ()`` stays silent);
- only donated arguments that are a bare name or ``self.attr`` —
  expressions have no identity to track;
- intraprocedural: a forward CFG walk from the call marks every path
  until the name is rebound; any read (including the call statement
  itself re-executing in a loop without a rebind) is the finding.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from kubeflow_tpu.analysis import cfg as cfg_mod
from kubeflow_tpu.analysis import tracetaint
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo


def _binds(target: ast.AST, name: str) -> bool:
    if isinstance(target, (ast.Tuple, ast.List)):
        return any(_binds(el, name) for el in target.elts)
    if isinstance(target, ast.Starred):
        return _binds(target.value, name)
    return tracetaint._bindable_name(target) == name


def _stmt_rebinds(cn: cfg_mod.CfgNode, name: str) -> bool:
    stmt = cn.node
    if stmt is None:
        return False
    if cn.kind == cfg_mod.WITH_ENTER:
        return any(item.optional_vars is not None
                   and _binds(item.optional_vars, name)
                   for item in stmt.items)
    if isinstance(stmt, ast.Assign):
        return any(_binds(t, name) for t in stmt.targets)
    if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        return _binds(stmt.target, name)
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return _binds(stmt.target, name)
    return False


def _reads_in(cn: cfg_mod.CfgNode, name: str) -> Optional[ast.AST]:
    """A Load of ``name`` among the expressions evaluated *at* this
    node (branch headers evaluate only their test; Store targets do
    not count — a pure rebind is the safe idiom)."""
    stmt = cn.node
    if stmt is None or cn.kind == cfg_mod.WITH_EXIT:
        return None
    if cn.kind == cfg_mod.WITH_ENTER:
        exprs: List[ast.AST] = [i.context_expr for i in stmt.items]
    elif isinstance(stmt, (ast.If, ast.While)):
        exprs = [stmt.test]
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        exprs = [stmt.iter]
    elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef, ast.Try)):
        return None
    elif hasattr(ast, "Match") and isinstance(stmt, ast.Match):
        exprs = [stmt.subject]
    else:
        exprs = [stmt]
    for root in exprs:
        for node in tracetaint.iter_exprs(root):
            if isinstance(node, ast.Name) and node.id == name \
                    and isinstance(node.ctx, ast.Load):
                return node
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and tracetaint._bindable_name(node) == name:
                return node
            # a Store INTO the donated buffer (x[i] = ...) is a use too
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and tracetaint._bindable_name(node.value) == name:
                return node
    return None


@register_checker
class UseAfterDonateChecker(Checker):
    rule = "TPU016"
    name = "use-after-donate"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        mt = tracetaint.taint_analysis(module)
        if not mt.jitted_names:
            return
        reported: Set[Tuple[int, str]] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = tracetaint._bindable_name(node.func)
            site = mt.site_for_name(callee) if callee else None
            if site is None or not site.donate_argnums:
                continue
            fn = module.enclosing_function(node)
            if fn is None:
                continue
            ft = mt.taint_of(fn)
            stmt = ft.enclosing_stmt(node)
            if stmt is None:
                continue
            start = ft.cfg.stmt_node.get(stmt)
            if start is None:
                continue
            for i in site.donate_argnums:
                if not (0 <= i < len(node.args)):
                    continue
                donated = tracetaint._bindable_name(node.args[i])
                if donated is None:
                    continue
                if _stmt_rebinds(start, donated):
                    continue  # state = step(state, ...): the idiom
                read = self._first_read_after(ft.cfg, start, donated)
                if read is None:
                    continue
                key = (node.lineno, donated)
                if key in reported:
                    continue
                reported.add(key)
                yield self.finding(
                    module, read[0],
                    f"{donated!r} read after being donated to "
                    f"{callee!r} (donate_argnums={i}, call at line "
                    f"{node.lineno}): the buffer may already be "
                    "aliased into the call's outputs",
                    hint="rebind the name from the call's result "
                         "(x = f(x, ...)) before any further use, or "
                         "drop the donation")

    def _first_read_after(self, graph: cfg_mod.Cfg,
                          start: cfg_mod.CfgNode, name: str,
                          ) -> Optional[Tuple[ast.AST, ast.AST]]:
        seen: Set[int] = set()
        stack = list(start.succs)
        while stack:
            nid = stack.pop()
            if nid in seen:
                continue
            seen.add(nid)
            cn = graph.nodes[nid]
            read = _reads_in(cn, name)
            if read is not None:
                return (cn.node, read)
            if _stmt_rebinds(cn, name):
                continue  # rebound: paths beyond here are clean
            stack.extend(cn.succs)
        return None
