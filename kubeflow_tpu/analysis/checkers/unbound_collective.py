"""TPU009 — collective over an axis no enclosing region binds.

``lax.psum(x, "tp")`` is only legal while ``"tp"`` is bound as a named
axis — inside a ``shard_map``/``pmap`` region over it. Called outside
one, it raises ``NameError: unbound axis name`` at trace time, which on
the serving path means the first real request, not the test suite.

The rule resolves *literal* axis names only (variables flow through
wrapper APIs whose values are runtime-checked; chasing them would
guess). A literal axis ``a`` used in a collective inside function ``f``
counts as bound when any function on the lexical chain around the call
(``f`` or an enclosing def) either

- is shard-wrapped in the same module — its name (or, for an inline
  lambda body, the lambda itself) appears as the mapped function of a
  ``shard_map(...)`` call (directly or through ``functools.partial``)
  whose ``axis_names={...}`` contains ``a``, or which passes no
  ``axis_names`` at all (full-manual: every mesh axis is bound); or
- is pmap/vmap/xmap-wrapped with ``axis_name="a"`` /
  ``axis_name=<non-literal>`` (a non-literal binder may bind anything:
  stay silent rather than guess).

Cross-module callers are invisible to a single-module AST, so exported
helpers meant to run inside someone else's region (the
``ops/attention.py`` cores take ``axis_name`` as a *parameter*, the
convention that sidesteps this rule entirely) should take the axis as
an argument rather than hard-coding it; intentional hard-coded cases
carry a pragma.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

COLLECTIVE_CALLS = {"psum", "pmean", "pmax", "pmin", "ppermute",
                    "all_gather", "all_to_all", "psum_scatter",
                    "axis_index", "axis_size"}
# axis_index/axis_size take the axis FIRST (no array operand)
AXIS_FIRST_CALLS = {"axis_index", "axis_size"}
BINDER_CALLS = {"shard_map", "pmap", "xmap", "vmap"}

ALL_AXES = "*"


@dataclasses.dataclass
class _Binding:
    axes: Set[str]            # bound axis literals; ALL_AXES = everything
    unknown: bool = False     # non-literal binder: could bind anything


def _mapped_fn(call: ast.Call):
    """What a binder call wraps: the function *name* for
    ``shard_map(core, ...)`` / ``shard_map(functools.partial(core,
    ...), ...)``, or the ``ast.Lambda`` node itself for an inline
    ``shard_map(lambda v: ..., ...)`` body."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Name):
        return arg.id
    if isinstance(arg, ast.Lambda):
        return arg
    if isinstance(arg, ast.Call):
        inner = astutil.call_name(arg) or ""
        if inner.split(".")[-1] == "partial" and arg.args:
            if isinstance(arg.args[0], ast.Name):
                return arg.args[0].id
            if isinstance(arg.args[0], ast.Lambda):
                return arg.args[0]
            name = astutil.dotted_name(arg.args[0])
            if name:
                return name.split(".")[-1]
    return None


def _binder_axes(call: ast.Call, binder: str) -> _Binding:
    if binder == "shard_map":
        for kw in call.keywords:
            if kw.arg == "axis_names":
                if isinstance(kw.value, (ast.Set, ast.Tuple, ast.List)):
                    axes = {astutil.const_str(e) for e in kw.value.elts}
                    if None in axes:
                        return _Binding(set(), unknown=True)
                    return _Binding({a for a in axes if a})
                return _Binding(set(), unknown=True)
        return _Binding({ALL_AXES})  # full-manual: all mesh axes bound
    for kw in call.keywords:   # pmap / vmap / xmap
        if kw.arg == "axis_name":
            s = astutil.const_str(kw.value)
            if s is None:
                return _Binding(set(), unknown=True)
            return _Binding({s})
    return _Binding(set())


@register_checker
class UnboundCollectiveChecker(Checker):
    rule = "TPU009"
    name = "unbound-collective"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        bindings: Dict[str, List[_Binding]] = {}       # by function name
        lambda_bindings: Dict[int, _Binding] = {}      # by Lambda node id
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            binder = (astutil.call_name(node) or "").split(".")[-1]
            if binder in BINDER_CALLS:
                target = _mapped_fn(node)
                if isinstance(target, ast.Lambda):
                    lambda_bindings[id(target)] = _binder_axes(node, binder)
                elif target:
                    bindings.setdefault(target, []).append(
                        _binder_axes(node, binder))

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = astutil.call_name(node) or ""
            short = dotted.split(".")[-1]
            if short not in COLLECTIVE_CALLS:
                continue
            axis = self._literal_axis(node, short)
            if axis is None:
                continue
            if self._is_bound(module, node, axis, bindings,
                              lambda_bindings):
                continue
            yield self.finding(
                module, node,
                f"{dotted}(..., {axis!r}) but no enclosing shard_map/"
                f"pmap region binds axis {axis!r} — this raises "
                "'unbound axis name' at trace time",
                hint="wrap the caller in shard_map over the axis, or "
                     "take the axis name as a parameter like the "
                     "ops/attention.py cores do")

    def _literal_axis(self, node: ast.Call,
                      short_name: str) -> Optional[str]:
        pos = 0 if short_name in AXIS_FIRST_CALLS else 1
        if len(node.args) > pos:
            s = astutil.const_str(node.args[pos])
            if s is not None:
                return s
        for kw in node.keywords:
            if kw.arg == "axis_name":
                return astutil.const_str(kw.value)
        return None

    def _is_bound(self, module: ModuleInfo, node: ast.AST, axis: str,
                  bindings: Dict[str, List[_Binding]],
                  lambda_bindings: Dict[int, _Binding]) -> bool:
        def matches(b: _Binding) -> bool:
            return b.unknown or ALL_AXES in b.axes or axis in b.axes

        # walk the full lexical chain (named defs AND inline lambdas
        # handed straight to a binder call)
        cur = module.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(matches(b) for b in bindings.get(cur.name, ())):
                    return True
            elif isinstance(cur, ast.Lambda):
                b = lambda_bindings.get(id(cur))
                if b is not None and matches(b):
                    return True
            cur = module.parents.get(cur)
        return False
