"""TPU015 — jit usage patterns that defeat the compile cache.

Four statically-visible recompile hazards, all of which the compile
ledger (PR 18) can only bill *after* the chip stalls:

- ``jax.jit(...)`` constructed inside a loop: a fresh jit wrapper per
  iteration means a fresh compile-cache entry per iteration;
- ``jax.jit`` wrapping a callable that is itself rebuilt per call —
  a ``lambda`` or ``functools.partial`` inside a function body: the
  cache keys on callable identity, so every call of the enclosing
  function compiles again (module-level lambdas/partials are built
  once and stay silent);
- a non-hashable literal (list/dict/set) or a *traced* value flowing
  into a ``static_argnums``/``static_argnames`` position at a call
  site of a jitted callable: non-hashables raise, traced statics
  either raise or recompile per value;
- an unbucketed shape-bearing value (``len(...)``/``.shape``-derived
  with no routing through the ``ops/autotune`` ``*bucket`` shape-class
  vocabulary) into a static position: one compile per distinct length
  instead of one per bucket — the exact storm
  ``--compile-audit`` attributes from ledger events.

Only call sites whose static spec resolved to literals are examined
(:mod:`tracetaint` leaves ``static_argnums=<expr>`` as None), so an
unresolvable spec stays silent per the conservatism contract.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from kubeflow_tpu.analysis import astutil, tracetaint
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo


def _derives_shape(scope: Optional[ast.AST], node: ast.AST,
                   depth: int = 2) -> bool:
    """Does ``node`` derive from ``len()``/``.shape`` with no
    ``*bucket`` sanitizer on the way? One level of single-assignment
    name resolution, bounded."""
    bucketed = False
    shapey = False
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = astutil.call_name(sub) or ""
            if name.split(".")[-1].endswith("bucket"):
                bucketed = True
            elif name == "len":
                shapey = True
        elif isinstance(sub, ast.Attribute) and sub.attr == "shape":
            shapey = True
    if bucketed:
        return False
    if shapey:
        return True
    if depth > 0 and isinstance(node, ast.Name) and scope is not None:
        values = list(astutil.assignments_to(scope, node.id))
        if len(values) == 1:
            return _derives_shape(scope, values[0], depth - 1)
    return False


_MEMO_DECORATORS = {"lru_cache", "cache"}


def _memoized_factory(module: ModuleInfo, node: ast.AST) -> bool:
    """Is ``node`` inside a function decorated with
    ``functools.lru_cache``/``functools.cache``? A memoized factory
    returning ``jax.jit(partial(...))`` builds one wrapper per key —
    the sanctioned per-config compile-cache idiom, not a hazard."""
    fn = module.enclosing_function(node)
    while fn is not None:
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = astutil.dotted_name(target) or ""
            if name.split(".")[-1] in _MEMO_DECORATORS:
                return True
        fn = module.enclosing_function(fn)
    return False


@register_checker
class RecompileHazardChecker(Checker):
    rule = "TPU015"
    name = "recompile-hazard"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        mt = tracetaint.taint_analysis(module)
        yield from self._construction_hazards(module, mt)
        yield from self._static_position_hazards(module, mt)

    # -- jit construction --------------------------------------------------

    def _construction_hazards(self, module, mt) -> Iterable[Finding]:
        for site in mt.sites:
            if site.kind != "call":
                continue
            if site.in_loop:
                yield self.finding(
                    module, site.node,
                    "jax.jit constructed inside a loop: every iteration "
                    "makes a fresh wrapper and a fresh compile-cache "
                    "entry",
                    hint="hoist the jit out of the loop and call the "
                         "one wrapper per iteration")
            elif site.fresh_callee and site.enclosing is not None \
                    and not site.immediate \
                    and not _memoized_factory(module, site.node):
                yield self.finding(
                    module, site.node,
                    f"jax.jit wraps a {site.wrapped!r} built per call "
                    f"of {site.enclosing!r}; the compile cache keys on "
                    "callable identity, so each call compiles again",
                    hint="define the callable once at module scope (or "
                         "close over the varying values inside one "
                         "def) and jit that single object")

    # -- static positions at call sites ------------------------------------

    def _static_position_hazards(self, module, mt) -> Iterable[Finding]:
        if not mt.jitted_names:
            return
        seen: Set[int] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = tracetaint._bindable_name(node.func)
            site = mt.site_for_name(name) if name else None
            if site is None:
                continue
            fn = module.enclosing_function(node)
            ft = mt.taint_of(fn) if fn is not None else None
            for i in site.static_argnums or ():
                if 0 <= i < len(node.args):
                    yield from self._check_static(
                        module, node, node.args[i], f"static_argnums {i}",
                        name, fn, ft, seen)
            for aname in site.static_argnames or ():
                for kw in node.keywords:
                    if kw.arg == aname:
                        yield from self._check_static(
                            module, node, kw.value,
                            f"static_argnames {aname!r}", name, fn, ft,
                            seen)

    def _check_static(self, module, call, arg, pos, callee, fn, ft,
                      seen: Set[int]) -> Iterable[Finding]:
        if id(arg) in seen:
            return
        if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
            seen.add(id(arg))
            yield self.finding(
                module, call,
                f"non-hashable literal in {pos} of jitted "
                f"{callee!r}: static arguments are cache keys and "
                "must hash",
                hint="pass a tuple (or a frozen dataclass) instead")
            return
        if ft is not None and ft.expr_tainted(arg):
            seen.add(id(arg))
            yield self.finding(
                module, call,
                f"traced value in {pos} of jitted {callee!r}: a "
                "tracer cannot be a cache key — this raises, or "
                "recompiles per value once materialized",
                hint="pass the value as a regular (traced) argument, "
                     "or materialize + bucket it on the host first")
            return
        if _derives_shape(fn, arg):
            seen.add(id(arg))
            yield self.finding(
                module, call,
                f"unbucketed shape-bearing value in {pos} of jitted "
                f"{callee!r}: one compile per distinct length instead "
                "of one per shape class",
                hint="route the value through the ops/autotune bucket "
                     "vocabulary (seq_bucket/pow2_bucket) so compiles "
                     "land on the ledger's shape-class grid",
                severity="warning")
