"""TPU017 — implicit device→host sync inside a hot path.

``.item()``, ``float()``/``int()``, ``np.asarray``, ``.tolist()`` and
``.block_until_ready()`` on a device value block the Python thread
until the device catches up. Once per job that is instrumentation;
inside a training-step loop or the decode engine's admission path it
serializes host and device per iteration — the dispatch-stall badput
the goodput ledger bills but cannot locate.

Hot regions (call-graph-scoped, like TPU012's deadlock reachability):

- the body of any loop that drives a jitted callable (a train/decode
  step loop), in any function;
- ``_admit*`` methods of a class owning jitted callables (the
  ``DecodeEngine`` admission path), plus every same-class method
  transitively reachable from a hot seed over direct call edges.

Only *tainted* values (per :mod:`tracetaint`: results of jitted
calls / ``jnp`` ops) flag — ``float(self.threshold)`` in the same
loop is host arithmetic and stays silent. Syncs before or after the
loop (e.g. materializing final tokens once) are not hot and never
flag. A deliberate sync — the one transfer point where results
surface per design — gets an inline pragma with its justification.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis import callgraph as cg
from kubeflow_tpu.analysis import tracetaint
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

SYNC_METHODS = {"item", "tolist", "block_until_ready"}
SYNC_CALLS = {"float", "int", "bool", "np.asarray", "np.array",
              "numpy.asarray", "numpy.array", "jax.device_get"}
HOT_METHOD_PREFIX = "_admit"


def _sync_target(node: ast.Call) -> Optional[Tuple[str, ast.AST]]:
    """(sync op name, the expression being synced) or None."""
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in SYNC_METHODS:
        return (f".{func.attr}()", func.value)
    name = astutil.call_name(node) or ""
    if name in SYNC_CALLS and node.args:
        return (f"{name}()", node.args[0])
    return None


def _calls_jitted(root: ast.AST, mt) -> bool:
    for node in tracetaint.iter_exprs(root):
        if isinstance(node, ast.Call):
            name = tracetaint._bindable_name(node.func)
            if name and name in mt.jitted_names:
                return True
    return False


def _hot_loops(fn, mt) -> List[ast.AST]:
    """Loops in ``fn`` (nested defs excluded) whose body drives a
    jitted callable — the step-loop signature."""
    out = []
    for node in tracetaint.iter_exprs(fn):
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)) \
                and _calls_jitted(node, mt):
            out.append(node)
    return out


@register_checker
class HostSyncChecker(Checker):
    rule = "TPU017"
    name = "host-sync-in-hot-path"
    severity = "warning"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        mt = tracetaint.taint_analysis(module)
        if not mt.jitted_names:
            return
        seen: Set[int] = set()
        # `a, b = np.asarray(a), np.asarray(b)` is one surfacing
        # point, not two findings — collapse identical (line, op).
        emitted: Set[Tuple[int, str]] = set()

        # class dimension: _admit* seeds + call-graph closure, on
        # classes that own jitted callables (self._step = jax.jit(...))
        hot_methods: Dict[int, str] = {}  # id(fn) → reason
        for cls in cg.classes_in(module.tree):
            owns = any(b.startswith("self.") and b in mt.jitted_names
                       for site in mt.sites for b in site.bound
                       if self._inside(module, site.node, cls))
            if not owns:
                continue
            graph = cg.class_graph(cls)
            seeds = {m for m in graph.methods
                     if m.startswith(HOT_METHOD_PREFIX)}
            # methods invoked from inside a hot loop of the same class
            for name, fn in graph.methods.items():
                for loop in _hot_loops(fn, mt):
                    for node in tracetaint.iter_exprs(loop):
                        if isinstance(node, ast.Call):
                            attr = tracetaint._self_attr(node.func)
                            if attr in graph.methods:
                                seeds.add(attr)
            reach = set(seeds)
            frontier = list(seeds)
            while frontier:
                m = frontier.pop()
                for callee in graph.direct_calls.get(m, ()):
                    if callee not in reach:
                        reach.add(callee)
                        frontier.append(callee)
            for m in reach:
                fn = graph.methods.get(m)
                if fn is not None:
                    hot_methods[id(fn)] = (
                        "decode admit path"
                        if m.startswith(HOT_METHOD_PREFIX)
                        else "reachable from an admit/step-loop seed")

        for fn in astutil.functions(module.tree):
            ft = None
            regions: List[Tuple[ast.AST, str]] = []
            if id(fn) in hot_methods:
                regions.append((fn, hot_methods[id(fn)]))
            else:
                for loop in _hot_loops(fn, mt):
                    regions.append(
                        (loop, f"loop driving a jitted callable "
                               f"(line {loop.lineno})"))
            for root, reason in regions:
                for node in tracetaint.iter_exprs(root):
                    if not isinstance(node, ast.Call) \
                            or id(node) in seen:
                        continue
                    hit = _sync_target(node)
                    if hit is None:
                        continue
                    if ft is None:
                        ft = mt.taint_of(fn)
                    if not ft.expr_tainted(hit[1]):
                        continue
                    seen.add(id(node))
                    if (node.lineno, hit[0]) in emitted:
                        continue
                    emitted.add((node.lineno, hit[0]))
                    yield self.finding(
                        module, node,
                        f"implicit host sync {hit[0]} on a device "
                        f"value in a hot path ({reason}): the host "
                        "blocks until the device drains",
                        hint="keep the value device-side, batch the "
                             "transfer outside the loop, or mark the "
                             "deliberate surfacing point with a "
                             "justified pragma")

    @staticmethod
    def _inside(module: ModuleInfo, node: ast.AST,
                cls: ast.ClassDef) -> bool:
        cur = module.parents.get(node)
        while cur is not None:
            if cur is cls:
                return True
            cur = module.parents.get(cur)
        return False
