"""TPU002 — host calls reachable inside jit/pjit/Pallas bodies.

A traced function runs at *trace* time, once per compilation: a
``time.time()`` inside it bakes one timestamp into the compiled
program, ``np.random`` silently produces one constant sample forever,
``print`` fires during tracing rather than per step, and file I/O
happens at an unpredictable moment on an unpredictable host. All four
are bugs that pass a single-run eyeball test and corrupt every run
after the first.

Jit contexts recognized:

- functions decorated ``@jax.jit`` / ``@jit`` / ``@pjit`` or
  ``@functools.partial(jax.jit, ...)``;
- functions passed by name to ``jax.jit(fn, ...)`` / ``pjit(fn)``
  anywhere in the module;
- Pallas kernel bodies: the first argument of a ``pl.pallas_call``
  (optionally wrapped in ``functools.partial``).

Nested defs inside a jit context are traced too and are walked; calls
under ``jax.debug.*`` / ``pl.debug_print`` / ``io_callback`` /
``host_callback`` are the sanctioned escape hatches and are ignored.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

JIT_NAMES = {"jit", "jax.jit", "pjit", "jax.experimental.pjit.pjit"}
PALLAS_CALL_SUFFIX = "pallas_call"

# dotted-name prefixes that mean "the host is doing work at trace time"
# ("os." covers all of it: filesystem, environ reads, getpid, ...)
BANNED_PREFIXES = ("time.", "np.random.", "numpy.random.", "random.",
                   "os.")
BANNED_EXACT = {"print", "open", "input", "breakpoint"}
# sanctioned escape hatches — anything routed through these is fine
ALLOWED_PREFIXES = ("jax.debug.", "pl.debug_", "pltpu.debug_")
ALLOWED_SUFFIXES = ("io_callback", "host_callback", "debug_print",
                    "debug_callback", "pure_callback")


def _first_arg_fn_name(call: ast.Call) -> str:
    """Name of the function a jit()/pallas_call() wraps: a bare Name or
    the first arg of a functools.partial."""
    if not call.args:
        return ""
    arg = call.args[0]
    if isinstance(arg, ast.Name):
        return arg.id
    if isinstance(arg, ast.Call):
        name = astutil.call_name(arg) or ""
        if name in ("functools.partial", "partial") and arg.args:
            inner = arg.args[0]
            if isinstance(inner, ast.Name):
                return inner.id
    return ""


def _jit_context_functions(module: ModuleInfo) -> Dict[str, ast.AST]:
    """qualified-ish name → FunctionDef for every jit/Pallas context."""
    defs: Dict[str, list] = {}
    for fn in astutil.functions(module.tree):
        defs.setdefault(fn.name, []).append(fn)

    contexts: Dict[str, ast.AST] = {}
    # decorated form
    for fn in astutil.functions(module.tree):
        if set(astutil.decorator_names(fn)) & JIT_NAMES:
            contexts[fn.name] = fn
    # call form: jax.jit(step) / pl.pallas_call(kernel) /
    # pl.pallas_call(functools.partial(kernel, ...))
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node) or ""
        is_jit = name in JIT_NAMES
        is_pallas = name.split(".")[-1] == PALLAS_CALL_SUFFIX
        if not (is_jit or is_pallas):
            continue
        target = _first_arg_fn_name(node)
        for fn in defs.get(target, []):
            contexts[fn.name] = fn
    return contexts


def _is_banned(name: str) -> bool:
    if name in BANNED_EXACT:
        return True
    return any(name.startswith(p) for p in BANNED_PREFIXES)


def _is_allowed(name: str) -> bool:
    if any(name.startswith(p) for p in ALLOWED_PREFIXES):
        return True
    return name.split(".")[-1] in ALLOWED_SUFFIXES


@register_checker
class HostCallInJitChecker(Checker):
    rule = "TPU002"
    name = "host-call-in-jit"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        seen: Set[int] = set()
        for ctx_name, fn in _jit_context_functions(module).items():
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                name = astutil.call_name(node) or ""
                if not name or _is_allowed(name) or not _is_banned(name):
                    continue
                seen.add(id(node))
                yield self.finding(
                    module, node,
                    f"host call {name}() reachable inside jit/Pallas "
                    f"context {ctx_name!r}; it runs at trace time, not "
                    "per step",
                    hint="move the call outside the traced function, pass "
                         "its result as an argument, or use jax.debug.* / "
                         "io_callback for intentional host round-trips")
