"""TPU011 — blocking call / foreign code invoked while holding a lock.

The lease-deadlock and serial-poller-staleness class: a lock is cheap
only while its critical sections are short and *closed* — the moment
a section does network I/O, sleeps, shells out, or calls code the
class does not own (a caller-supplied callback), every other thread
needing that lock inherits the latency, and a callback that re-enters
or raises under the guard wedges or corrupts the class (the fleet
edge's raising ``url_for`` aborted every remaining model's scaling
tick; the multiplexer's store load under the pager lock serialized
every cold fault behind one RPC).

Flagged, at any statement where the lock-set analysis proves a lock is
held:

- sleep-shaped calls: ``time.sleep`` and the injectable-``Sleep``
  contract (any ``*sleep`` callable — ``self._sleep(...)``);
- network fetches: ``urlopen``, ``requests.get/post/...``,
  ``socket.create_connection``, ``getresponse``;
- subprocess spawns: ``subprocess.run/Popen/call/check_*``,
  ``os.system``/``os.popen``;
- caller-supplied callbacks: invoking ``self._x`` where ``__init__``
  assigned it from a bare constructor parameter, or invoking a bare
  parameter of the enclosing method. Clock-named injectables are
  exempt (calling a clock under a lock is cheap and everywhere — the
  TPU003 idiom must not collide with this rule).

The fix shape is always the same and the codebase is full of worked
examples: snapshot state under the lock, drop the lock, do the slow
thing, re-take the lock to publish (``serving/multiplex.py`` fault
protocol, ``edge/fleet.py`` poller).
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from kubeflow_tpu.analysis import cfg as cfg_mod
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.locksets import (
    _dotted,
    _stmt_exprs,
    lock_analysis,
)
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

_SUBPROCESS = {"subprocess.run", "subprocess.Popen", "subprocess.call",
               "subprocess.check_call", "subprocess.check_output",
               "os.system", "os.popen"}
_NET_SEGMENTS = {"urlopen", "getresponse", "create_connection"}
_REQUESTS_VERBS = {"get", "post", "put", "patch", "delete", "head",
                   "request"}


def _method_params(fn) -> Set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    return {n for n in names if n != "self" and "clock" not in n.lower()}


def classify_blocking(call: ast.Call, injected: Set[str],
                      params: Set[str]) -> Optional[str]:
    """What kind of blocking call this is, or None."""
    func = call.func
    name = _dotted(func) or ""
    seg = name.split(".")[-1].lower() if name else ""
    if seg == "sleep" or seg.endswith("_sleep"):
        return "sleep"
    if name in _SUBPROCESS:
        return "subprocess"
    if seg in _NET_SEGMENTS:
        return "network fetch"
    if name.startswith("requests.") and seg in _REQUESTS_VERBS:
        return "network fetch"
    if (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and func.attr in injected):
        return "caller-supplied callback"
    if isinstance(func, ast.Name) and func.id in params:
        return "caller-supplied callback"
    return None


@register_checker
class BlockingUnderLockChecker(Checker):
    rule = "TPU011"
    name = "blocking-under-lock"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for cla in lock_analysis(module):
            if not cla.locks:
                continue
            injected = set(cla.graph.injected_callables)
            for mname, ml in sorted(cla.methods.items()):
                params = _method_params(ml.fn)
                for cn in ml.cfg.nodes:
                    if cn.kind not in (cfg_mod.STMT, cfg_mod.WITH_ENTER):
                        continue
                    held = ml.held_in.get(cn.nid)
                    if not held:
                        continue
                    for node in _stmt_exprs(cn):
                        if not isinstance(node, ast.Call):
                            continue
                        kind = classify_blocking(node, injected, params)
                        if kind is None:
                            continue
                        locks = ", ".join(f"self.{n}"
                                          for n in sorted(held))
                        what = _dotted(node.func) or "<call>"
                        yield Finding(
                            rule=self.rule, severity=self.severity,
                            path=module.rel, line=node.lineno,
                            span=module.node_span(
                                cn.node if cn.node is not None else node),
                            message=(
                                f"{kind} `{what}(...)` in "
                                f"{cla.cls.name}.{mname}() while "
                                f"holding {locks} — every thread "
                                f"needing the lock inherits this "
                                f"latency, and foreign code under a "
                                f"guard can re-enter or raise"),
                            hint=("snapshot under the lock, release, "
                                  "do the slow call, re-lock to "
                                  "publish (see serving/multiplex.py "
                                  "fault protocol)"))
