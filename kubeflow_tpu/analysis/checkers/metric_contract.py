"""TPU013 — metric-contract consistency for ``kftpu_*`` series.

Two shipped bugs define the class: ``kftpu_engine_slots`` split its
series across ``model=""``/``model="x"`` because one emission site
labeled and another did not, and the five ``kftpu_engine_kv_pages_*``
gauge write sites drifted until PR 11 unified them. The registry
dedups metrics **by name, first registration wins** — so a second
registration with a different help string silently loses, and an
emission site with a different label-key set silently forks the
series into rows no query joins back together.

Walker-level (no dataflow): per module, collect

- **registration sites**: ``<registry>.counter/gauge/histogram(
  "kftpu_...", "help")`` calls — the name and help literals;
- **emission sites**: ``.inc/.set/.observe/.get/.remove(...)`` calls
  on a module variable bound to a registered metric — the label-key
  set is the call's keyword names (``**{"k": v}`` dict-literal splats
  are resolved; a non-literal splat makes the site unknowable and it
  is skipped, per the prove-it-or-stay-silent contract).

Then cross-reference at :meth:`finalize`: every ``kftpu_*`` name must
have exactly one help string across all registrations and exactly one
label-key set across all resolvable emission sites, repo-wide. The
majority contract wins; minority sites are flagged.
"""

from __future__ import annotations

import ast
import dataclasses
from collections import Counter
from typing import Dict, FrozenSet, Iterable, List, Optional, Tuple

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

_REG_METHODS = {"counter", "gauge", "histogram"}
_EMIT_METHODS = {"inc", "set", "observe", "get", "remove"}
# value-position keywords that are not label keys
_NON_LABEL_KWARGS = {"amount", "value", "exemplar_trace_id"}


@dataclasses.dataclass
class _RegSite:
    name: str
    help: Optional[str]          # None: non-literal (unknowable)
    rel: str
    lineno: int
    span: Tuple[int, int]


@dataclasses.dataclass
class _EmitSite:
    name: str
    labels: FrozenSet[str]
    rel: str
    lineno: int
    span: Tuple[int, int]


def _label_keys(call: ast.Call) -> Optional[FrozenSet[str]]:
    """Keyword names of an emission call, or None when a non-literal
    ``**splat`` makes the set unknowable."""
    keys = []
    for kw in call.keywords:
        if kw.arg is None:
            if isinstance(kw.value, ast.Dict) and all(
                    astutil.const_str(k) is not None
                    for k in kw.value.keys):
                keys.extend(astutil.const_str(k) for k in kw.value.keys)
            else:
                return None
        elif kw.arg not in _NON_LABEL_KWARGS:
            keys.append(kw.arg)
    return frozenset(keys)


@register_checker
class MetricContractChecker(Checker):
    rule = "TPU013"
    name = "metric-contract"
    severity = "error"

    def __init__(self) -> None:
        self.regs: List[_RegSite] = []
        self.emits: List[_EmitSite] = []

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if "analysis/" in module.rel:
            return ()  # rule docstrings quote example series
        var_to_metric: Dict[str, str] = {}
        calls: List[ast.Call] = [
            n for n in ast.walk(module.tree) if isinstance(n, ast.Call)]
        for call in calls:
            func = call.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr not in _REG_METHODS or not call.args:
                continue
            name = astutil.const_str(call.args[0])
            if not name or not name.startswith("kftpu_"):
                continue
            help_ = None
            if len(call.args) > 1:
                help_ = astutil.const_str(call.args[1])
            for kw in call.keywords:
                if kw.arg in ("help_", "help"):
                    help_ = astutil.const_str(kw.value)
            self.regs.append(_RegSite(
                name=name, help=help_, rel=module.rel,
                lineno=call.lineno, span=module.node_span(call)))
            parent = module.parents.get(call)
            if isinstance(parent, ast.Assign) \
                    and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                var_to_metric[parent.targets[0].id] = name
        for call in calls:
            func = call.func
            if not isinstance(func, ast.Attribute) \
                    or func.attr not in _EMIT_METHODS:
                continue
            if not isinstance(func.value, ast.Name):
                continue
            metric = var_to_metric.get(func.value.id)
            if metric is None:
                continue
            labels = _label_keys(call)
            if labels is None:
                continue  # non-literal splat: unknowable
            self.emits.append(_EmitSite(
                name=metric, labels=labels, rel=module.rel,
                lineno=call.lineno, span=module.node_span(call)))
        return ()

    def finalize(self) -> Iterable[Finding]:
        by_name: Dict[str, List[_RegSite]] = {}
        for r in self.regs:
            by_name.setdefault(r.name, []).append(r)
        for name in sorted(by_name):
            regs = sorted(by_name[name], key=lambda r: (r.rel, r.lineno))
            helps = [r.help for r in regs if r.help is not None]
            variants = sorted(set(helps))
            if len(variants) > 1:
                canon = Counter(helps).most_common(1)[0][0]
                for r in regs:
                    if r.help is not None and r.help != canon:
                        yield Finding(
                            rule=self.rule, severity=self.severity,
                            path=r.rel, line=r.lineno, span=r.span,
                            message=(
                                f"metric {name!r} registered with "
                                f"help {r.help!r} but the majority of "
                                f"registration sites say {canon!r} — "
                                f"the registry keeps whichever loads "
                                f"first, so one of them silently "
                                f"loses"),
                            hint="hoist the registration next to the "
                                 "canonical help string (one "
                                 "registration site per metric)")
        by_emit: Dict[str, List[_EmitSite]] = {}
        for e in self.emits:
            by_emit.setdefault(e.name, []).append(e)
        for name in sorted(by_emit):
            emits = sorted(by_emit[name],
                           key=lambda e: (e.rel, e.lineno))
            sets = Counter(e.labels for e in emits)
            if len(sets) <= 1:
                continue
            # the majority label-key set is the contract; ties break
            # toward the lexicographically smallest so runs are stable
            canon = sorted(sets.items(),
                           key=lambda kv: (-kv[1], sorted(kv[0])))[0][0]
            want = "{" + ", ".join(sorted(canon)) + "}"
            for e in emits:
                if e.labels == canon:
                    continue
                got = "{" + ", ".join(sorted(e.labels)) + "}"
                yield Finding(
                    rule=self.rule, severity=self.severity,
                    path=e.rel, line=e.lineno, span=e.span,
                    message=(
                        f"metric {name!r} emitted with label keys "
                        f"{got} but its other sites use {want} — "
                        f"mismatched key sets fork the series into "
                        f"rows no query joins back (the "
                        f"kftpu_engine_slots model=\"\" split)"),
                    hint="emit every site with the same label-key "
                         "set (label an 'unknown' value explicitly "
                         "rather than omitting the key)")
