"""Shipped tpulint checkers — importing this package registers them."""

from kubeflow_tpu.analysis.checkers import (  # noqa: F401
    host_call_in_jit,
    mesh_axes,
    raw_clock,
    spec_legality,
    tile_legality,
    unbound_collective,
    unbounded_retry,
    version_gate,
    wiring,
)
