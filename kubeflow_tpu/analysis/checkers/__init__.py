"""Shipped tpulint checkers — importing this package registers them."""

from kubeflow_tpu.analysis.checkers import (  # noqa: F401
    host_call_in_jit,
    lock_blocking,
    lock_reentrant,
    lock_unguarded_state,
    mesh_axes,
    metric_contract,
    raw_clock,
    spec_legality,
    tile_legality,
    unbound_collective,
    unbounded_retry,
    version_gate,
    wiring,
)
