"""Shipped tpulint checkers — importing this package registers them."""

from kubeflow_tpu.analysis.checkers import (  # noqa: F401
    host_call_in_jit,
    host_sync,
    lock_blocking,
    lock_reentrant,
    lock_unguarded_state,
    mesh_axes,
    metric_contract,
    raw_clock,
    recompile_hazard,
    spec_legality,
    tile_legality,
    trace_control_flow,
    unbound_collective,
    unbounded_retry,
    unledgered_compile,
    use_after_donate,
    version_gate,
    wiring,
)
