"""Shipped tpulint checkers — importing this package registers them."""

from kubeflow_tpu.analysis.checkers import (  # noqa: F401
    host_call_in_jit,
    raw_clock,
    tile_legality,
    unbounded_retry,
    wiring,
)
