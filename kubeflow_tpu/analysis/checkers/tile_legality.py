"""TPU001 — Mosaic tile legality for Pallas BlockSpec shapes.

The TPU vector layout tiles the last two axes of every kernel block:
the last (*lane*) axis in units of 128, the second-to-last (*sublane*)
axis in units of 8 for f32 (16 for bf16, 32 for int8/fp8 — 8 is the
weakest legal floor, so that is what a static checker can enforce
without dtype inference). A block whose lane dim is not a multiple of
128 (and not a full/broadcast dim of size 1) compiles in interpret mode
— where CPU tests run — and then fails Mosaic lowering on real
hardware. That is exactly the PR 1 ``ops/bnconv.py`` bug: block sizes
came from ``_pick_block(dim, want)`` whose default ``floor=8`` happily
returns lane tiles of 8.

Two detections:

1. a **literal** lane/sublane dim in a ``BlockSpec((...), ...)`` tuple
   that violates the floor — suppressed when the enclosing function
   guards untileable shapes with an XLA fallback branch (a call to a
   ``*tileable*`` predicate), because then the literal is only reached
   for shapes the guard admitted;
2. a dim that resolves to a ``_pick_block(..., floor=F)`` helper call
   with a lane-position ``F < 128`` — flagged even under a fallback
   guard, because the guard itself is typically computed with the same
   wrong floor (the PR 1 failure mode: ``_tileable`` said yes, Mosaic
   said no).

**Table-resolved tiles** (the autotune plane): the flash/paged kernels'
block dims are now dynamic values resolved from
``kubeflow_tpu/ops/tile_table.json`` — unresolvable at the call site,
so detections 1/2 correctly stay silent there. The legality obligation
moves to the TABLE: when this checker reaches the plane's owner module
(``ops/autotune.py``) it lints every committed entry with the plane's
own ``validate_entry`` (divisibility, analytic VMEM estimate,
dtype-lane/sublane legality) and reports illegal rows against the JSON
file. The autotune module is loaded standalone (stdlib-only top level)
so the lint run never pays the ``kubeflow_tpu.ops`` jax import.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import sys
from typing import Iterable, Optional

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

LANE_MULTIPLE = 128
SUBLANE_MULTIPLE = 8  # f32 floor; bf16/int8 need 16/32 (see docstring)
PICK_BLOCK_DEFAULT_FLOOR = 8

# the autotune plane's owner module (triggers the table lint) and the
# committed table the findings anchor to
TABLE_OWNER = "kubeflow_tpu/ops/autotune.py"
TABLE_REL = "kubeflow_tpu/ops/tile_table.json"


def _ops_dir() -> str:
    return os.path.normpath(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir, os.pardir,
        "ops"))


def _table_path() -> str:
    """Monkeypatch point for tests; the real table sits beside the
    autotune module."""
    return os.path.join(_ops_dir(), "tile_table.json")


def _autotune_module():
    """The validation logic lives in ONE place — the autotune plane.
    Reuse an already-imported module when present; otherwise load it
    standalone from file, skipping ``kubeflow_tpu.ops.__init__`` (whose
    attention import pulls jax — a multi-second tax per lint run the
    +25%-wall budget cannot afford)."""
    mod = sys.modules.get("kubeflow_tpu.ops.autotune")
    if mod is not None:
        return mod
    path = os.path.join(_ops_dir(), "autotune.py")
    spec = importlib.util.spec_from_file_location("_tpulint_autotune", path)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec: the module's dataclasses resolve their
    # (string) annotations through sys.modules[cls.__module__]
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(spec.name, None)
        raise
    return mod


def _pick_block_floor(scope: ast.AST, node: ast.AST) -> Optional[int]:
    """If ``node`` is a Name assigned from a ``*_pick_block(...)`` call
    in ``scope``, return that call's ``floor`` (3rd positional or
    keyword; helper default 8 when the argument is absent). None = not
    a pick-block value, OR a floor expression that is not a literal —
    an unprovable floor stays silent (astutil contract), it does not
    get assumed to be the default."""
    if not isinstance(node, ast.Name) or scope is None:
        return None
    values = list(astutil.assignments_to(scope, node.id))
    if len(values) != 1 or not isinstance(values[0], ast.Call):
        return None
    call = values[0]
    name = astutil.call_name(call) or ""
    if not name.split(".")[-1].endswith("pick_block"):
        return None
    if len(call.args) >= 3:
        return astutil.const_int(call.args[2])
    for kw in call.keywords:
        if kw.arg == "floor":
            return astutil.const_int(kw.value)
    return PICK_BLOCK_DEFAULT_FLOOR


def _has_fallback_guard(fn: Optional[ast.AST]) -> bool:
    """Heuristic: the function consults a ``*tileable*`` predicate
    somewhere (the canonical shape-guard spelling in ops/)."""
    if fn is None:
        return False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node) or ""
            if "tileable" in name.split(".")[-1]:
                return True
    return False


@register_checker
class TileLegalityChecker(Checker):
    rule = "TPU001"
    name = "tile-legality"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node) or ""
            if name.split(".")[-1] != "BlockSpec" or not node.args:
                continue
            shape = node.args[0]
            if not isinstance(shape, (ast.Tuple, ast.List)):
                continue
            dims = shape.elts
            if not dims:
                continue
            fn = module.enclosing_function(node)
            guarded = _has_fallback_guard(fn)
            yield from self._check_dim(
                module, node, fn, dims[-1], guarded, lane=True)
            if len(dims) >= 2:
                yield from self._check_dim(
                    module, node, fn, dims[-2], guarded, lane=False)
        if module.rel.replace("\\", "/") == TABLE_OWNER:
            yield from self._check_table()

    def _check_table(self) -> Iterable[Finding]:
        """Lint the committed tile table with the autotune plane's own
        legality check — the table is where the kernels' now-dynamic
        block values actually come from, so it carries the tile-
        legality obligation the silent call sites shed."""
        path = _table_path()
        if not os.path.exists(path):
            yield Finding(
                rule=self.rule, severity=self.severity, path=TABLE_REL,
                line=1,
                message="committed tile table is missing (every tuned "
                        "kernel silently degrades to the analytic "
                        "fallback)",
                hint="restore kubeflow_tpu/ops/tile_table.json or "
                     "regenerate it with scripts/tile_sweep.py "
                     "--update-table")
            return
        at = _autotune_module()
        table = at.load_table(path, warn=False)
        for entry, errs in table.rejected:
            for err in errs:
                yield Finding(
                    rule=self.rule, severity=self.severity,
                    path=TABLE_REL, line=1,
                    message=f"tile table entry {at.entry_key(entry)}: "
                            f"{err}",
                    hint="fix the entry (or drop it — the analytic "
                         "fallback covers the shape class) and rerun "
                         "scripts/tile_sweep.py --validate")

    def _check_dim(self, module: ModuleInfo, call: ast.Call,
                   fn: Optional[ast.AST], dim: ast.AST, guarded: bool,
                   lane: bool) -> Iterable[Finding]:
        axis = "lane" if lane else "sublane"
        multiple = LANE_MULTIPLE if lane else SUBLANE_MULTIPLE

        floor = _pick_block_floor(fn, dim)
        if floor is not None:
            # detection 2: wrong pick-block floor; fallback guard does
            # not excuse this (the guard shares the floor)
            if floor % multiple != 0:
                src = getattr(dim, "id", "?")
                yield self.finding(
                    module, call,
                    f"{axis} block dim {src!r} comes from a pick-block "
                    f"helper with floor {floor}; Mosaic requires {axis} "
                    f"tiles in multiples of {multiple}",
                    hint=f"pass floor={multiple} (or larger) when picking "
                         f"a {axis}-axis block size, and use the same "
                         f"floor in the tileable-shape guard")
            return

        value = astutil.resolve_int(fn, dim)
        if value is None or value == 1:
            # unresolvable (dynamic) or full/broadcast dim — Mosaic
            # relayouts size-1 trailing dims (see ops/attention.py's
            # (1, block_q, 1) lse blocks)
            return
        if value % multiple != 0 and not guarded:
            yield self.finding(
                module, call,
                f"{axis} block dim {value} is not a multiple of "
                f"{multiple}; Mosaic rejects this tile in compiled mode "
                f"(interpret-mode CPU tests will not catch it)",
                hint=f"use {axis} tiles in multiples of {multiple}, or "
                     "guard the kernel with an XLA fallback for "
                     "untileable shapes")
