"""TPU006 — version-gated jax APIs outside ``compat/``.

The exact bug class the TPU rebuild warns about: code that only fails
on the real runtime. The platform targets the current jax surface, but
the pinned container jax (0.4.37) predates part of it — 4 direct
``jax.shard_map`` call sites sailed through every CPU-side check and
killed 22 tier-1 tests with an AttributeError at run time. The repo
policy (docs/COMPAT.md) is that ``kubeflow_tpu/compat/`` is the single
sanctioned call site for version-sensitive jax APIs; this rule makes
the policy mechanical.

Table-driven: :data:`GATED_APIS` maps a dotted jax name to the version
window where it exists and the compat shim to call instead. Flagged,
anywhere outside ``compat/``:

- attribute chains (``jax.shard_map(...)``, a bare
  ``jax.sharding.get_abstract_mesh`` reference);
- ``from jax import shard_map`` / ``from jax.sharding import use_mesh``
  style imports of a gated name;
- any import touching ``jax.experimental.shard_map`` — present on the
  pinned jax but *removed* on current jax, so it is just as
  version-gated in the other direction.

``hasattr(jax, "shard_map")`` / ``getattr(..., None)`` probes pass the
name as a string and are deliberately not flagged — that is how the
compat shims themselves resolve the surface, and a probe cannot crash.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Tuple

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

SANCTIONED_DIR = "kubeflow_tpu/compat/"

# dotted api -> (availability window, sanctioned replacement)
GATED_APIS: Dict[str, Tuple[str, str]] = {
    "jax.shard_map":
        ("jax>=0.6 (absent from the pinned 0.4.37)",
         "kubeflow_tpu.compat.shard_map"),
    "jax.experimental.shard_map.shard_map":
        ("jax<0.8 only (removed upstream)",
         "kubeflow_tpu.compat.shard_map"),
    "jax.sharding.get_abstract_mesh":
        ("jax>=0.5", "kubeflow_tpu.compat.current_mesh"),
    "jax.sharding.use_mesh":
        ("jax>=0.8 window of the use_mesh/set_mesh rename",
         "kubeflow_tpu.compat.mesh_context"),
    "jax.sharding.set_mesh":
        ("jax>=0.9 side of the use_mesh/set_mesh rename",
         "kubeflow_tpu.compat.mesh_context"),
    "jax.lax.pvary":
        ("jax>=0.6", "kubeflow_tpu.compat.pvary"),
    "jax.lax.pcast":
        ("jax>=0.7", "kubeflow_tpu.compat.pvary"),
    "jax.lax.axis_size":
        ("jax>=0.5", "kubeflow_tpu.compat.axis_size"),
}

# gated import roots: importing the module at all is version-sensitive
GATED_MODULES: Dict[str, Tuple[str, str]] = {
    "jax.experimental.shard_map":
        ("jax<0.8 only (removed upstream)",
         "kubeflow_tpu.compat.shard_map"),
}


@register_checker
class VersionGateChecker(Checker):
    rule = "TPU006"
    name = "version-gated-api"
    severity = "error"

    def _emit(self, module: ModuleInfo, node: ast.AST, api: str,
              window: str, use: str) -> Finding:
        return self.finding(
            module, node,
            f"{api} is version-gated ({window}); only compat/ may "
            "touch version-sensitive jax APIs",
            hint=f"call {use} instead — the shim spans the versions "
                 "this direct use does not")

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        # exact path-component prefix, not a substring: a sibling
        # "netcompat/" or a nested "*/compat/" must NOT be exempt
        if module.rel.startswith(SANCTIONED_DIR):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Attribute):
                name = astutil.dotted_name(node)
                if name in GATED_APIS:
                    window, use = GATED_APIS[name]
                    yield self._emit(module, node, name, window, use)
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod = node.module
                if node.level:  # relative import: not a jax module
                    continue
                if mod in GATED_MODULES:
                    window, use = GATED_MODULES[mod]
                    yield self._emit(module, node, mod, window, use)
                    continue
                for alias in node.names:
                    full = f"{mod}.{alias.name}"
                    if full in GATED_APIS:
                        window, use = GATED_APIS[full]
                        yield self._emit(module, node, full, window, use)
                    elif full in GATED_MODULES:
                        # `from jax.experimental import shard_map`: the
                        # gated module pulled in via its parent package
                        window, use = GATED_MODULES[full]
                        yield self._emit(module, node, full, window, use)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    for root, (window, use) in GATED_MODULES.items():
                        if alias.name == root \
                                or alias.name.startswith(root + "."):
                            yield self._emit(module, node, alias.name,
                                             window, use)
