"""TPU005 — retry/poll loops with no max-attempts or deadline.

A ``while True:`` loop that sleeps and can never ``break``, ``return``,
or ``raise`` retries forever: a wedged dependency turns into a silently
hung controller instead of a failed, restartable one. Bounded shapes —
``for attempt in range(n)``, ``while clock() - t0 < timeout`` — are the
platform convention (see ``k8s/apply.py``, ``platform/gcp.py``).

Flagged: a constant-truthy ``while`` whose body contains a sleep-like
call and no loop exit (``break`` in this loop, or ``return``/``raise``
anywhere in the body outside nested defs). Intentional serve-forever
loops (container entrypoints parked on ``time.sleep(3600)``) carry a
line pragma — the pragma is the documentation that forever is a
decision, not an accident.
"""

from __future__ import annotations

import ast
from typing import Iterable

from kubeflow_tpu.analysis import astutil
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo


def _body_nodes(loop: ast.While):
    """Walk the loop body, not descending into nested function defs
    (their control flow does not exit this loop)."""
    stack = list(loop.body) + list(loop.orelse)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _breaks_this_loop(nodes) -> bool:
    # a break only exits THIS loop when not inside a nested loop or def
    for n in nodes:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda, ast.While, ast.For)):
            continue  # nested defs/loops own their own breaks
        if isinstance(n, ast.Break):
            return True
        if _breaks_this_loop(ast.iter_child_nodes(n)):
            return True
    return False


def _has_exit(loop: ast.While) -> bool:
    for node in _body_nodes(loop):
        if isinstance(node, (ast.Return, ast.Raise)):
            return True
    return _breaks_this_loop(loop.body + loop.orelse)


def _sleeps(loop: ast.While) -> bool:
    for node in _body_nodes(loop):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node) or ""
            if name.split(".")[-1] == "sleep":
                return True
    return False


@register_checker
class UnboundedRetryChecker(Checker):
    rule = "TPU005"
    name = "unbounded-retry"
    severity = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.While):
                continue
            if not astutil.is_const_true(node.test):
                continue
            if not _sleeps(node) or _has_exit(node):
                continue
            yield self.finding(
                module, node,
                "unbounded retry/poll loop: `while True` sleeps with no "
                "break/return/raise — a wedged dependency hangs here "
                "forever instead of failing",
                hint="bound it with max-attempts or a deadline (see "
                     "k8s/apply.py backoff), or add "
                     "`# tpulint: disable=TPU005` if serving forever is "
                     "the point")
