"""TPU018 — compile entry points invisible to the CompileLedger.

PR 18 made ``CompileLedger.timed_compile`` the instrumented AOT entry
point: it fingerprints the HLO, records the ``memory_analysis``
budget, and lands the compile on the ``kftpu_compile_seconds`` series
the goodput ledger and the planned fleet compile cache key on. A bare
``jax.jit``/``pjit`` site in the serving/train/elastic planes is a
compile those consumers can never attribute or warm — the startup
badput the ROADMAP item exists to kill.

A site is **sanctioned** when a name it is bound to (``step``,
``self._step``, aliases through plain assignment, or the decorated
function's own name) appears as the first argument of a
``*.timed_compile(...)`` call anywhere in the same module — i.e. the
module offers a ledger-routed path to that executable. Everything
else needs either that wiring or an inline pragma explaining why the
compile is deliberately listener-only (the process-wide
``CompileLedger.install`` subscription still bills it, but without
an AOT fingerprint or memory budget).

Scope is deliberately the hot planes only — ``serving/``, ``train/``,
``elastic/``. Kernels, benches, and examples jit freely.
"""

from __future__ import annotations

from typing import Iterable

from kubeflow_tpu.analysis import tracetaint
from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.registry import Checker, register_checker
from kubeflow_tpu.analysis.walker import ModuleInfo

SCOPES = ("kubeflow_tpu/serving/", "kubeflow_tpu/train/",
          "kubeflow_tpu/elastic/")


@register_checker
class UnledgeredCompileChecker(Checker):
    rule = "TPU018"
    name = "unledgered-compile"
    severity = "warning"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        if not module.rel.startswith(SCOPES):
            return
        mt = tracetaint.taint_analysis(module)
        for site in mt.sites:
            names = set(site.bound) | ({site.wrapped} if site.wrapped
                                       else set())
            if names & mt.sanctioned:
                continue
            label = site.wrapped or "/".join(sorted(site.bound)) \
                or "<anonymous>"
            yield self.finding(
                module, site.node,
                f"jit site {label!r} bypasses "
                "CompileLedger.timed_compile: the compile has no HLO "
                "fingerprint or memory budget on the ledger, so the "
                "fleet compile cache and AOT warm pools cannot key it",
                hint="expose a ledger-routed path (pass the jitted "
                     "callable to CompileLedger.timed_compile with "
                     "example args/ShapeDtypeStructs), or pragma the "
                     "site with the reason it stays listener-only")
