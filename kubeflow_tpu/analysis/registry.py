"""Checker registry — the pluggable part of tpulint.

A checker is a class with a ``rule`` id, a ``severity``, and a
``check(module)`` generator; registering it is one decorator. Cross-file
rules (TPU004) additionally implement ``finalize()``, called once after
every module has been seen, so they can collect facts per file and
cross-reference at the end.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Type

from kubeflow_tpu.analysis.findings import Finding
from kubeflow_tpu.analysis.walker import ModuleInfo


class Checker:
    """Base class: subclass, set ``rule``/``name``/``severity``,
    implement :meth:`check`. One instance lives for one lint run, so
    per-run state (for :meth:`finalize`) goes on ``self``."""

    rule: str = "TPU000"
    name: str = "base"
    severity: str = "error"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        raise NotImplementedError

    def finalize(self) -> Iterable[Finding]:
        """Cross-file findings, after all modules were checked."""
        return ()

    def finding(self, module: ModuleInfo, node, message: str,
                hint: str = "", severity: Optional[str] = None) -> Finding:
        return Finding(
            rule=self.rule, severity=severity or self.severity,
            path=module.rel, line=node.lineno, message=message, hint=hint,
            span=module.node_span(node))


_REGISTRY: Dict[str, Type[Checker]] = {}


def register_checker(cls: Type[Checker]) -> Type[Checker]:
    if cls.rule in _REGISTRY:
        raise ValueError(f"duplicate checker rule {cls.rule}")
    _REGISTRY[cls.rule] = cls
    return cls


def all_checkers() -> Dict[str, Type[Checker]]:
    # import for side effect: shipped checkers self-register on import
    import kubeflow_tpu.analysis.checkers  # noqa: F401
    return dict(_REGISTRY)


def create_checkers(rules: Optional[Sequence[str]] = None) -> List[Checker]:
    known = all_checkers()
    if rules is None:
        return [cls() for _, cls in sorted(known.items())]
    bad = [r for r in rules if r not in known]
    if bad:
        raise KeyError(f"unknown rules {bad}; known: {sorted(known)}")
    return [known[r]() for r in sorted(rules)]
